"""Shared-memory model plane: one model per node, N prefork workers map it.

Without the plane, every ``pio deploy --workers N`` worker owns a private
copy of everything: with ``--follow`` each worker runs its OWN embedded
follower (the same delta folded N times, the same host_inverted CSR built
N times) and resident model memory is N× the model size.  The plane
inverts the topology to match the reference's deployment model (many
stateless serving processes reading ONE trained model from a shared
store):

- each model generation is emitted exactly ONCE — by the single
  plane-publisher process (``pio deploy --plane-publisher``, spawned next
  to the prefork group when ``--follow`` is on) or by whichever worker
  handles a ``/reload`` — into the plane's **blob store** under the
  storage dir (:func:`store.columnar.write_arrays` containers: magic +
  JSON manifest + 64-aligned blobs; two-phase tmp+fsync+rename under a
  flock'd generation ticket, the same crash-safety discipline as
  snapshots).  The arena includes the *derived* serving state
  (host_inverted CSR, host_pop_order, user_seen CSRs) so workers never
  rebuild it;
- prefork workers watch the plane's ``CURRENT.json`` manifest
  (:class:`PlaneWatcher` — an inotify wake on Linux, a cheap stat poll
  elsewhere), map the new generation's arrays READ-ONLY (``mmap`` +
  ``np.frombuffer`` — all workers share page cache, so resident model
  bytes go N× → ~1×), reconstruct thin :class:`URModel` wrappers around
  the views, and install through the query server's build-ticket
  ``_install`` path.  The old generation unmaps once in-flight queries
  drain (the arrays' refcounts ARE the drain barrier);
- stale blob files are GC'd by the publisher with **chain refcounting**:
  the newest ``PIO_MODEL_PLANE_KEEP`` generations are retained together
  with every older generation file their delta chains still reference
  (back to each kept generation's keyframe) — GC can never unlink a blob
  a kept manifest needs, and a mapped-but-unlinked blob stays valid
  (POSIX keeps the pages) so GC can never corrupt a serving worker;
- a torn blob (publisher SIGKILL'd mid-emit, disk corruption) fails
  validation on map, the FAILING file is quarantined
  (``*.quarantine``), and workers keep serving the old generation; the
  publisher notices the broken chain at its next publish and heals it
  with a full keyframe.

**Delta arenas** (``PIO_MODEL_PLANE_DELTA``, default on): instead of
rewriting the whole arena every generation — O(model) write I/O at
fold-tick rates — ``publish`` emits a small ``gen-N.delta`` container
holding ONLY the bytes that changed, plus a per-array manifest in its
header.  Per array the publisher picks the cheapest faithful encoding:

- ``ref`` — unchanged (same object, the fold engine's carried
  components; or bytes-equal): no bytes written, the worker carries its
  previous generation's array (which is, inductively, the original
  mmap view — page sharing survives refs);
- ``ext`` — pure END growth (the new array is byte-prefix-proven
  against the previous): only the suffix is written.  Dictionary
  blob/offs pairs ride this together with the existing
  ``prevCrc``/``prevN`` machinery, so workers extend their cached
  ``IdDict`` in O(new strings) without touching the covered prefix;
- ``patch`` — sparsely changed (a few elements moved, e.g. popularity
  counts, indicator idx rows): changed flat positions + values;
- ``nz`` — the indicator LLR case: every *finite* cell's score moves
  each fold (Dunning G² couples all cells through N) while the -inf
  padding never does, so the blob is just the values at cells where the
  (already-composed) idx table is valid — the true changed-bytes floor,
  ≈ nnz·4 bytes instead of I_p·K·4;
- ``inv`` / ``pop_order`` — replay instructions: the fold engine
  PATCHED these (``_patch_inverted_csr`` splice / ``_merge_pop_order``)
  and a byte-diff would see ~100% change because positions shift, but
  the patch ARGUMENTS (changed row/id sets — the emit-snapshot
  provenance ``fold._carry_serving_state`` records on the model) are
  O(delta).  The worker replays the SAME functions against its previous
  composed generation, which is bit-exact by induction;
- ``full`` — genuinely rebuilt arrays, written whole.

A worker composes a delta generation against the one it already serves
(or, cold, walks the chain back to the last keyframe — bounded by
``PIO_MODEL_PLANE_FULL_EVERY``, which forces a periodic full-arena
keyframe).  Composed (non-ref) arrays are worker-private copies until
the next keyframe re-shares everything via the page cache; refs stay
mapped views throughout.  ``PIO_MODEL_PLANE_DELTA=off`` keeps the
full-arena-per-generation writer as the bit-exact parity oracle.

``PIO_MODEL_PLANE=off`` keeps the per-worker in-process path as the
parity oracle; ``on`` forces the plane even at ``--workers 1`` (the
in-process test topology); the default (auto) enables it for prefork
groups (``--workers > 1``).  Only single-:class:`URModel` bundles ride
the plane — anything else raises :class:`PlaneUnsupported` and the
caller degrades to the private-model path.
"""

from __future__ import annotations

import json
import logging
import os
import select
import threading
import time
import zlib
from collections.abc import Mapping
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.obs import lineage as _lineage
from predictionio_tpu.obs import metrics as _obs_metrics
from predictionio_tpu.store.columnar import (
    CSRLookup,
    IdDict,
    read_arrays,
    write_arrays,
)

log = logging.getLogger("pio.modelplane")

_REG = _obs_metrics.get_registry()
_M_GEN = _REG.gauge(
    "pio_model_plane_generation",
    "Model-plane generation this worker serves, one {worker} series per "
    "process (the publisher's series is the generation it last emitted) "
    "— all series equal means the prefork group has converged")
_M_BYTES = _REG.gauge(
    "pio_model_plane_bytes",
    "On-disk bytes of the model-plane generation file this worker last "
    "mapped (or, for the publisher, last emitted), one {worker} series "
    "per process — a full keyframe arena ≈ the per-node resident model "
    "cost; a delta generation is just that generation's changed bytes")
_M_MAP_S = _REG.gauge(
    "pio_model_plane_map_seconds",
    "Wall seconds this worker spent mapping/composing + installing its "
    "last plane generation (mmap + delta compose + wrapper "
    "reconstruction + serving-bundle warm), one {worker} series — the "
    "per-worker cost that replaced a full fold + derived-state rebuild")
_M_GC = _REG.counter(
    "pio_model_plane_gc_total",
    "Stale model-plane blob files unlinked by the publisher's GC "
    "(generations older than every kept generation's delta chain, "
    "quarantined torn blobs past the keep window, and abandoned tmp "
    "files)")
_M_PUB_BYTES = _REG.counter(
    "pio_model_plane_publish_bytes_total",
    "Logical model bytes per publish by path: full (written as whole "
    "blobs — keyframes and rebuilt arrays), delta (bytes actually "
    "written by delta encodings: ext suffixes, patch/nz values, replay "
    "instructions), ref (bytes NOT written — referenced, extended-over, "
    "patched-over or replay-derived).  (full+delta)/(full+delta+ref) is "
    "the publish write amplification; delta-scaled folds should keep it "
    "near the changed-bytes fraction, not 1.0")
_M_BLOBS = _REG.gauge(
    "pio_model_plane_blob_count",
    "Generation blob files currently retained in the plane directory "
    "(kept window + the delta-chain files it still references), one "
    "{worker} series set by the publisher after each publish+GC")
_M_CHAIN = _REG.gauge(
    "pio_model_plane_chain_len",
    "Delta generations between the newest published generation and its "
    "keyframe (0 = the newest generation IS a full keyframe arena) — "
    "the compose depth a cold worker pays, bounded by "
    "PIO_MODEL_PLANE_FULL_EVERY, one {worker} series")

_CURRENT = "CURRENT.json"
_LOCK = "plane.lock"
# manifest key stamped by a replication subscriber on every manifest it
# lands (value: the publisher endpoint it replicates from).  Its absence
# from a manifest in a subscriber-fed directory means a LOCAL publisher
# wrote it — the split-brain the subscriber must refuse to fight; its
# presence tells a local publisher the directory is replica-fed (publish
# degrades to keyframes, never deltas against a chain it didn't write).
REPLICA_KEY = "replicatedFrom"


class PlaneUnsupported(RuntimeError):
    """The model bundle cannot ride the plane (not exactly one URModel);
    callers degrade to the private in-process path."""


class _PlaneCorrupt(ValueError):
    """Deterministic content corruption in one plane file; ``fname`` is
    the file that failed (quarantine THAT one — a delta generation can
    fail because a file earlier in its chain is torn)."""

    def __init__(self, fname: str, msg: str):
        super().__init__(msg)
        self.fname = fname


def plane_mode() -> str:
    """'on' | 'off' | 'auto' from PIO_MODEL_PLANE (default auto)."""
    conf = os.environ.get("PIO_MODEL_PLANE", "").lower()
    if conf in ("off", "0", "false"):
        return "off"
    if conf in ("on", "1", "true"):
        return "on"
    return "auto"


def plane_wanted(workers: int) -> bool:
    """auto enables the plane exactly where private copies multiply:
    prefork groups.  'on' forces it for a single worker too (tests, and
    the child workers the parent spawns with the dir pre-resolved)."""
    mode = plane_mode()
    return mode == "on" or (mode == "auto" and workers > 1)


def plane_poll_s() -> float:
    """PIO_MODEL_PLANE_POLL_S: seconds between a worker's manifest polls
    (default 0.2).  With the inotify fast path this is only the fallback
    heartbeat — swap propagation wakes on the manifest rename itself;
    without inotify the watcher stat-polls the manifest at this cadence
    (one cheap os.stat; the manifest is opened/parsed only on change)."""
    try:
        return max(
            float(os.environ.get("PIO_MODEL_PLANE_POLL_S", "0.2")), 0.02)
    except ValueError:
        return 0.2


def plane_keep() -> int:
    """PIO_MODEL_PLANE_KEEP: newest generations the publisher's GC
    retains on disk (default 3 — current + drain margin; each kept
    delta generation also pins its chain back to its keyframe; a worker
    still mapping an unlinked blob keeps serving it, POSIX keeps the
    pages)."""
    try:
        return max(int(os.environ.get("PIO_MODEL_PLANE_KEEP", "3")), 1)
    except ValueError:
        return 3


def plane_delta_enabled() -> bool:
    """``PIO_MODEL_PLANE_DELTA=off`` restores the full-arena-per-
    generation writer (the bit-exact parity oracle; also the most
    page-cache-shared steady state).  Default on: publish O(changed
    bytes) per generation, keyframe every PIO_MODEL_PLANE_FULL_EVERY."""
    return os.environ.get("PIO_MODEL_PLANE_DELTA", "").lower() not in (
        "off", "0", "false")


def plane_full_every() -> int:
    """PIO_MODEL_PLANE_FULL_EVERY: force a full keyframe arena every N
    generations (default 16).  Bounds the delta chain a cold/restarted
    worker composes AND the interval over which composed (non-ref)
    arrays live as worker-private copies before the keyframe re-shares
    them via the page cache.  1 = every generation is a keyframe
    (equivalent to PIO_MODEL_PLANE_DELTA=off)."""
    try:
        return max(int(os.environ.get("PIO_MODEL_PLANE_FULL_EVERY",
                                      "16")), 1)
    except ValueError:
        return 16


def resolve_plane_dir(storage, engine_id: str,
                      variant: str) -> Optional[str]:
    """Where the plane lives: PIO_MODEL_PLANE_DIR wins (the prefork
    parent pins children and the publisher to its own resolution), else
    next to the engine metadata under the METADATA **localfs** path;
    None (plane unavailable) for other backends.  A sharedfs METADATA
    store does NOT auto-resolve: the plane's mmap/GC/flock invariants
    assume one node's kernel (an unlinked-but-mapped arena stays valid;
    flock is advisory-reliable), neither of which holds across NFS-style
    mounts — multi-node sharedfs operators must point
    PIO_MODEL_PLANE_DIR at a node-LOCAL directory explicitly."""
    env = os.environ.get("PIO_MODEL_PLANE_DIR")
    if env:
        return env
    try:
        src = storage.config.sources[storage.config.repositories["METADATA"]]
    except (KeyError, AttributeError):
        return None
    if src.get("type") == "sharedfs":
        log.warning(
            "model plane: METADATA store is sharedfs — the plane's "
            "mmap/flock/GC invariants hold on one node's kernel only, "
            "so a shared mount cannot host it.  For multi-node serving "
            "use plane REPLICATION instead: publish with `pio deploy "
            "--plane-publish PORT` and point every other node at it "
            "with `pio deploy --plane-from HOST:PORT` (or the "
            "standalone `pio plane-subscribe`), each against a "
            "node-LOCAL PIO_MODEL_PLANE_DIR.  See docs/operations.md "
            "\"Multi-node plane replication\".")
        return None
    if src.get("type") != "localfs" or not src.get("path"):
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in f"{engine_id}-{variant}")
    return str(Path(src["path"]) / "model_plane" / safe)


class _LazyProps(Mapping):
    """``item_properties`` view over the arena's JSON blob, parsed ONCE
    on first real access — steady-state workers serve business rules
    from carried derived indexes and never pay the parse."""

    __slots__ = ("_raw", "_doc")

    def __init__(self, raw):
        # raw: an ndarray, or a zero-arg thunk returning one (delta
        # compose is lazy for the props blob — an unparsed carried blob
        # never materializes)
        self._raw = raw
        self._doc: Optional[dict] = None

    def _load(self) -> dict:
        if self._doc is None:
            raw = self._raw() if callable(self._raw) else self._raw
            if raw is None or len(raw) == 0:
                self._doc = {}
            else:
                self._doc = json.loads(bytes(raw))
            self._raw = None   # the parsed dict owns the data now
        return self._doc

    def __getitem__(self, key):
        return self._load()[key]

    def __iter__(self):
        return iter(self._load())

    def __len__(self):
        return len(self._load())


def _json_info(info: Optional[Dict]) -> Dict:
    """JSON-safe subset of a publish info dict (it may carry follower
    internals)."""
    return {k: v for k, v in (info or {}).items()
            if isinstance(v, (str, int, float, bool, type(None)))}


def _flat_u8(arr: np.ndarray) -> np.ndarray:
    """The array's bytes as a flat uint8 view (C-contiguous input)."""
    return arr.reshape(-1).view(np.uint8)


def _freeze(arr: np.ndarray) -> np.ndarray:
    """Mark a composed array read-only — the same contract as the mmap
    views: no worker can mutate model state another query is reading."""
    if arr.flags.writeable:
        arr.flags.writeable = False
    return arr


class _ComposedGen(Mapping):
    """One composed generation: array name → ndarray, some entries lazy
    (dictionary blobs/offsets and the props JSON are only touched when
    the worker's caches miss).  Lazy entries are self-contained
    ``(dtype, shape, [byte parts])`` descriptors — raw mmap views, never
    references to previous :class:`_ComposedGen` objects, so a delta
    chain does NOT retain every intermediate composed generation in
    memory.  ``suffix_of`` exposes this generation's ``ext`` suffix so
    the dictionary extension path can decode only the tail without ever
    composing (or touching) the covered prefix."""

    __slots__ = ("_arrays", "_parts", "_suffixes")

    def __init__(self):
        self._arrays: Dict[str, np.ndarray] = {}
        # name -> (dtype str, shape tuple, [flat uint8 parts])
        self._parts: Dict[str, Tuple[str, Tuple[int, ...],
                                     List[np.ndarray]]] = {}
        # name -> (this generation's suffix bytes, prefix nbytes)
        self._suffixes: Dict[str, Tuple[np.ndarray, int]] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        arr = self._arrays.get(name)
        if arr is None:
            dt, shape, parts = self._parts.pop(name)
            flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
            arr = _freeze(flat.view(np.dtype(dt)).reshape(shape))
            self._arrays[name] = arr
        return arr

    def parts_of(self, name: str):
        """The byte-parts descriptor (materialized arrays count as one
        part) — how the next generation chains onto this one without
        forcing a concat."""
        got = self._parts.get(name)
        if got is not None:
            return got
        arr = self._arrays[name]
        return (arr.dtype.str, tuple(arr.shape), [_flat_u8(
            np.ascontiguousarray(arr))])

    def get(self, name: str, default=None):
        if name in self._arrays or name in self._parts:
            return self[name]
        return default

    def __contains__(self, name: str) -> bool:
        return name in self._arrays or name in self._parts

    def __iter__(self):
        yield from self._arrays
        for n in self._parts:
            if n not in self._arrays:
                yield n

    def __len__(self):
        return len(set(self._arrays) | set(self._parts))

    def suffix_of(self, name: str) -> Optional[Tuple[np.ndarray, int]]:
        return self._suffixes.get(name)


# names whose compose stays lazy (worker caches usually skip them)
def _lazy_name(name: str) -> bool:
    return name.startswith("dict_") or name == "props_json"


class ModelPlane:
    """One plane directory: generation emit (publisher side) + map/
    compose (worker side).  Both sides are safe to host in one process
    (the ``--workers 1`` / in-process-test topology): the caches are
    per-instance and the publish ticket is a cross-process flock."""

    def __init__(self, directory: str):
        self.dir = str(directory)
        # publisher-side caches: dict blobs / props blobs keyed by OBJECT
        # identity — the fold engine carries unchanged dictionaries and
        # property maps by object across generations, so steady-state
        # publishes re-encode nothing
        self._pub_dicts: Dict[str, Dict[str, Any]] = {}
        self._pub_props: Optional[Tuple[Any, np.ndarray, int]] = None
        # publisher-side delta state: the last generation THIS instance
        # published — payload arrays (for identity/bytes diffing), the
        # model object (provenance validity), and the chain files from
        # its keyframe (existence-checked before every delta publish so
        # a quarantined/missing chain heals with a keyframe)
        self._pub_prev: Optional[Dict[str, Any]] = None
        self._gc_keyframes: Dict[int, int] = {}   # gen -> its keyframe
        # worker-side caches: reconstructed IdDicts keyed by content crc
        # (carried when unchanged, extended when the publisher proves the
        # previous blob is a byte-prefix), the previous generation's
        # model for derived-prop-index carry, and the composed-array
        # state the delta chain patches forward
        self._dict_cache: Dict[str, Tuple[int, IdDict]] = {}
        self._prev_model = None
        self._prev_meta: Optional[Dict] = None
        self._composed: Optional[_ComposedGen] = None
        self._composed_gen = 0
        # per event type: {"for_idx": <idx the perm matches>, "perm": …}
        self._inv_perms: Dict[int, Dict[str, Any]] = {}
        self._mapped: Dict[str, Tuple[Dict[str, np.ndarray], Dict]] = {}
        self.dicts_extended = 0   # test observability
        self.dicts_rebuilt = 0
        self.last_publish_stats: Dict[str, int] = {}

    # -- manifest ------------------------------------------------------------

    @property
    def current_path(self) -> str:
        return os.path.join(self.dir, _CURRENT)

    def current(self) -> Optional[Dict]:
        """The live manifest, or None (no generation published yet /
        torn manifest — the write is atomic, so torn means absent)."""
        try:
            with open(self.current_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if not isinstance(doc, dict) or "generation" not in doc \
                or "file" not in doc:
            return None
        return doc

    @contextmanager
    def _publish_lock(self):
        import fcntl

        os.makedirs(self.dir, exist_ok=True)
        with open(os.path.join(self.dir, _LOCK), "a+") as f:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    # -- publisher side ------------------------------------------------------

    def publish(self, models, info: Optional[Dict] = None) -> int:
        """Emit one model generation into the blob store; returns the
        plane generation.  Exactly the ``FollowTrainer.on_publish``
        signature, so the plane publisher wires in as the follower's
        publish hook.

        With delta arenas on, a generation whose predecessor THIS
        instance published (and whose chain files are intact, and whose
        keyframe interval hasn't lapsed) writes only its changed bytes;
        everything else — first publish, another process published in
        between, broken chain, keyframe due — writes a full arena.

        Raises :class:`PlaneUnsupported` for non-UR bundles and lets
        OSError/ValueError propagate — the follower's publish-retry
        machinery owns transient failures."""
        from predictionio_tpu.models.universal_recommender.engine import (
            URModel,
        )

        if not (isinstance(models, (list, tuple)) and len(models) == 1
                and type(models[0]) is URModel):
            raise PlaneUnsupported(
                "the model plane serializes exactly one URModel; got "
                f"{[type(m).__name__ for m in (models or [])]}")
        model = models[0]
        w0, t0 = time.time(), time.perf_counter()
        # the publisher pays the ONE derived-state build (or the fold
        # engine's incremental patch) per node; workers only map
        model.ensure_host_serving_state()
        arrays, meta = self._model_payload(model)
        meta["info"] = _json_info(info)
        logical = sum(int(np.asarray(a).nbytes) for a in arrays.values())
        # a restage/retrain publish rebuilt the whole model: the diff
        # would write a mostly-full delta AND lengthen the chain —
        # publish it as a keyframe instead (resets the chain for free)
        rebuilt = (info or {}).get("mode") in ("restage", "retrain")
        with self._publish_lock():
            cur = self.current()
            gen = int(cur["generation"]) + 1 if cur else 1
            prev = self._pub_prev
            if cur is not None and REPLICA_KEY in cur \
                    and not getattr(self, "_warned_replica", False):
                # foreign-publisher detection: this directory is fed by
                # plane replication — a local publisher racing the
                # subscriber is split-brain.  Publish keyframes only
                # (never a delta against a chain another node wrote) and
                # say so loudly.
                self._warned_replica = True
                log.warning(
                    "model plane: publishing into a directory fed by "
                    "plane replication (replicatedFrom=%s) — this is "
                    "split-brain; run either a local publisher OR "
                    "plane-subscribe against %s, not both.  Forcing "
                    "keyframe publishes.", cur.get(REPLICA_KEY), self.dir)
            delta = None
            if (plane_delta_enabled() and not rebuilt
                    and prev is not None and cur is not None
                    and REPLICA_KEY not in cur
                    and int(cur["generation"]) == prev["gen"]
                    and gen - prev["keyframe_gen"] < plane_full_every()
                    and self._chain_intact(prev)):
                delta = self._encode_delta(arrays, model, prev)
            meta["generation"] = gen
            # serve-level provenance for the WORKERS' response caches:
            # the fold's changed sets, serialized alongside the arena so
            # a subscriber's generation swap can invalidate selectively
            # (serve.response_cache).  Rides deltas AND periodic
            # keyframes — only a rebuild (restage/retrain) or a broken
            # prev-generation link publishes provenance-free, which
            # workers answer with a full flush.
            sprov_blobs = self._serve_prov_payload(model, meta, cur, prev,
                                                   rebuilt)
            if delta is not None:
                entries, blobs, stats = delta
                meta["planeKind"] = "delta"
                meta["prevGeneration"] = prev["gen"]
                meta["prevFile"] = prev["file"]
                meta["manifest"] = entries
                keyframe_gen = prev["keyframe_gen"]
                meta["keyframeGeneration"] = keyframe_gen
                fname = f"gen-{gen:010d}.delta"
                payload = blobs
                chain = prev["chain"] + [fname]
            else:
                meta["planeKind"] = "full"
                meta["keyframeGeneration"] = keyframe_gen = gen
                stats = {"full": logical, "delta": 0, "ref": 0}
                fname = f"gen-{gen:010d}.arena"
                payload = arrays
                chain = [fname]
            if sprov_blobs:
                # blobs ride the WRITTEN payload only — never
                # self._pub_prev["arrays"], whose key set must keep
                # matching the model payload for delta encoding
                payload = dict(payload)
                payload.update(sprov_blobs)
            path = os.path.join(self.dir, fname)
            tmp = os.path.join(self.dir, f".{fname}.tmp-{os.getpid()}")
            write_arrays(tmp, payload, meta)         # flush+fsync inside
            os.replace(tmp, path)
            size = os.path.getsize(path)
            # the lineage id rides the manifest too (not just the
            # container header): replication forwards it in flip/file
            # frames so subscriber-side repl.* stages stitch under the
            # publisher's record without composing the container first
            lin_id = (info or {}).get("lineageId")
            self._write_manifest({
                "version": 1, "generation": gen, "file": fname,
                "kind": meta["planeKind"], "bytes": size,
                "logicalBytes": logical,
                "keyframeGeneration": keyframe_gen,
                "publisherPid": os.getpid(),
                "publishedAt": time.time(),
                **({"lineageId": str(lin_id)} if lin_id else {}),
            })
            self._gc_keyframes[gen] = keyframe_gen
            kept = self._gc(gen)
        self._pub_prev = {
            "gen": gen, "file": fname, "keyframe_gen": keyframe_gen,
            "chain": chain, "arrays": dict(arrays), "model": model,
        }
        self.last_publish_stats = dict(
            stats, written=stats["full"] + stats["delta"], file=size,
            logical=logical)
        tag = _obs_metrics.worker_tag()
        for p in ("full", "delta", "ref"):
            if stats.get(p):
                _M_PUB_BYTES.inc(int(stats[p]), path=p)
        _M_GEN.set(gen, worker=tag)
        _M_BYTES.set(size, worker=tag)
        _M_CHAIN.set(gen - keyframe_gen, worker=tag)
        if kept is not None:
            _M_BLOBS.set(kept, worker=tag)
        lid = (info or {}).get("lineageId")
        if lid:
            lin = _lineage.get_lineage()
            if lin.enabled:
                # the PLANE generation is the id workers install under —
                # note it here so /lineage/<gen>.json resolves from the
                # number any consumer actually sees
                lin.stage(lid, "plane.write", start=w0,
                          duration_s=time.perf_counter() - t0,
                          generation=gen, kind=meta["planeKind"],
                          bytes=int(size), full=int(stats["full"]),
                          delta=int(stats["delta"]),
                          ref=int(stats["ref"]))
                lin.note_generation(lid, gen)
        log.info(
            "model plane: published generation %d (%s, %.1f MB on disk, "
            "%.1f MB logical; full/delta/ref %.1f/%.2f/%.1f MB)",
            gen, fname, size / 1e6, logical / 1e6,
            stats["full"] / 1e6, stats["delta"] / 1e6, stats["ref"] / 1e6)
        return gen

    def _chain_intact(self, prev: Dict[str, Any]) -> bool:
        """Every file of the previous generation's delta chain still
        present?  A worker may have quarantined a torn file (or an
        operator removed one): delta-publishing on top would strand the
        whole group on the old generation forever — heal with a
        keyframe instead."""
        for fname in prev["chain"]:
            if not os.path.exists(os.path.join(self.dir, fname)):
                log.warning("model plane: chain file %s missing — "
                            "publishing a full keyframe to heal", fname)
                return False
        return True

    def _serve_prov_payload(self, model, meta: Dict, cur, prev,
                            rebuilt: bool) -> Dict[str, np.ndarray]:
        """``meta["serveProv"]`` + its int64 blobs when the fold's
        serve-level provenance is valid against the generation THIS
        instance published last (and the plane hasn't moved underneath
        us); {} otherwise — absent provenance makes workers full-flush,
        never serve stale."""
        from predictionio_tpu.serve.response_cache import _swap_provenance

        if (rebuilt or prev is None or cur is None
                or int(cur["generation"]) != prev["gen"]):
            return {}
        sp = _swap_provenance(model, prev["model"])
        if sp is None:
            return {}
        blobs: Dict[str, np.ndarray] = {}
        inv_keys: Dict[str, str] = {}
        for i, name in enumerate(model.indicator_idx):
            key = f"sprov_inv_{i}"
            blobs[key] = np.ascontiguousarray(sp["inv"][name], np.int64)
            inv_keys[name] = key
        blobs["sprov_pop"] = np.ascontiguousarray(sp["pop"], np.int64)
        meta["serveProv"] = {
            "prev": int(prev["gen"]),
            "props": int(bool(sp["props_changed"])),
            "inv": inv_keys, "pop": "sprov_pop"}
        return blobs

    def _encode_delta(self, arrays: Dict[str, np.ndarray], model,
                      prev: Dict[str, Any]):
        """(manifest entries, blob dict, byte stats) for one delta
        generation, or None when nothing encodes smaller than a
        keyframe (shape regressions etc. — callers fall back)."""
        prev_arrays: Dict[str, np.ndarray] = prev["arrays"]
        if set(arrays) != set(prev_arrays):
            return None     # schema changed (event types appeared/went)
        prov = model.__dict__.get("_plane_prov")
        prov_ok = bool(prov) and prov["prev"]() is prev["model"]
        names = list(model.indicator_idx)
        entries: Dict[str, Dict] = {}
        blobs: Dict[str, np.ndarray] = {}
        stats = {"full": 0, "delta": 0, "ref": 0}

        def put_blob(key: str, arr: np.ndarray) -> None:
            blobs[key] = arr
            stats["delta"] += int(arr.nbytes)

        # 1) replay instructions from the fold's emit provenance: the
        #    inverted CSR trios and pop_order byte-shift wholesale under
        #    a patch (positions move), but the patch ARGUMENTS are tiny
        if prov_ok:
            for i, name in enumerate(names):
                trio = [f"inv_{i}_indptr", f"inv_{i}_rows", f"inv_{i}_w"]
                changed = prov["inv"].get(name)
                if changed is None or any(t not in arrays for t in trio):
                    continue
                if all(arrays[t] is prev_arrays[t] for t in trio):
                    continue        # carried by object: plain refs below
                key = f"instr_inv_{i}"
                put_blob(key, np.asarray(changed, np.int64))
                for t in trio:
                    entries[t] = {"k": "inv", "type": i, "changed": key}
                    stats["ref"] += int(arrays[t].nbytes)
            po = prov.get("pop_order")
            if po is not None and "pop_order" in arrays \
                    and arrays["pop_order"] is not prev_arrays["pop_order"]:
                put_blob("instr_pop_order", np.asarray(po, np.int64))
                entries["pop_order"] = {"k": "pop_order",
                                        "changed": "instr_pop_order"}
                stats["ref"] += int(arrays["pop_order"].nbytes)
        # 2) everything else: generic byte-level delta detection
        for name, arr in arrays.items():
            if name in entries:
                continue
            arr = np.ascontiguousarray(arr)
            old = prev_arrays.get(name)
            entries[name] = self._encode_array(
                name, arr, None if old is None
                else np.ascontiguousarray(old),
                arrays.get(name.replace("_llr", "_idx"))
                if name.endswith("_llr") else None,
                put_blob, stats,
                identical=arrays[name] is prev_arrays.get(name))
        return entries, blobs, stats

    def _encode_array(self, name: str, arr: np.ndarray,
                      old: Optional[np.ndarray], mask: Optional[np.ndarray],
                      put_blob, stats, identical: bool) -> Dict:
        nb = int(arr.nbytes)
        if old is not None and old.dtype == arr.dtype \
                and old.shape[1:] == arr.shape[1:]:
            if identical:
                stats["ref"] += nb
                return {"k": "ref"}
            a8, o8 = _flat_u8(arr), _flat_u8(old)
            prefix_eq = False
            if a8.size >= o8.size:
                # ONE prefix scan decides both ref (equal sizes) and
                # ext, with a 4 KB quick reject so the common
                # changed-everywhere arrays (LLR tables) skip the full
                # O(nbytes) pass entirely
                head = min(int(o8.size), 4096)
                prefix_eq = bool(
                    np.array_equal(a8[:head], o8[:head])
                    and np.array_equal(a8[:o8.size], o8))
            if prefix_eq and a8.size == o8.size:
                stats["ref"] += nb
                return {"k": "ref"}
            if prefix_eq:
                put_blob(f"{name}", a8[o8.size:].copy())
                stats["ref"] += int(o8.size)
                return {"k": "ext", "suffix": name,
                        "pre": int(o8.size), "shape": list(arr.shape)}
            # nz: values at the finite cells of the (same-shaped) idx
            # table; everything the mask calls invalid is one pad value.
            # Self-contained (no prev needed): the changed-bytes floor
            # for the LLR tables, whose every finite score moves per
            # fold while the padding never does
            if mask is not None and mask.shape == arr.shape:
                invalid = np.ascontiguousarray(mask) < 0
                pad_vals = arr[invalid]
                if len(pad_vals):
                    pad = pad_vals.ravel()[0]
                    if np.all(pad_vals == pad):
                        vals = arr[~invalid]
                        if vals.nbytes + 64 < nb:
                            put_blob(f"{name}", vals.copy())
                            stats["ref"] += nb - int(vals.nbytes)
                            return {"k": "nz",
                                    "mask": name.replace("_llr", "_idx"),
                                    "pad": float(pad),
                                    "shape": list(arr.shape)}
            # sparse element patch (covers growth: every element past
            # the old length counts as changed; a shrunk array cannot
            # patch — fall through to a full blob)
            if a8.size >= o8.size:
                it = arr.dtype.itemsize
                n_old = o8.size // it
                flat_a = arr.reshape(-1)
                diff = np.flatnonzero(
                    (a8[:o8.size].reshape(-1, it)
                     != o8.reshape(-1, it)).any(axis=1))
                n_new = flat_a.shape[0]
                tail = np.arange(n_old, n_new, dtype=np.int64)
                idx = (np.concatenate([diff.astype(np.int64), tail])
                       if len(tail) else diff.astype(np.int64))
                patch_bytes = int(idx.nbytes + idx.shape[0] * it)
                if patch_bytes + 64 < nb // 2:
                    put_blob(f"{name}.pidx", idx)
                    put_blob(f"{name}.pval", flat_a[idx].copy())
                    stats["ref"] += nb - patch_bytes
                    return {"k": "patch", "idx": f"{name}.pidx",
                            "vals": f"{name}.pval",
                            "shape": list(arr.shape)}
        put_blob(name, arr)
        stats["delta"] -= nb        # full blobs count as full, not delta
        stats["full"] += nb
        return {"k": "full", "key": name}

    def _write_manifest(self, doc: Dict) -> None:
        tmp = self.current_path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.current_path)

    def file_meta(self, name: str) -> Optional[Dict]:
        """A generation file's ``meta`` dict, reading only the JSON
        header (16-byte head + header bytes — no blob mapping); None
        when unreadable or torn.  The replication publisher plans
        catch-ups from these headers without ever composing a model."""
        try:
            with open(os.path.join(self.dir, name), "rb") as f:
                head = f.read(16)
                if len(head) < 16:
                    return None
                hlen = int.from_bytes(head[8:16], "little")
                if hlen > 64 << 20:
                    return None
                meta = json.loads(f.read(hlen)).get("meta", {})
        except (OSError, ValueError):
            return None
        return meta if isinstance(meta, dict) else None

    def _file_keyframe(self, name: str) -> Optional[int]:
        """A generation file's keyframeGeneration from its header alone;
        None when unreadable."""
        meta = self.file_meta(name)
        if meta is None:
            return None
        kf = meta.get("keyframeGeneration")
        if kf is not None:
            return int(kf)
        g = _gen_of(name)
        return g if name.endswith(".arena") else None

    def chain_files(self, fname: str) -> List[str]:
        """The ordered delta chain ``[keyframe .. fname]`` for one
        generation file, walking ``prevFile`` header links (headers
        only).  This is how the replicator serves a cold or lagging
        subscriber: ship the nearest keyframe plus every delta forward.
        Raises :class:`_PlaneCorrupt` naming the file that breaks the
        walk (missing link, unreadable header)."""
        chain = [str(fname)]
        f = str(fname)
        # a chain is bounded by plane_full_every(), but walk defensively
        for _ in range(100000):
            meta = self.file_meta(f)
            if meta is None:
                raise _PlaneCorrupt(f, f"{f}: unreadable header in "
                                    "delta-chain walk")
            if (meta.get("planeKind") or "full") != "delta":
                chain.reverse()
                return chain
            pf = meta.get("prevFile")
            if not pf:
                raise _PlaneCorrupt(f, f"{f}: delta with no prevFile")
            f = str(pf)
            chain.append(f)
        raise _PlaneCorrupt(str(fname), f"{fname}: delta chain does not "
                            "terminate at a keyframe")

    def _gc(self, newest_gen: int) -> Optional[int]:
        """Unlink generation files no kept generation's delta chain can
        reference.  The kept window is the newest ``PIO_MODEL_PLANE_KEEP``
        generations; each pins every file back to ITS keyframe (chains
        are contiguous generation runs and never cross a keyframe), so
        the reclaim floor is the minimum keyframe over the window —
        refcounting by construction: a blob referenced by any kept
        manifest is ≥ the floor and survives.  Also reclaims quarantined
        files past the floor and abandoned tmp files.  Returns the
        retained generation-file count (for the blob_count gauge)."""
        keep_min = newest_gen - plane_keep() + 1
        try:
            names = os.listdir(self.dir)
        except OSError:
            return None
        floor = keep_min
        for g in range(keep_min, newest_gen + 1):
            kf = self._gc_keyframes.get(g)
            if kf is None:
                # published before this process started: read its header
                for nm in (f"gen-{g:010d}.delta", f"gen-{g:010d}.arena"):
                    if os.path.exists(os.path.join(self.dir, nm)):
                        kf = self._file_keyframe(nm)
                        break
                self._gc_keyframes[g] = kf if kf is not None else g
                kf = self._gc_keyframes[g]
            floor = min(floor, kf)
        for g in [g for g in self._gc_keyframes if g < floor]:
            del self._gc_keyframes[g]
        now = time.time()
        removed = 0
        kept = 0
        for name in names:
            path = os.path.join(self.dir, name)
            if ".tmp-" in name:
                # a SIGKILL'd publisher's partial emit: invisible to
                # readers (never referenced by the manifest), reclaimed
                # once clearly abandoned
                try:
                    if now - os.path.getmtime(path) > 300:
                        os.unlink(path)
                        removed += 1
                except OSError:
                    pass
                continue
            gen = _gen_of(name)
            if gen is None:
                continue
            if gen < floor:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
            elif not name.endswith(".quarantine"):
                kept += 1
        if removed:
            _M_GC.inc(removed)
        return kept

    def _model_payload(self, model) -> Tuple[Dict[str, np.ndarray], Dict]:
        names: List[str] = list(model.indicator_idx)
        bl_names: List[str] = list(model.user_seen_by_event)
        arrays: Dict[str, np.ndarray] = {}
        meta: Dict[str, Any] = {
            "schema": 1,
            "primaryEvent": model.primary_event,
            "eventNames": names,
            "blacklistNames": bl_names,
            "nItems": len(model.item_dict),
            "nUsers": len(model.user_dict),
            "dicts": {},
        }
        arrays["popularity"] = np.asarray(model.popularity)
        arrays["pop_order"] = model.host_pop_order()
        arrays["user_seen_indptr"] = model.user_seen.indptr
        arrays["user_seen_values"] = model.user_seen.values
        for j, bname in enumerate(bl_names):
            csr = model.user_seen_by_event[bname]
            arrays[f"seen_{j}_indptr"] = csr.indptr
            arrays[f"seen_{j}_values"] = csr.values
        for i, name in enumerate(names):
            arrays[f"ind_{i}_idx"] = model.indicator_idx[name]
            arrays[f"ind_{i}_llr"] = model.indicator_llr[name]
            indptr, rows, w = model.host_inverted(name)
            arrays[f"inv_{i}_indptr"] = indptr
            arrays[f"inv_{i}_rows"] = rows
            arrays[f"inv_{i}_w"] = w
        meta["dicts"]["item"] = self._encode_dict(
            "item", model.item_dict, arrays)
        meta["dicts"]["user"] = self._encode_dict(
            "user", model.user_dict, arrays)
        for i, name in enumerate(names):
            d = model.event_item_dicts[name]
            if d is model.item_dict:
                meta["dicts"][f"ev_{i}"] = {"sameAs": "item"}
            else:
                meta["dicts"][f"ev_{i}"] = self._encode_dict(
                    f"ev_{i}", d, arrays)
        arrays["props_json"], crc = self._encode_props(
            model.item_properties)
        meta["propsCrc"] = crc
        return arrays, meta

    def _encode_dict(self, slot: str, d: IdDict,
                     arrays: Dict[str, np.ndarray]) -> Dict:
        """Dictionary → flat utf-8 blob + int64 offsets.  The blob is
        cached by dictionary OBJECT (the fold engine carries unchanged
        dicts by object — the cached ndarrays keep their identity so the
        delta publisher refs them for free), and a changed dictionary
        whose previous blob is a byte-prefix records ``prevCrc``/
        ``prevN`` so workers holding the previous dictionary extend it
        in O(new strings) instead of rebuilding — pure END growth of the
        catalog (the fold engine's common new-item case) stays O(delta)
        end to end."""
        cached = self._pub_dicts.get(slot)
        if cached is not None and cached["obj"] is d:
            entry = {"crc": cached["crc"], "n": cached["n"]}
        else:
            strings = d.strings()
            enc = [s.encode("utf-8", "surrogatepass") for s in strings]
            blob = b"".join(enc)
            offs = np.zeros(len(enc) + 1, np.int64)
            if enc:
                np.cumsum([len(b) for b in enc], out=offs[1:])
            crc = int(zlib.crc32(blob))
            entry = {"crc": crc, "n": len(strings)}
            if cached is not None and entry["n"] >= cached["n"] \
                    and len(blob) >= len(cached["blob"]) \
                    and blob[:len(cached["blob"])] == cached["blob"]:
                entry["prevCrc"] = cached["crc"]
                entry["prevN"] = cached["n"]
            cached = self._pub_dicts[slot] = {
                "obj": d, "blob": blob,
                "blob_arr": np.frombuffer(blob, np.uint8),
                "offs": offs, "crc": crc, "n": len(strings)}
        arrays[f"dict_{slot}_blob"] = cached["blob_arr"]
        arrays[f"dict_{slot}_offs"] = cached["offs"]
        return entry

    def _encode_props(self, props) -> Tuple[np.ndarray, int]:
        cached = self._pub_props
        if cached is not None and cached[0] is props:
            return cached[1], cached[2]
        blob = json.dumps(dict(props or {}), separators=(",", ":"),
                          sort_keys=True, default=str).encode()
        crc = int(zlib.crc32(blob))
        arr = np.frombuffer(blob, np.uint8)
        self._pub_props = (props, arr, crc)
        return arr, crc

    # -- worker side ---------------------------------------------------------

    def quarantine(self, manifest: Dict, err: Exception) -> None:
        """Set the torn file aside (first sibling to rename wins) and
        keep serving — the publisher's next emit notices the broken
        chain and heals it with a keyframe.  The file is the one that
        actually failed: a delta generation can fail on a file earlier
        in its chain."""
        fname = getattr(err, "fname", None) or manifest.get("file")
        log.warning(
            "model plane: generation %s unusable (%s: %s) — quarantined "
            "%s; keeping the served generation",
            manifest.get("generation"), type(err).__name__, err, fname)
        if not fname:
            return
        path = os.path.join(self.dir, str(fname))
        try:
            os.replace(path, path + ".quarantine")
        except OSError:
            pass
        self._mapped.pop(str(fname), None)

    def _map_file(self, fname: str):
        """(arrays, meta) for one generation file, cached by name —
        an already-mapped file costs a dict hit, not a remap."""
        hit = self._mapped.get(fname)
        if hit is not None:
            return hit
        path = os.path.join(self.dir, fname)
        try:
            arrays, meta = read_arrays(path, mmap=True)
        except ValueError as e:
            raise _PlaneCorrupt(fname, str(e)) from e
        self._mapped[fname] = (arrays, meta)
        return arrays, meta

    def load(self, manifest: Dict):
        """Map/compose the manifest's generation →
        ``(URModel-over-views, info)``.

        A full arena maps directly (read-only views into the shared
        mapping).  A delta generation composes against the previously
        loaded one — or, cold, walks ``prevFile`` links back to the
        last keyframe and composes the chain forward.  Derived serving
        state (inverted CSRs, pop order) installs straight into the
        model's ``__dict__`` caches, and dictionaries / property indexes
        carry from the previously loaded generation whenever the
        manifest proves them unchanged.  Raises ValueError
        (:class:`_PlaneCorrupt` with the failing file) on torn content —
        the caller quarantines; OSError (e.g. a chain file briefly
        missing mid-GC) — the caller retries."""
        fname = str(manifest["file"])
        chain: List[Tuple[str, Dict[str, np.ndarray], Dict]] = []
        f = fname
        for _ in range(100000):
            arrays, meta = self._map_file(f)
            kind = meta.get("planeKind") or "full"
            chain.append((f, arrays, meta))
            if kind != "delta":
                break
            pg = int(meta.get("prevGeneration") or 0)
            pf = meta.get("prevFile")
            if self._composed is not None and self._composed_gen == pg:
                break
            if not pf:
                raise _PlaneCorrupt(f, f"{f}: delta with no prevFile")
            f = str(pf)
        else:
            raise _PlaneCorrupt(fname, "delta chain does not terminate")
        chain.reverse()
        composed = self._composed
        inv_perms = dict(self._inv_perms)
        for cf, arrays, meta in chain:
            kind = meta.get("planeKind") or "full"
            if kind != "delta":
                composed = _ComposedGen()
                composed._arrays = {
                    n: a for n, a in arrays.items()}
                inv_perms = {}
            else:
                composed = self._compose_delta(
                    cf, composed, arrays, meta, inv_perms)
        final_meta = chain[-1][2]
        if final_meta.get("schema") != 1:
            raise _PlaneCorrupt(
                chain[-1][0],
                f"unknown arena schema {final_meta.get('schema')}")
        model = self._build_model(composed, final_meta)
        gen = int(final_meta.get("generation")
                  or manifest["generation"])
        # serve-level provenance (serve.response_cache): small int64
        # changed-set blobs COPIED out of the newest file's mapping (so
        # they never pin it) — only meaningful when this worker's
        # installed generation is exactly prevGeneration, which the
        # cache checks itself; malformed/missing blobs simply leave the
        # model provenance-free (full flush, never stale)
        sp = final_meta.get("serveProv")
        if isinstance(sp, dict):
            try:
                raw = chain[-1][1]
                model.__dict__["_serve_prov"] = {
                    "prev_gen": int(sp["prev"]),
                    "props_changed": bool(sp.get("props")),
                    "inv": {str(name): np.array(raw[str(key)], np.int64)
                            for name, key in dict(sp["inv"]).items()},
                    "pop": np.array(raw[str(sp["pop"])], np.int64),
                }
            except (KeyError, TypeError, ValueError):
                model.__dict__.pop("_serve_prov", None)
        # commit the compose state only after a fully successful build
        self._composed, self._composed_gen = composed, gen
        self._inv_perms = inv_perms
        live = {cf for cf, _a, _m in chain}
        for stale in [k for k in self._mapped if k not in live]:
            del self._mapped[stale]    # views keep their mmaps alive
        info = dict(final_meta.get("info") or {})
        info["planeGeneration"] = gen
        info["planeBytes"] = int(manifest.get("bytes") or 0)
        return model, info

    def _compose_delta(self, fname: str, prev: Optional[_ComposedGen],
                       arrays: Dict[str, np.ndarray], meta: Dict,
                       inv_perms: Dict[int, np.ndarray]) -> _ComposedGen:
        """Apply one delta generation's manifest over the previous
        composed generation.  Eager for numeric arrays (everything the
        model build touches anyway), lazy for dictionary blobs and the
        props JSON (worker caches usually skip them)."""
        if prev is None:
            raise _PlaneCorrupt(
                fname, f"{fname}: delta chain has no base generation")
        manifest: Dict[str, Dict] = meta.get("manifest") or {}
        out = _ComposedGen()
        memo: Dict[str, np.ndarray] = {}
        trio_memo: Dict[int, Tuple] = {}
        resolving: set = set()

        def prev_arr(name: str) -> np.ndarray:
            try:
                return prev[name]
            except KeyError:
                raise _PlaneCorrupt(
                    fname, f"{fname}: base generation lacks {name}")

        def resolve(name: str) -> np.ndarray:
            got = memo.get(name)
            if got is not None:
                return got
            if name in resolving:
                raise _PlaneCorrupt(fname, f"{fname}: manifest cycle at "
                                           f"{name}")
            resolving.add(name)
            try:
                entry = manifest.get(name)
                if entry is None:
                    raise _PlaneCorrupt(
                        fname, f"{fname}: manifest lacks {name}")
                arr = self._compose_entry(fname, name, entry, prev_arr,
                                          arrays, meta, resolve,
                                          inv_perms, trio_memo)
            finally:
                resolving.discard(name)
            memo[name] = arr
            return arr

        for name, entry in manifest.items():
            k = entry["k"]
            if _lazy_name(name) and k in ("ref", "ext", "full"):
                # stay lazy WITHOUT referencing the previous composed
                # generation: carry a self-contained byte-parts chain
                try:
                    if k == "full":
                        out._arrays[name] = arrays[entry["key"]]
                    elif name not in prev:
                        raise _PlaneCorrupt(
                            fname, f"{fname}: base generation lacks "
                                   f"{name}")
                    elif k == "ref":
                        got = prev._arrays.get(name)
                        if got is not None:
                            out._arrays[name] = got
                        else:
                            out._parts[name] = prev.parts_of(name)
                    else:               # ext
                        suffix = arrays[entry["suffix"]]
                        dt, _shape, base = prev.parts_of(name)
                        out._parts[name] = (
                            dt, tuple(entry["shape"]), base + [suffix])
                        out._suffixes[name] = (suffix,
                                               int(entry["pre"]))
                except KeyError as e:
                    raise _PlaneCorrupt(
                        fname, f"{fname}: cannot compose {name}: "
                               f"{e}") from e
            else:
                out._arrays[name] = _freeze(resolve(name))
        return out

    def _compose_entry(self, fname: str, name: str, entry: Dict,
                       prev_arr, arrays: Dict[str, np.ndarray],
                       meta: Dict, resolve, inv_perms,
                       trio_memo: Dict[int, Tuple]) -> np.ndarray:
        try:
            k = entry["k"]
            if k == "ref":
                return prev_arr(name)
            if k == "full":
                return arrays[entry["key"]]
            if k == "ext":
                old = prev_arr(name)
                suffix = arrays[entry["suffix"]]
                flat = np.concatenate([_flat_u8(
                    np.ascontiguousarray(old)), suffix])
                return flat.view(old.dtype).reshape(
                    tuple(entry["shape"]))
            if k == "patch":
                old = prev_arr(name)
                shape = tuple(entry["shape"])
                idx = arrays[entry["idx"]]
                vals = arrays[entry["vals"]]
                n = int(np.prod(shape)) if shape else 1
                flat = np.empty(n, old.dtype)
                flat[:old.size] = old.reshape(-1)
                flat[idx] = vals
                return flat.reshape(shape)
            if k == "nz":
                mask = resolve(entry["mask"])
                vals = arrays[name]
                out = np.full(mask.shape, entry["pad"], vals.dtype)
                out[mask >= 0] = vals
                return out
            if k == "inv":
                i = int(entry["type"])
                part = name.rsplit("_", 1)[1]
                return self._replay_inv(
                    fname, i, arrays[entry["changed"]], prev_arr,
                    resolve, meta, inv_perms, trio_memo)[
                        {"indptr": 0, "rows": 1, "w": 2}[part]]
            if k == "pop_order":
                from predictionio_tpu.streaming.fold import (
                    _merge_pop_order,
                )

                old = prev_arr("pop_order")
                pop = np.asarray(resolve("popularity"), np.float32)
                return _merge_pop_order(old, pop,
                                        arrays[entry["changed"]])
            raise KeyError(f"unknown entry kind {k!r}")
        except _PlaneCorrupt:
            raise
        except (KeyError, IndexError, ValueError) as e:
            raise _PlaneCorrupt(
                fname,
                f"{fname}: cannot compose {name}: "
                f"{type(e).__name__}: {e}") from e

    def _replay_inv(self, fname: str, i: int, changed: np.ndarray,
                    prev_arr, resolve, meta: Dict, inv_perms,
                    trio_memo: Dict[int, Tuple]
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Replay the fold engine's inverted-CSR patch for event type
        ``i`` — the same functions, the same arguments (the changed-row
        set from the emit-snapshot provenance), so the result is
        bit-identical to the publisher's arrays (which were produced by
        this very replay on its side).  The inversion permutation is
        maintained across generations like the fold's ``_inv_cache``
        (validity keyed to the idx object it was built for) and
        recomputed from the previous idx table when absent — e.g. right
        after a keyframe."""
        from predictionio_tpu.streaming.fold import (
            _inverted_perm,
            _patch_inverted_csr,
        )

        got = trio_memo.get(i)
        if got is not None:
            return got      # the trio composes once per generation
        old_indptr = prev_arr(f"inv_{i}_indptr")
        old_rows = prev_arr(f"inv_{i}_rows")
        old_idx = prev_arr(f"ind_{i}_idx")
        new_idx = resolve(f"ind_{i}_idx")
        new_llr = resolve(f"ind_{i}_llr")
        dent = meta["dicts"][f"ev_{i}"]
        if dent.get("sameAs") == "item":
            dent = meta["dicts"]["item"]
        n_t = max(int(dent["n"]), 1)
        i_p = int(new_idx.shape[0])
        cache = inv_perms.get(i)
        if cache is not None and cache["for_idx"] is old_idx:
            perm = cache["perm"]
        else:
            perm = _inverted_perm(np.asarray(old_idx))
        changed = np.asarray(changed, np.int64)
        if len(changed) == 0:
            indptr = np.asarray(old_indptr)
            if len(indptr) < n_t + 1:
                indptr = np.concatenate([indptr, np.full(
                    n_t + 1 - len(indptr), indptr[-1], np.int64)])
            rows = np.asarray(old_rows)
        else:
            indptr, rows, perm = _patch_inverted_csr(
                np.asarray(old_indptr), np.asarray(old_rows), perm,
                changed, np.asarray(old_idx), np.asarray(new_idx),
                n_t, i_p)
        w = np.asarray(new_llr).ravel()[perm].astype(
            np.float32, copy=False)
        inv_perms[i] = {"for_idx": new_idx, "perm": perm}
        trio = (_freeze(np.asarray(indptr)), _freeze(np.asarray(rows)),
                _freeze(w))
        trio_memo[i] = trio
        return trio

    def _build_model(self, arrays, meta: Dict):
        from predictionio_tpu.models.universal_recommender.engine import (
            URModel,
        )

        names = list(meta["eventNames"])
        item_dict = self._restore_dict("item", meta["dicts"]["item"],
                                       arrays)
        user_dict = self._restore_dict("user", meta["dicts"]["user"],
                                       arrays)
        event_item_dicts: Dict[str, IdDict] = {}
        for i, name in enumerate(names):
            entry = meta["dicts"][f"ev_{i}"]
            event_item_dicts[name] = (
                item_dict if entry.get("sameAs") == "item"
                else self._restore_dict(f"ev_{i}", entry, arrays))
        user_seen_by_event = {
            bname: CSRLookup(arrays[f"seen_{j}_indptr"],
                             arrays[f"seen_{j}_values"])
            for j, bname in enumerate(meta["blacklistNames"])}
        prev, prev_meta = self._prev_model, self._prev_meta
        item_crc = meta["dicts"]["item"]["crc"]
        props_carried = (
            prev is not None and prev_meta is not None
            and meta.get("propsCrc") == prev_meta.get("propsCrc")
            and item_crc == prev_meta["dicts"]["item"]["crc"])
        if props_carried:
            props = prev.item_properties
        elif "props_json" in arrays:
            # the lazy thunk must capture only the SELF-CONTAINED parts
            # descriptor (raw mmap byte views), never the _ComposedGen —
            # a long-carried unparsed props object would otherwise pin
            # an entire stale generation's composed arrays in memory
            dt, shape, parts = arrays.parts_of("props_json")

            def _raw_props(dt=dt, shape=shape, parts=parts):
                flat = (parts[0] if len(parts) == 1
                        else np.concatenate(parts))
                return flat.view(np.dtype(dt)).reshape(shape)
            props = _LazyProps(_raw_props)
        else:
            props = _LazyProps(None)
        model = URModel(
            primary_event=meta["primaryEvent"],
            item_dict=item_dict,
            user_dict=user_dict,
            indicator_idx={n: arrays[f"ind_{i}_idx"]
                           for i, n in enumerate(names)},
            indicator_llr={n: arrays[f"ind_{i}_llr"]
                           for i, n in enumerate(names)},
            event_item_dicts=event_item_dicts,
            popularity=arrays["popularity"],
            item_properties=props,
            user_seen=CSRLookup(arrays["user_seen_indptr"],
                                arrays["user_seen_values"]),
            user_seen_by_event=user_seen_by_event,
        )
        # derived serving state rides the plane: pre-populate the lazy
        # caches so warm()/first-query find them built (as views)
        model.__dict__["_host_inv"] = {
            n: (arrays[f"inv_{i}_indptr"], arrays[f"inv_{i}_rows"],
                arrays[f"inv_{i}_w"])
            for i, n in enumerate(names)}
        model.__dict__["_host_pop_order"] = arrays["pop_order"]
        if props_carried:
            # the property-derived indexes (value→ids, date arrays,
            # known-name set, date-offset LRU) are functions of
            # (item_dict, item_properties) — both proven unchanged, so
            # whatever THIS worker already built carries forward and
            # rules keep serving without a rebuild
            for attr in ("_prop_value_index", "_prop_date_array",
                         "_known_prop_names", "_date_off"):
                v = prev.__dict__.get(attr)
                if v is not None:
                    model.__dict__[attr] = v
        if prev is not None:
            # composed rule masks / value bitsets / date arrays carry on
            # the same (item crc + propsCrc) proof; a props change
            # records the per-entry drop instead of flushing silently
            model.adopt_rule_caches(prev, carry=props_carried)
        if prev is not None and prev_meta is not None \
                and item_crc == prev_meta["dicts"]["item"]["crc"]:
            z = prev.__dict__.get("_host_zeros")
            if z is not None:   # read-only by contract; same n_items
                model.__dict__["_host_zeros"] = z
        model.__dict__["_plane_generation"] = int(meta.get("generation",
                                                           0))
        self._prev_model, self._prev_meta = model, meta
        return model

    def _restore_dict(self, slot: str, entry: Dict, arrays) -> IdDict:
        crc, n = int(entry["crc"]), int(entry["n"])
        cached = self._dict_cache.get(slot)
        if cached is not None and cached[0] == crc \
                and len(cached[1]) == n:
            return cached[1]
        if cached is not None and entry.get("prevCrc") == cached[0] \
                and entry.get("prevN") == len(cached[1]):
            # publisher proved our dictionary is a byte-prefix of the
            # new blob: extend a clone with only the tail strings
            d = cached[1].clone()
            start = int(entry["prevN"])
            suffix = (arrays.suffix_of(f"dict_{slot}_blob")
                      if isinstance(arrays, _ComposedGen) else None)
            if suffix is not None:
                # delta fast path: the ext suffix IS the tail bytes —
                # decode it with the offs suffix, never composing (or
                # even touching) the covered prefix
                tail_blob, base = suffix
                tail = bytes(tail_blob)
                offs_sfx = arrays.suffix_of(f"dict_{slot}_offs")
                if offs_sfx is not None \
                        and offs_sfx[0].size == (n - start) * 8:
                    offs_tail = offs_sfx[0].view(np.int64)
                    bounds = np.concatenate(
                        [[np.int64(base)], offs_tail]) - base
                else:
                    offs = arrays[f"dict_{slot}_offs"]
                    bounds = np.asarray(offs[start:n + 1], np.int64) - base
                for j in range(n - start):
                    d.add(tail[int(bounds[j]):int(bounds[j + 1])]
                          .decode("utf-8", "surrogatepass"))
            else:
                blob = arrays[f"dict_{slot}_blob"]
                offs = arrays[f"dict_{slot}_offs"]
                base = int(offs[start])
                tail = bytes(blob[base:])
                for j in range(start, n):
                    d.add(tail[int(offs[j]) - base:
                               int(offs[j + 1]) - base]
                          .decode("utf-8", "surrogatepass"))
            self.dicts_extended += 1
        else:
            blob = arrays[f"dict_{slot}_blob"]
            offs = arrays[f"dict_{slot}_offs"]
            raw = bytes(blob)
            d = IdDict.from_state(
                [raw[int(offs[j]):int(offs[j + 1])]
                 .decode("utf-8", "surrogatepass") for j in range(n)])
            self.dicts_rebuilt += 1
        self._dict_cache[slot] = (crc, d)
        return d


def _gen_of(name: str) -> Optional[int]:
    """Generation number encoded in a plane file name (gen-N.arena,
    gen-N.delta, either + .quarantine); None for foreign files."""
    if not name.startswith("gen-"):
        return None
    try:
        return int(name[4:14])
    except ValueError:
        return None


class _DirNotify:
    """inotify wake-up on the plane directory (Linux, via ctypes — no
    external deps): ``wait`` returns as soon as a file lands/renames in
    the dir, so manifest flips propagate in ~ms instead of a poll
    period.  Degrades to None (callers poll) anywhere the syscalls are
    unavailable."""

    IN_CLOSE_WRITE = 0x00000008
    IN_CREATE = 0x00000100
    IN_MOVED_TO = 0x00000080

    def __init__(self, directory: str):
        import ctypes
        import ctypes.util

        libc_name = ctypes.util.find_library("c")
        if not libc_name:
            raise OSError("no libc")
        libc = ctypes.CDLL(libc_name, use_errno=True)
        try:
            init1 = libc.inotify_init1
            add_watch = libc.inotify_add_watch
        except AttributeError:   # non-Linux libc: no inotify symbols —
            raise OSError("inotify unavailable")  # callers poll instead
        self._fd = init1(os.O_NONBLOCK | 0o2000000)
        if self._fd < 0:
            raise OSError("inotify_init1 failed")
        wd = add_watch(
            self._fd, os.fsencode(directory),
            self.IN_CLOSE_WRITE | self.IN_CREATE | self.IN_MOVED_TO)
        if wd < 0:
            os.close(self._fd)
            raise OSError("inotify_add_watch failed")
        # self-pipe so stop() interrupts a wait immediately
        self._r, self._w = os.pipe()
        os.set_blocking(self._r, False)
        # poll(), not select(): fd numbers in a busy prefork worker can
        # exceed select's FD_SETSIZE (1024), which raises ValueError and
        # would kill the watch thread
        self._poll = select.poll()
        self._poll.register(self._fd, select.POLLIN)
        self._poll.register(self._r, select.POLLIN)

    def wait(self, timeout: float) -> bool:
        """Block up to ``timeout``; True when a directory event (not a
        stop poke) woke us."""
        try:
            ready = self._poll.poll(max(timeout, 0) * 1000)
        except (OSError, ValueError):
            return False
        woke = False
        for fd, _ev in ready:
            try:
                data = os.read(fd, 65536)
            except OSError:
                data = b""
            if fd == self._fd and data:
                woke = True
        return woke

    def poke(self) -> None:
        try:
            os.write(self._w, b"x")
        except OSError:
            pass

    def close(self) -> None:
        for fd in (self._fd, self._r, self._w):
            try:
                os.close(fd)
            except OSError:
                pass


def plane_notify_enabled() -> bool:
    """``PIO_MODEL_PLANE_NOTIFY=off`` forces the stat-poll fallback
    (debugging aid; also for filesystems with broken inotify)."""
    return os.environ.get("PIO_MODEL_PLANE_NOTIFY", "").lower() not in (
        "off", "0", "false")


class PlaneWatcher:
    """Per-worker manifest watcher: installs each new generation through
    the server's build-ticket install path.  Wake-up is inotify on the
    plane dir where available (manifest renames propagate in ~ms —
    swap latency is no longer quantized by PIO_MODEL_PLANE_POLL_S);
    otherwise a stat-cheap poll: one ``os.stat`` of CURRENT.json per
    period, opening/parsing it only when (mtime, size, ino) moved.
    ``check_now()`` runs one synchronous check (the ``/reload`` handler
    and the in-process publisher use it so their response generation is
    live before they answer)."""

    def __init__(self, plane: ModelPlane, install,
                 poll_s: Optional[float] = None):
        self.plane = plane
        self.install = install     # callable(models, info) -> bool
        self.poll = poll_s if poll_s is not None else plane_poll_s()
        self.generation = 0
        self._bad_gen = 0
        self._warned_gen = 0
        self._retry = False
        self._stat_sig: Optional[Tuple] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._notify: Optional[_DirNotify] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pio-model-plane-watch")
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._notify is not None:
            self._notify.poke()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._notify is not None:
            self._notify.close()
            self._notify = None

    def _manifest_moved(self) -> bool:
        """Stat-cheap change probe: did CURRENT.json's (ino, mtime,
        size) move since the last probe?  First call always reports
        movement (the worker must catch up with whatever is live)."""
        try:
            st = os.stat(self.plane.current_path)
            sig = (st.st_ino, st.st_mtime_ns, st.st_size)
        except OSError:
            sig = None
        if sig == self._stat_sig:
            return False
        self._stat_sig = sig
        return True

    def _loop(self) -> None:
        if plane_notify_enabled() and self._notify is None:
            try:
                os.makedirs(self.plane.dir, exist_ok=True)
                self._notify = _DirNotify(self.plane.dir)
            except OSError:
                self._notify = None     # poll fallback
        while not self._stop.is_set():
            if self._notify is not None:
                self._notify.wait(self.poll)
            elif self._stop.wait(self.poll):
                break
            if self._stop.is_set():
                break
            try:
                # the stat probe elides parsing an unchanged manifest;
                # a pending transient-failure retry bypasses it (the
                # manifest didn't move, but the chain may have healed)
                if self._manifest_moved() or self._retry:
                    self.check_now()
            except Exception:
                log.exception("model-plane watch failed; keeping the "
                              "served generation")

    def check_now(self) -> bool:
        """One check-and-install; True when a new generation went live
        on this worker."""
        with self._lock:
            self._retry = False
            cur = self.plane.current()
            if cur is None:
                return False
            gen = int(cur.get("generation") or 0)
            if gen <= self.generation or gen == self._bad_gen:
                return False
            t0 = time.perf_counter()
            w_wake = time.time()
            try:
                model, info = self.plane.load(cur)
            except (ValueError, KeyError) as e:
                # deterministic content corruption (torn write): retrying
                # cannot help — quarantine the failing file, remember the
                # bad generation (no re-probe storm), serve the old one;
                # the publisher heals the chain with a keyframe
                self._bad_gen = gen
                self.plane.quarantine(cur, e)
                return False
            except OSError as e:
                # transient I/O (EMFILE under load, a sibling's
                # quarantine rename racing us, mid-GC): do NOT
                # quarantine a possibly-good blob — keep serving and
                # retry on the next poll (log once per generation)
                self._retry = True
                if self._warned_gen != gen:
                    self._warned_gen = gen
                    log.warning(
                        "model plane: could not map generation %s (%s) "
                        "— keeping the served generation, will retry",
                        gen, e)
                return False
            lid = (info or {}).get("lineageId")
            if lid:
                lin = _lineage.get_lineage()
                if lin.enabled:
                    # watcher_wake spans publish→this poll noticing it
                    # (the cross-process freshness gap); compose is the
                    # mmap+chain-compose this worker just paid
                    pub_at = float(cur.get("publishedAt") or w_wake)
                    lin.stage(lid, "watcher_wake", start=pub_at,
                              duration_s=max(w_wake - pub_at, 0.0))
                    lin.stage(lid, "compose", start=w_wake,
                              duration_s=time.perf_counter() - t0,
                              generation=gen,
                              kind=str(cur.get("kind") or ""))
            installed = self.install([model], info)
            # the generation is consumed either way: install() returns
            # False only when a newer build ticket (a later check or the
            # startup private load racing us) already swapped in
            self.generation = gen
            tag = _obs_metrics.worker_tag()
            _M_GEN.set(gen, worker=tag)
            _M_BYTES.set(int(cur.get("bytes") or 0), worker=tag)
            if installed:
                _M_MAP_S.set(time.perf_counter() - t0, worker=tag)
            _obs_metrics.update_process_rss()
            return installed
