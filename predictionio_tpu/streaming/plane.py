"""Shared-memory model plane: one model per node, N prefork workers map it.

Without the plane, every ``pio deploy --workers N`` worker owns a private
copy of everything: with ``--follow`` each worker runs its OWN embedded
follower (the same delta folded N times, the same host_inverted CSR built
N times) and resident model memory is N× the model size.  The plane
inverts the topology to match the reference's deployment model (many
stateless serving processes reading ONE trained model from a shared
store):

- each model generation is emitted exactly ONCE — by the single
  plane-publisher process (``pio deploy --plane-publisher``, spawned next
  to the prefork group when ``--follow`` is on) or by whichever worker
  handles a ``/reload`` — into an mmap-able **arena** file under the
  storage dir (:func:`store.columnar.write_arrays`: magic + JSON manifest
  + 64-aligned blobs; two-phase tmp+fsync+rename under a flock'd
  generation ticket, the same crash-safety discipline as snapshots).  The
  arena includes the *derived* serving state (host_inverted CSR,
  host_pop_order, user_seen CSRs) so workers never rebuild it;
- prefork workers watch the plane's ``CURRENT.json`` manifest
  (:class:`PlaneWatcher`), map the new generation's arrays READ-ONLY
  (``mmap`` + ``np.frombuffer`` — all workers share page cache, so
  resident model bytes go N× → ~1×), reconstruct thin :class:`URModel`
  wrappers around the views, and install through the query server's
  build-ticket ``_install`` path.  The old generation unmaps once
  in-flight queries drain (the arrays' refcounts ARE the drain barrier);
- stale arena files are GC'd by the publisher (``PIO_MODEL_PLANE_KEEP``
  newest generations retained; a mapped-but-unlinked arena stays valid —
  POSIX keeps the pages — so GC can never corrupt a serving worker);
- a torn arena (publisher SIGKILL'd mid-emit) fails validation on map,
  is quarantined (``*.quarantine``), and workers keep serving the old
  generation until the publisher re-emits.

``PIO_MODEL_PLANE=off`` keeps the per-worker in-process path as the
parity oracle; ``on`` forces the plane even at ``--workers 1`` (the
in-process test topology); the default (auto) enables it for prefork
groups (``--workers > 1``).  Only single-:class:`URModel` bundles ride
the plane — anything else raises :class:`PlaneUnsupported` and the
caller degrades to the private-model path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from collections.abc import Mapping
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.obs import metrics as _obs_metrics
from predictionio_tpu.store.columnar import (
    CSRLookup,
    IdDict,
    read_arrays,
    write_arrays,
)

log = logging.getLogger("pio.modelplane")

_REG = _obs_metrics.get_registry()
_M_GEN = _REG.gauge(
    "pio_model_plane_generation",
    "Model-plane generation this worker serves, one {worker} series per "
    "process (the publisher's series is the generation it last emitted) "
    "— all series equal means the prefork group has converged")
_M_BYTES = _REG.gauge(
    "pio_model_plane_bytes",
    "On-disk bytes of the model-plane arena this worker last mapped "
    "(or, for the publisher, last emitted), one {worker} series per "
    "process — ≈ the ONE per-node resident model cost: model tables + "
    "derived CSRs, shared by every mapping worker via page cache")
_M_MAP_S = _REG.gauge(
    "pio_model_plane_map_seconds",
    "Wall seconds this worker spent mapping + installing its last plane "
    "generation (mmap + wrapper reconstruction + serving-bundle warm), "
    "one {worker} series — the per-worker cost that replaced a full "
    "fold + derived-state rebuild")
_M_GC = _REG.counter(
    "pio_model_plane_gc_total",
    "Stale model-plane arena files unlinked by the publisher's GC "
    "(generations older than PIO_MODEL_PLANE_KEEP, quarantined torn "
    "arenas past the keep window, and abandoned tmp files)")

_CURRENT = "CURRENT.json"
_LOCK = "plane.lock"


class PlaneUnsupported(RuntimeError):
    """The model bundle cannot ride the plane (not exactly one URModel);
    callers degrade to the private in-process path."""


def plane_mode() -> str:
    """'on' | 'off' | 'auto' from PIO_MODEL_PLANE (default auto)."""
    conf = os.environ.get("PIO_MODEL_PLANE", "").lower()
    if conf in ("off", "0", "false"):
        return "off"
    if conf in ("on", "1", "true"):
        return "on"
    return "auto"


def plane_wanted(workers: int) -> bool:
    """auto enables the plane exactly where private copies multiply:
    prefork groups.  'on' forces it for a single worker too (tests, and
    the child workers the parent spawns with the dir pre-resolved)."""
    mode = plane_mode()
    return mode == "on" or (mode == "auto" and workers > 1)


def plane_poll_s() -> float:
    """PIO_MODEL_PLANE_POLL_S: seconds between a worker's manifest polls
    (default 0.2 — the swap-propagation latency bound; the poll is one
    small-file read)."""
    try:
        return max(
            float(os.environ.get("PIO_MODEL_PLANE_POLL_S", "0.2")), 0.02)
    except ValueError:
        return 0.2


def plane_keep() -> int:
    """PIO_MODEL_PLANE_KEEP: newest arena generations the publisher's GC
    retains on disk (default 3 — current + drain margin; a worker still
    mapping an unlinked arena keeps serving it, POSIX keeps the pages)."""
    try:
        return max(int(os.environ.get("PIO_MODEL_PLANE_KEEP", "3")), 1)
    except ValueError:
        return 3


def resolve_plane_dir(storage, engine_id: str,
                      variant: str) -> Optional[str]:
    """Where the plane lives: PIO_MODEL_PLANE_DIR wins (the prefork
    parent pins children and the publisher to its own resolution), else
    next to the engine metadata under the METADATA **localfs** path;
    None (plane unavailable) for other backends.  A sharedfs METADATA
    store does NOT auto-resolve: the plane's mmap/GC/flock invariants
    assume one node's kernel (an unlinked-but-mapped arena stays valid;
    flock is advisory-reliable), neither of which holds across NFS-style
    mounts — multi-node sharedfs operators must point
    PIO_MODEL_PLANE_DIR at a node-LOCAL directory explicitly."""
    env = os.environ.get("PIO_MODEL_PLANE_DIR")
    if env:
        return env
    try:
        src = storage.config.sources[storage.config.repositories["METADATA"]]
    except (KeyError, AttributeError):
        return None
    if src.get("type") != "localfs" or not src.get("path"):
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in f"{engine_id}-{variant}")
    return str(Path(src["path"]) / "model_plane" / safe)


class _LazyProps(Mapping):
    """``item_properties`` view over the arena's JSON blob, parsed ONCE
    on first real access — steady-state workers serve business rules
    from carried derived indexes and never pay the parse."""

    __slots__ = ("_raw", "_doc")

    def __init__(self, raw: Optional[np.ndarray]):
        self._raw = raw
        self._doc: Optional[dict] = None

    def _load(self) -> dict:
        if self._doc is None:
            if self._raw is None or len(self._raw) == 0:
                self._doc = {}
            else:
                self._doc = json.loads(bytes(self._raw))
            self._raw = None   # the parsed dict owns the data now
        return self._doc

    def __getitem__(self, key):
        return self._load()[key]

    def __iter__(self):
        return iter(self._load())

    def __len__(self):
        return len(self._load())


def _json_info(info: Optional[Dict]) -> Dict:
    """JSON-safe subset of a publish info dict (it may carry follower
    internals)."""
    return {k: v for k, v in (info or {}).items()
            if isinstance(v, (str, int, float, bool, type(None)))}


class ModelPlane:
    """One plane directory: arena emit (publisher side) + arena map
    (worker side).  Both sides are safe to host in one process (the
    ``--workers 1`` / in-process-test topology): the caches are
    per-instance and the publish ticket is a cross-process flock."""

    def __init__(self, directory: str):
        self.dir = str(directory)
        # publisher-side caches: dict blobs / props blobs keyed by OBJECT
        # identity — the fold engine carries unchanged dictionaries and
        # property maps by object across generations, so steady-state
        # publishes re-encode nothing
        self._pub_dicts: Dict[str, Dict[str, Any]] = {}
        self._pub_props: Optional[Tuple[Any, bytes, int]] = None
        # worker-side caches: reconstructed IdDicts keyed by content crc
        # (carried when unchanged, extended when the publisher proves the
        # previous blob is a byte-prefix), plus the previous generation's
        # model for derived-prop-index carry
        self._dict_cache: Dict[str, Tuple[int, IdDict]] = {}
        self._prev_model = None
        self._prev_meta: Optional[Dict] = None
        self.dicts_extended = 0   # test observability
        self.dicts_rebuilt = 0

    # -- manifest ------------------------------------------------------------

    @property
    def current_path(self) -> str:
        return os.path.join(self.dir, _CURRENT)

    def current(self) -> Optional[Dict]:
        """The live manifest, or None (no generation published yet /
        torn manifest — the write is atomic, so torn means absent)."""
        try:
            with open(self.current_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if not isinstance(doc, dict) or "generation" not in doc \
                or "file" not in doc:
            return None
        return doc

    @contextmanager
    def _publish_lock(self):
        import fcntl

        os.makedirs(self.dir, exist_ok=True)
        with open(os.path.join(self.dir, _LOCK), "a+") as f:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    # -- publisher side ------------------------------------------------------

    def publish(self, models, info: Optional[Dict] = None) -> int:
        """Emit one model generation into the arena; returns the plane
        generation.  Exactly the ``FollowTrainer.on_publish`` signature,
        so the plane publisher wires in as the follower's publish hook.

        Raises :class:`PlaneUnsupported` for non-UR bundles and lets
        OSError/ValueError propagate — the follower's publish-retry
        machinery owns transient failures."""
        from predictionio_tpu.models.universal_recommender.engine import (
            URModel,
        )

        if not (isinstance(models, (list, tuple)) and len(models) == 1
                and type(models[0]) is URModel):
            raise PlaneUnsupported(
                "the model plane serializes exactly one URModel; got "
                f"{[type(m).__name__ for m in (models or [])]}")
        model = models[0]
        # the publisher pays the ONE derived-state build (or the fold
        # engine's incremental patch) per node; workers only map
        model.ensure_host_serving_state()
        arrays, meta = self._model_payload(model)
        meta["info"] = _json_info(info)
        with self._publish_lock():
            cur = self.current()
            gen = int(cur["generation"]) + 1 if cur else 1
            meta["generation"] = gen
            fname = f"gen-{gen:010d}.arena"
            path = os.path.join(self.dir, fname)
            tmp = os.path.join(self.dir, f".{fname}.tmp-{os.getpid()}")
            write_arrays(tmp, arrays, meta)          # flush+fsync inside
            os.replace(tmp, path)
            size = os.path.getsize(path)
            self._write_manifest({
                "version": 1, "generation": gen, "file": fname,
                "bytes": size, "publisherPid": os.getpid(),
                "publishedAt": time.time(),
            })
            self._gc(gen)
        tag = _obs_metrics.worker_tag()
        _M_GEN.set(gen, worker=tag)
        _M_BYTES.set(size, worker=tag)
        log.info("model plane: published generation %d (%s, %.1f MB)",
                 gen, fname, size / 1e6)
        return gen

    def _write_manifest(self, doc: Dict) -> None:
        tmp = self.current_path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.current_path)

    def _gc(self, newest_gen: int) -> None:
        """Unlink arenas older than the keep window (plus quarantined
        torn arenas past it and abandoned tmp files).  A worker still
        mapping an unlinked arena is unaffected — the mapping holds the
        pages until the worker's old generation drains."""
        keep_min = newest_gen - plane_keep() + 1
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        now = time.time()
        removed = 0
        for name in names:
            path = os.path.join(self.dir, name)
            if ".tmp-" in name:
                # a SIGKILL'd publisher's partial emit: invisible to
                # readers (never referenced by the manifest), reclaimed
                # once clearly abandoned
                try:
                    if now - os.path.getmtime(path) > 300:
                        os.unlink(path)
                        removed += 1
                except OSError:
                    pass
                continue
            if not name.startswith("gen-"):
                continue
            try:
                gen = int(name[4:14])
            except ValueError:
                continue
            if gen < keep_min:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
        if removed:
            _M_GC.inc(removed)

    def _model_payload(self, model) -> Tuple[Dict[str, np.ndarray], Dict]:
        names: List[str] = list(model.indicator_idx)
        bl_names: List[str] = list(model.user_seen_by_event)
        arrays: Dict[str, np.ndarray] = {}
        meta: Dict[str, Any] = {
            "schema": 1,
            "primaryEvent": model.primary_event,
            "eventNames": names,
            "blacklistNames": bl_names,
            "nItems": len(model.item_dict),
            "nUsers": len(model.user_dict),
            "dicts": {},
        }
        arrays["popularity"] = np.asarray(model.popularity)
        arrays["pop_order"] = model.host_pop_order()
        arrays["user_seen_indptr"] = model.user_seen.indptr
        arrays["user_seen_values"] = model.user_seen.values
        for j, bname in enumerate(bl_names):
            csr = model.user_seen_by_event[bname]
            arrays[f"seen_{j}_indptr"] = csr.indptr
            arrays[f"seen_{j}_values"] = csr.values
        for i, name in enumerate(names):
            arrays[f"ind_{i}_idx"] = model.indicator_idx[name]
            arrays[f"ind_{i}_llr"] = model.indicator_llr[name]
            indptr, rows, w = model.host_inverted(name)
            arrays[f"inv_{i}_indptr"] = indptr
            arrays[f"inv_{i}_rows"] = rows
            arrays[f"inv_{i}_w"] = w
        meta["dicts"]["item"] = self._encode_dict(
            "item", model.item_dict, arrays)
        meta["dicts"]["user"] = self._encode_dict(
            "user", model.user_dict, arrays)
        for i, name in enumerate(names):
            d = model.event_item_dicts[name]
            if d is model.item_dict:
                meta["dicts"][f"ev_{i}"] = {"sameAs": "item"}
            else:
                meta["dicts"][f"ev_{i}"] = self._encode_dict(
                    f"ev_{i}", d, arrays)
        blob, crc = self._encode_props(model.item_properties)
        arrays["props_json"] = np.frombuffer(blob, np.uint8)
        meta["propsCrc"] = crc
        return arrays, meta

    def _encode_dict(self, slot: str, d: IdDict,
                     arrays: Dict[str, np.ndarray]) -> Dict:
        """Dictionary → flat utf-8 blob + int64 offsets.  The blob is
        cached by dictionary OBJECT (the fold engine carries unchanged
        dicts by object), and a changed dictionary whose previous blob
        is a byte-prefix records ``prevCrc``/``prevN`` so workers
        holding the previous dictionary extend it in O(new strings)
        instead of rebuilding — pure END growth of the catalog (the
        fold engine's common new-item case) stays O(delta) end to
        end."""
        cached = self._pub_dicts.get(slot)
        if cached is not None and cached["obj"] is d:
            entry = {"crc": cached["crc"], "n": cached["n"]}
        else:
            strings = d.strings()
            enc = [s.encode("utf-8", "surrogatepass") for s in strings]
            blob = b"".join(enc)
            offs = np.zeros(len(enc) + 1, np.int64)
            if enc:
                np.cumsum([len(b) for b in enc], out=offs[1:])
            crc = int(zlib.crc32(blob))
            entry = {"crc": crc, "n": len(strings)}
            if cached is not None and entry["n"] >= cached["n"] \
                    and len(blob) >= len(cached["blob"]) \
                    and blob[:len(cached["blob"])] == cached["blob"]:
                entry["prevCrc"] = cached["crc"]
                entry["prevN"] = cached["n"]
            cached = self._pub_dicts[slot] = {
                "obj": d, "blob": blob, "offs": offs,
                "crc": crc, "n": len(strings)}
        arrays[f"dict_{slot}_blob"] = np.frombuffer(cached["blob"],
                                                    np.uint8)
        arrays[f"dict_{slot}_offs"] = cached["offs"]
        return entry

    def _encode_props(self, props) -> Tuple[bytes, int]:
        cached = self._pub_props
        if cached is not None and cached[0] is props:
            return cached[1], cached[2]
        blob = json.dumps(dict(props or {}), separators=(",", ":"),
                          sort_keys=True, default=str).encode()
        crc = int(zlib.crc32(blob))
        self._pub_props = (props, blob, crc)
        return blob, crc

    # -- worker side ---------------------------------------------------------

    def quarantine(self, manifest: Dict, err: Exception) -> None:
        """Set a torn arena aside (first sibling to rename wins) and
        keep serving — the publisher's next emit supersedes it."""
        fname = manifest.get("file")
        log.warning(
            "model plane: arena generation %s unusable (%s) — "
            "quarantined; keeping the served generation",
            manifest.get("generation"), err)
        if not fname:
            return
        path = os.path.join(self.dir, str(fname))
        try:
            os.replace(path, path + ".quarantine")
        except OSError:
            pass

    def load(self, manifest: Dict):
        """Map the manifest's arena → ``(URModel-over-views, info)``.

        The arrays are read-only views into the shared mapping; derived
        serving state (inverted CSRs, pop order) installs straight into
        the model's ``__dict__`` caches, and dictionaries / property
        indexes carry from the previously loaded generation whenever the
        manifest proves them unchanged.  Raises ValueError/OSError on a
        torn arena — the caller quarantines."""
        path = os.path.join(self.dir, str(manifest["file"]))
        arrays, meta = read_arrays(path, mmap=True)
        if meta.get("schema") != 1:
            raise ValueError(f"unknown arena schema {meta.get('schema')}")
        model = self._build_model(arrays, meta)
        info = dict(meta.get("info") or {})
        info["planeGeneration"] = int(meta.get("generation")
                                      or manifest["generation"])
        info["planeBytes"] = int(manifest.get("bytes") or 0)
        return model, info

    def _build_model(self, arrays: Dict[str, np.ndarray], meta: Dict):
        from predictionio_tpu.models.universal_recommender.engine import (
            URModel,
        )

        names = list(meta["eventNames"])
        item_dict = self._restore_dict("item", meta["dicts"]["item"],
                                       arrays)
        user_dict = self._restore_dict("user", meta["dicts"]["user"],
                                       arrays)
        event_item_dicts: Dict[str, IdDict] = {}
        for i, name in enumerate(names):
            entry = meta["dicts"][f"ev_{i}"]
            event_item_dicts[name] = (
                item_dict if entry.get("sameAs") == "item"
                else self._restore_dict(f"ev_{i}", entry, arrays))
        user_seen_by_event = {
            bname: CSRLookup(arrays[f"seen_{j}_indptr"],
                             arrays[f"seen_{j}_values"])
            for j, bname in enumerate(meta["blacklistNames"])}
        prev, prev_meta = self._prev_model, self._prev_meta
        item_crc = meta["dicts"]["item"]["crc"]
        props_carried = (
            prev is not None and prev_meta is not None
            and meta.get("propsCrc") == prev_meta.get("propsCrc")
            and item_crc == prev_meta["dicts"]["item"]["crc"])
        props = (prev.item_properties if props_carried
                 else _LazyProps(arrays.get("props_json")))
        model = URModel(
            primary_event=meta["primaryEvent"],
            item_dict=item_dict,
            user_dict=user_dict,
            indicator_idx={n: arrays[f"ind_{i}_idx"]
                           for i, n in enumerate(names)},
            indicator_llr={n: arrays[f"ind_{i}_llr"]
                           for i, n in enumerate(names)},
            event_item_dicts=event_item_dicts,
            popularity=arrays["popularity"],
            item_properties=props,
            user_seen=CSRLookup(arrays["user_seen_indptr"],
                                arrays["user_seen_values"]),
            user_seen_by_event=user_seen_by_event,
        )
        # derived serving state rides the arena: pre-populate the lazy
        # caches so warm()/first-query find them built (as views)
        model.__dict__["_host_inv"] = {
            n: (arrays[f"inv_{i}_indptr"], arrays[f"inv_{i}_rows"],
                arrays[f"inv_{i}_w"])
            for i, n in enumerate(names)}
        model.__dict__["_host_pop_order"] = arrays["pop_order"]
        if props_carried:
            # the property-derived indexes (value→ids, date arrays,
            # known-name set, date-offset LRU) are functions of
            # (item_dict, item_properties) — both proven unchanged, so
            # whatever THIS worker already built carries forward and
            # rules keep serving without a rebuild
            for attr in ("_prop_value_index", "_prop_date_array",
                         "_known_prop_names", "_date_off"):
                v = prev.__dict__.get(attr)
                if v is not None:
                    model.__dict__[attr] = v
        if prev is not None and prev_meta is not None \
                and item_crc == prev_meta["dicts"]["item"]["crc"]:
            z = prev.__dict__.get("_host_zeros")
            if z is not None:   # read-only by contract; same n_items
                model.__dict__["_host_zeros"] = z
        model.__dict__["_plane_generation"] = int(meta.get("generation", 0))
        self._prev_model, self._prev_meta = model, meta
        return model

    def _restore_dict(self, slot: str, entry: Dict,
                      arrays: Dict[str, np.ndarray]) -> IdDict:
        crc, n = int(entry["crc"]), int(entry["n"])
        cached = self._dict_cache.get(slot)
        if cached is not None and cached[0] == crc \
                and len(cached[1]) == n:
            return cached[1]
        blob = arrays[f"dict_{slot}_blob"]
        offs = arrays[f"dict_{slot}_offs"]
        if cached is not None and entry.get("prevCrc") == cached[0] \
                and entry.get("prevN") == len(cached[1]):
            # publisher proved our dictionary is a byte-prefix of the
            # new blob: extend a clone with only the tail strings
            d = cached[1].clone()
            start = int(entry["prevN"])
            base = int(offs[start])
            tail = bytes(blob[base:])
            for j in range(start, n):
                d.add(tail[int(offs[j]) - base:int(offs[j + 1]) - base]
                      .decode("utf-8", "surrogatepass"))
            self.dicts_extended += 1
        else:
            raw = bytes(blob)
            d = IdDict.from_state(
                [raw[int(offs[j]):int(offs[j + 1])]
                 .decode("utf-8", "surrogatepass") for j in range(n)])
            self.dicts_rebuilt += 1
        self._dict_cache[slot] = (crc, d)
        return d


class PlaneWatcher:
    """Per-worker manifest watcher: polls ``CURRENT.json`` and installs
    each new generation through the server's build-ticket install path.
    ``check_now()`` runs one synchronous check (the ``/reload`` handler
    and the in-process publisher use it so their response generation is
    live before they answer)."""

    def __init__(self, plane: ModelPlane, install,
                 poll_s: Optional[float] = None):
        self.plane = plane
        self.install = install     # callable(models, info) -> bool
        self.poll = poll_s if poll_s is not None else plane_poll_s()
        self.generation = 0
        self._bad_gen = 0
        self._warned_gen = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pio-model-plane-watch")
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll):
            try:
                self.check_now()
            except Exception:
                log.exception("model-plane watch failed; keeping the "
                              "served generation")

    def check_now(self) -> bool:
        """One check-and-install; True when a new generation went live
        on this worker."""
        with self._lock:
            cur = self.plane.current()
            if cur is None:
                return False
            gen = int(cur.get("generation") or 0)
            if gen <= self.generation or gen == self._bad_gen:
                return False
            t0 = time.perf_counter()
            try:
                model, info = self.plane.load(cur)
            except (ValueError, KeyError) as e:
                # deterministic content corruption (torn write): retrying
                # cannot help — quarantine, remember the bad generation
                # (no re-probe storm), serve the old one until the next
                # good publish supersedes it
                self._bad_gen = gen
                self.plane.quarantine(cur, e)
                return False
            except OSError as e:
                # transient I/O (EMFILE under load, a sibling's
                # quarantine rename racing us, mid-GC): do NOT
                # quarantine a possibly-good arena — keep serving and
                # retry on the next poll (log once per generation)
                if self._warned_gen != gen:
                    self._warned_gen = gen
                    log.warning(
                        "model plane: could not map generation %s (%s) "
                        "— keeping the served generation, will retry",
                        gen, e)
                return False
            installed = self.install([model], info)
            # the generation is consumed either way: install() returns
            # False only when a newer build ticket (a later check or the
            # startup private load racing us) already swapped in
            self.generation = gen
            tag = _obs_metrics.worker_tag()
            _M_GEN.set(gen, worker=tag)
            _M_BYTES.set(int(cur.get("bytes") or 0), worker=tag)
            if installed:
                _M_MAP_S.set(time.perf_counter() - t0, worker=tag)
            _obs_metrics.update_process_rss()
            return installed
