from predictionio_tpu.events.event import (  # noqa: F401
    DataMap,
    Event,
    PropertyMap,
    aggregate_properties,
    SET_EVENT,
    UNSET_EVENT,
    DELETE_EVENT,
)
