"""Canonical event model.

Capability parity with the reference's event model
(data/src/main/scala/io/prediction/data/storage/Event.scala, DataMap.scala,
PropertyMap.scala, LEventAggregator.scala — paths per SURVEY.md §2; the
reference mount was empty so citations are path-level):

- ``Event``: entityType/entityId, event verb, optional target entity,
  free-form JSON properties, eventTime, tags, prId, creationTime.
- Special verbs ``$set`` / ``$unset`` / ``$delete`` mutate an entity's
  property snapshot; ``aggregate_properties`` folds an event stream into
  per-entity ``PropertyMap`` snapshots exactly as the reference's
  ``LEventAggregator.aggregateProperties`` does (last-write-wins by
  eventTime, ``$delete`` clears the entity, first-set time kept).
"""

from __future__ import annotations

import datetime as _dt
import json
import os as _os
import threading as _threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Mapping, Optional

SET_EVENT = "$set"
UNSET_EVENT = "$unset"
DELETE_EVENT = "$delete"
SPECIAL_EVENTS = frozenset({SET_EVENT, UNSET_EVENT, DELETE_EVENT})


class _IdPool:
    """Pooled 128-bit random event ids.

    ``os.urandom(16)`` is a getrandom(2) syscall per call — measured
    ~50 µs/event on the ingest path, the single largest per-event cost.
    Drawing 64 KiB per syscall and slicing yields the SAME entropy source
    at <1 µs/id.  Lock-guarded: ids are handed out to concurrent server
    threads.  The pool is discarded in fork children (``register_at_fork``
    below — checked at fork, not per-call: getpid() is itself a measurable
    syscall on sandboxed kernels), so forked workers can never hand out
    overlapping slices of an inherited buffer."""

    _CHUNK = 16 * 4096

    def __init__(self):
        self._lock = _threading.Lock()
        self._buf = b""
        self._off = 0

    def reset(self) -> None:
        with self._lock:
            self._buf = b""
            self._off = 0

    def next_hex(self) -> str:
        with self._lock:
            if self._off + 16 > len(self._buf):
                self._buf = _os.urandom(self._CHUNK)
                self._off = 0
            out = self._buf[self._off:self._off + 16].hex()
            self._off += 16
            return out


_id_pool = _IdPool()
if hasattr(_os, "register_at_fork"):   # absent on non-POSIX
    _os.register_at_fork(after_in_child=_id_pool.reset)


def new_event_id() -> str:
    """A fresh 32-hex-char event id (uuid4-strength randomness, pooled)."""
    return _id_pool.next_hex()


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def parse_time(value: Any) -> _dt.datetime:
    """Parse an ISO-8601 timestamp (the reference accepts joda ISO format)."""
    if value is None:
        return _utcnow()
    if isinstance(value, _dt.datetime):
        if value.tzinfo is None:
            return value.replace(tzinfo=_dt.timezone.utc)
        return value
    if isinstance(value, (int, float)):
        return _dt.datetime.fromtimestamp(value, _dt.timezone.utc)
    s = str(value)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    t = _dt.datetime.fromisoformat(s)
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return t


class DataMap(dict):
    """JSON property bag with typed getters (reference: DataMap.scala).

    Behaves as a plain dict; ``get_as`` raises ``KeyError`` for missing
    required fields like the reference's ``DataMap.get[T]`` and returns the
    default for ``get_opt``-style access.
    """

    def get_as(self, key: str, typ: type) -> Any:
        if key not in self:
            raise KeyError(f"required property '{key}' missing from DataMap")
        v = self[key]
        if typ is float and isinstance(v, (int, float)):
            return float(v)
        if typ is int and isinstance(v, (int, float)) and float(v).is_integer():
            return int(v)
        if not isinstance(v, typ):
            raise TypeError(f"property '{key}'={v!r} is not of type {typ.__name__}")
        return v

    def get_opt(self, key: str, default: Any = None) -> Any:
        return self.get(key, default)


class PropertyMap(DataMap):
    """Entity property snapshot with lifecycle times (reference: PropertyMap.scala)."""

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]] = None,
        first_updated: Optional[_dt.datetime] = None,
        last_updated: Optional[_dt.datetime] = None,
    ):
        super().__init__(fields or {})
        now = _utcnow()
        self.first_updated = first_updated or now
        self.last_updated = last_updated or now


@dataclass
class Event:
    """A single immutable event (reference: Event.scala)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: _dt.datetime = field(default_factory=_utcnow)
    tags: tuple = ()
    pr_id: Optional[str] = None
    event_id: Optional[str] = None
    creation_time: _dt.datetime = field(default_factory=_utcnow)

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            self.properties = DataMap(self.properties)
        self.event_time = parse_time(self.event_time)
        self.creation_time = parse_time(self.creation_time)
        if self.event_id is None:
            # 128 random bits like uuid4().hex, minus the UUID object
            # construction and the per-event getrandom syscall
            self.event_id = new_event_id()
        self._validate()

    def _validate(self):
        if not self.event or not isinstance(self.event, str):
            raise ValueError("event must be a non-empty string")
        # '' is preserved verbatim (batch fast-path parity contract);
        # non-strings would crash the wire encoders downstream
        if not isinstance(self.event_id, str):
            raise ValueError("eventId must be a string")
        if not self.entity_type or self.entity_id is None or self.entity_id == "":
            raise ValueError("entityType and entityId must be non-empty")
        if self.event in SPECIAL_EVENTS:
            # Reference EventValidation: special events must not carry targets.
            if self.target_entity_type or self.target_entity_id:
                raise ValueError(f"{self.event} must not have a target entity")
            if self.event == UNSET_EVENT and not self.properties:
                raise ValueError("$unset requires a non-empty properties map")
        if self.event.startswith("$") and self.event not in SPECIAL_EVENTS:
            raise ValueError(f"unsupported reserved event verb {self.event!r}")

    # -- JSON wire format (reference: EventJson4sSupport.scala) --------------

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": str(self.entity_id),
            "properties": dict(self.properties),
            "eventTime": self.event_time.isoformat(),
            "creationTime": self.creation_time.isoformat(),
        }
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = str(self.target_entity_id)
        if self.tags:
            d["tags"] = list(self.tags)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        return d

    def to_json_line(self) -> str:
        return json.dumps(self.to_json(), separators=(",", ":"), sort_keys=True)

    _WIRE_FIELDS = frozenset({
        "eventId", "event", "entityType", "entityId", "targetEntityType",
        "targetEntityId", "properties", "eventTime", "creationTime",
        "tags", "prId",
    })

    @classmethod
    def from_json(cls, d: Mapping[str, Any]) -> "Event":
        unknown = set(d) - cls._WIRE_FIELDS
        if unknown:
            raise ValueError(f"unknown event fields: {sorted(unknown)}")
        if d.get("entityId") is None:
            raise ValueError("entityType and entityId must be non-empty")
        props = d.get("properties") or {}
        if not isinstance(props, Mapping):
            raise ValueError("properties must be a JSON object")
        return cls(
            event=d["event"],
            entity_type=d["entityType"],
            entity_id=str(d["entityId"]),
            target_entity_type=d.get("targetEntityType"),
            target_entity_id=(
                str(d["targetEntityId"]) if "targetEntityId" in d and d["targetEntityId"] is not None else None
            ),
            properties=DataMap(props),
            event_time=parse_time(d.get("eventTime")),
            tags=tuple(d.get("tags") or ()),
            pr_id=d.get("prId"),
            event_id=d.get("eventId"),
            creation_time=parse_time(d.get("creationTime")) if d.get("creationTime") else _utcnow(),
        )


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Fold $set/$unset/$delete events into per-entity property snapshots.

    Reference: LEventAggregator.aggregateProperties — events are applied in
    eventTime order; ``$set`` merges keys, ``$unset`` removes the named keys,
    ``$delete`` drops the entity snapshot entirely.
    """
    ordered = sorted(events, key=lambda e: (e.event_time, e.creation_time))
    snap: Dict[str, PropertyMap] = {}
    for e in ordered:
        if e.event not in SPECIAL_EVENTS:
            continue
        key = e.entity_id
        if e.event == DELETE_EVENT:
            snap.pop(key, None)
            continue
        cur = snap.get(key)
        if e.event == SET_EVENT:
            if cur is None:
                cur = PropertyMap({}, first_updated=e.event_time, last_updated=e.event_time)
                snap[key] = cur
            cur.update(e.properties)
            cur.last_updated = max(cur.last_updated, e.event_time)
        elif e.event == UNSET_EVENT:
            if cur is None:
                continue
            for k in e.properties:
                cur.pop(k, None)
            cur.last_updated = max(cur.last_updated, e.event_time)
    return snap


def canonical_event_json(d: Mapping[str, Any],
                         now_iso: Optional[str] = None) -> Dict[str, Any]:
    """Validate + canonicalize one wire-format event dict WITHOUT building
    an Event object — the batch-ingest hot path (Event.from_json →
    Event.to_json costs ~70 µs/event in dataclass/datetime round-trips;
    this is ~5×  cheaper and byte-identical: same fields, same coercions,
    same validation as from_json + _validate + to_json).

    ``now_iso`` — a precomputed ``_utcnow().isoformat()`` — fills the
    eventTime/creationTime defaults for group-committed batches: one
    clock read per batch instead of two per event, and every event in a
    commit group shares the group's commit instant.

    Returns the storage/wire dict (eventId and creationTime assigned);
    ``json.dumps(..., separators=(",", ":"), sort_keys=True)`` of it equals
    ``Event.from_json(d).to_json_line()`` for the same eventId and
    creationTime — asserted by tests.
    """
    unknown = set(d) - Event._WIRE_FIELDS
    if unknown:
        raise ValueError(f"unknown event fields: {sorted(unknown)}")
    try:
        event = d["event"]
        entity_type = d["entityType"]
        entity_id = d["entityId"]
    except KeyError as e:
        raise ValueError(f"missing required event field: {e}") from None
    if not event or not isinstance(event, str):
        raise ValueError("event must be a non-empty string")
    if not entity_type or entity_id is None or entity_id == "":
        raise ValueError("entityType and entityId must be non-empty")
    props = d.get("properties") or {}
    # exact-dict fast path first: typing.Mapping's __instancecheck__ walks
    # the ABC machinery (~4 µs), and every wire payload is a plain dict
    if type(props) is not dict and not isinstance(props, Mapping):
        raise ValueError("properties must be a JSON object")
    tet = d.get("targetEntityType")
    tei = d.get("targetEntityId")
    # coerce BEFORE the special-event check, exactly as Event.from_json →
    # _validate does: a numeric-falsy target (0) becomes truthy "0" and must
    # be rejected on $set/$unset/$delete, or the stored line would fail
    # Event.from_json on every subsequent read of the log
    tei_s = str(tei) if tei is not None else None
    if event in SPECIAL_EVENTS:
        if tet or tei_s:
            raise ValueError(f"{event} must not have a target entity")
        if event == UNSET_EVENT and not props:
            raise ValueError("$unset requires a non-empty properties map")
    if event.startswith("$") and event not in SPECIAL_EVENTS:
        raise ValueError(f"unsupported reserved event verb {event!r}")
    eid = d.get("eventId")
    if eid is not None and not isinstance(eid, str):
        # mirror _validate: a non-string id written to the log would crash
        # Event.from_json on every subsequent read of that segment
        raise ValueError("eventId must be a string")
    if now_iso is None:
        now_iso = _utcnow().isoformat()
    out: Dict[str, Any] = {
        # `is None` (not truthiness) to mirror Event.__post_init__ exactly:
        # a client-supplied empty-string eventId is preserved on both paths
        "eventId": eid if eid is not None else new_event_id(),
        "event": event,
        "entityType": entity_type,
        "entityId": str(entity_id),
        "properties": dict(props),
        "eventTime": (parse_time(d["eventTime"]).isoformat()
                      if d.get("eventTime") is not None else now_iso),
        "creationTime": (parse_time(d["creationTime"]).isoformat()
                         if d.get("creationTime") else now_iso),
    }
    if tet is not None:
        out["targetEntityType"] = tet
    if tei is not None:
        out["targetEntityId"] = tei_s
    if d.get("tags"):
        out["tags"] = list(d["tags"])
    if d.get("prId") is not None:
        out["prId"] = d["prId"]
    return out
