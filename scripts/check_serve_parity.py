#!/usr/bin/env python
"""Verify host-tail ≡ device-tail serving parity on a trained UR model.

Trains a small Universal Recommender model on deterministic synthetic
commerce data (two clusters, category properties, availability dates),
then replays a fixed query corpus — user, cold user, item-similarity,
itemSet, hard field filter, field boost, blacklist, dateRange,
currentDate avail/expire, an all-masked query, and a no-match empty
result — through BOTH serve tails (``PIO_UR_SERVE_TAIL=host`` vs
``device``) and through ``serve_batch_predict`` vs serial ``predict``
under each tail, diffing results EXACTLY: same items, same float scores,
same order.

A candidate-pruned phase then replays the corpus through the sparse
host tail (``PIO_UR_SERVE_CANDIDATES=on`` — posting-union candidates,
sliced rule masks, popularity-order backfill merge) serial AND batched,
diffing exact floats against the dense reference.

Then the same corpus goes over HTTP against the event-loop front end —
a live deployed query server — in BOTH wire modes: serial keep-alive
(one request/response at a time) and HTTP/1.1 pipelined (the SDK's
QueryPipeline, every query in flight at once), each replayed under the
candidate-pruned AND the dense tail, diffing the JSON responses exactly
against the in-process reference.  Any divergence — tail math,
candidate pruning, micro-batching, request-loop parsing, response
ordering under pipelining — fails the script.

The host tail's contract is that it is a bit-exact twin of the device
tail (elementwise f32 mask math matches XLA, host_topk_desc reproduces
``lax.top_k``'s tie order), so any diff here is a real divergence, not
float noise.

Exit 0 = every query identical across all paths; 1 = any diff
(printed).  Run standalone (``python scripts/check_serve_parity.py``) or
via the tier-1 suite (tests/test_serve_tail.py wraps it), like
check_metrics_names.py and check_snapshot_integrity.py.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the parity contract is backend-independent; CPU keeps the script fast
# and runnable inside tier-1 (PIO_JAX_PLATFORM survives sitecustomize)
os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")


def build_app():
    import numpy as np

    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    storage = Storage(StorageConfig(
        sources={"MEM": {"type": "memory"}},
        repositories={r: "MEM" for r in ("METADATA", "EVENTDATA",
                                         "MODELDATA")},
    ))
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "parityapp"))
    rng = np.random.default_rng(42)
    e_items = [f"e{i}" for i in range(8)]
    b_items = [f"b{i}" for i in range(8)]
    events = []
    for u in range(40):
        mine = e_items if u < 20 else b_items
        for it in mine:
            if rng.random() < 0.7:
                events.append(Event(
                    event="purchase", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
            if rng.random() < 0.9:
                events.append(Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
    for k, it in enumerate(e_items):
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({
                "category": "electronics",
                "availableDate": "2026-01-01T00:00:00",
                "expireDate": f"2026-0{(k % 6) + 1}-15T00:00:00"})))
    for it in b_items:
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({"category": "books",
                                "availableDate": "2026-02-01T00:00:00"})))
    storage.l_events.insert_batch(events, app_id)
    return storage


def corpus_bodies():
    """The corpus as wire-format JSON bodies — shared verbatim by the
    in-process phase (parsed via query_cls.from_json, exactly what the
    query server does) and the HTTP phases."""
    return [
        {"user": "u2", "num": 6},
        {"user": "u25", "num": 6},
        {"user": "nobody-cold", "num": 5},
        {"item": "e1", "num": 5},
        {"itemSet": ["e0", "e2"], "num": 6},
        {"user": "u3", "num": 6,
         "fields": [{"name": "category", "values": ["books"],
                     "bias": -1}]},
        {"user": "u3", "num": 6,
         "fields": [{"name": "category", "values": ["electronics"],
                     "bias": 4.0}]},
        {"user": "u4", "num": 6, "blacklistItems": ["e0", "e1", "e2"]},
        {"user": "u5", "num": 6,
         "dateRange": {"name": "expireDate",
                       "after": "2026-02-01T00:00:00"}},
        {"user": "u6", "num": 8, "currentDate": "2026-03-01T00:00:00"},
        # all-masked: no item carries this category value → empty result
        {"user": "u7", "num": 6,
         "fields": [{"name": "category", "values": ["no-such-cat"],
                     "bias": -1}]},
        # empty-history user + hard filter (pure backfill under a mask)
        {"user": "ghost", "num": 4,
         "fields": [{"name": "category", "values": ["books"],
                     "bias": -1}]},
    ]


def corpus(query_cls, field_cls):
    return [query_cls.from_json(b) for b in corpus_bodies()]


def canon(result):
    return [(s.item, float(s.score)) for s in result.item_scores]


def canon_http(resp: dict):
    return [(r["item"], float(r["score"])) for r in resp["itemScores"]]


def http_phase(engine, ep, query_cls, storage, reference, problems) -> None:
    """Deploy the trained model behind the event-loop front end and
    replay the corpus in serial-keep-alive and pipelined wire modes;
    responses must match the in-process reference EXACTLY (JSON
    round-trips floats losslessly, so this is float-equality, not
    tolerance)."""
    import http.client
    import json as _json

    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.sdk import EngineClient
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    core_workflow.run_train(engine, ep, engine_id="parity-engine",
                            storage=storage)
    state = QueryServerState(engine, ep, query_cls, "parity-engine", "1",
                             "default", storage=storage)
    httpd = start_server(make_handler(state), "127.0.0.1", 0,
                         background=True)
    port = httpd.server_address[1]
    bodies = corpus_bodies()
    try:
        # the deployed server is in-process, so the per-query env switch
        # flips ITS tail too: each wire mode replays under the dense AND
        # the candidate-pruned tail
        for cand in ("off", "on"):
            os.environ["PIO_UR_SERVE_CANDIDATES"] = cand
            # serial keep-alive: one request/response at a time per socket
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            serial = []
            for body in bodies:
                conn.request("POST", "/queries.json",
                             _json.dumps(body).encode(),
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                payload = r.read()
                if r.status != 200:
                    problems.append(
                        f"http/serial/cand_{cand} HTTP {r.status}: "
                        f"{payload[:200]!r}")
                    return
                serial.append(canon_http(_json.loads(payload)))
            conn.close()
            # pipelined: every query in flight at once on one socket; the
            # event loop must answer strictly in order
            with EngineClient(f"http://127.0.0.1:{port}").pipeline(
                    depth=len(bodies)) as p:
                handles = [p.send_query(body) for body in bodies]
            pipelined = [canon_http(h.result()) for h in handles]
            for name, results in ((f"http/serial/cand_{cand}", serial),
                                  (f"http/pipelined/cand_{cand}",
                                   pipelined)):
                for qi, (got, want) in enumerate(zip(results, reference)):
                    if got != want:
                        problems.append(
                            f"query #{qi} differs on {name} vs "
                            f"in-process:\n  got:  {got}\n  want: {want}")
    finally:
        httpd.shutdown()
        httpd.server_close()


def hotswap_phase(engine, ep, query_cls, storage, problems) -> None:
    """Replay the rules corpus through a LIVE deploy while an embedded
    follow-trainer swaps model generations mid-stream: every response
    must be a valid 200 (zero 5xx — a query must never observe a
    half-swapped model), and once the stream of appends has been folded
    the deployed responses must match a from-scratch retrain over the
    same events EXACTLY."""
    import http.client
    import json as _json
    import threading
    import time as _time

    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    app = storage.apps.get_by_name("parityapp")
    state = QueryServerState(engine, ep, query_cls, "parity-engine", "1",
                             "default", storage=storage)
    follower = state.follower = FollowTrainer(
        engine, ep, "parity-engine", storage=storage, interval=0.05,
        on_publish=state.swap_models, persist=False)
    follower.start()
    httpd = start_server(make_handler(state), "127.0.0.1", 0,
                         background=True)
    port = httpd.server_address[1]
    bodies = corpus_bodies()
    gen_start = state.generation
    errors_5xx = []
    replay_errors = []
    replay_count = [0]
    stop = threading.Event()

    def replay_loop():
        # a transport error mid-swap (reset, half-response) is exactly
        # the failure this phase exists to catch — it must FAIL the
        # phase, not silently kill the replay thread and leave the
        # zero-5xx assertion vacuously true
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            while not stop.is_set():
                for body in bodies:
                    conn.request("POST", "/queries.json",
                                 _json.dumps(body).encode(),
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    payload = r.read()
                    replay_count[0] += 1
                    if r.status >= 500:
                        errors_5xx.append((r.status, payload[:200]))
            conn.close()
        except Exception as e:
            replay_errors.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=replay_loop, daemon=True)
    try:
        t.start()
        # appends forcing folds/swaps while the replay loop is live:
        # fresh users co-purchasing with the electronics cluster
        for k in range(6):
            storage.l_events.insert_batch(
                [Event(event="purchase", entity_type="user",
                       entity_id=f"swapper{k}", target_entity_type="item",
                       target_entity_id=f"e{j}") for j in (0, 1, 2)],
                app.id)
            _time.sleep(0.15)
        deadline = _time.time() + 20
        while _time.time() < deadline and (
                state.generation <= gen_start
                or follower.last_outcome not in ("fold", "idle")):
            _time.sleep(0.05)
        # drain: one more tick's worth so the LAST append is folded
        while _time.time() < deadline and follower.last_outcome != "idle":
            _time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        follower.stop()
    swaps = state.generation - gen_start
    if swaps < 1:
        problems.append("hotswap: follower never swapped a generation "
                        f"(outcome={follower.last_outcome})")
    if errors_5xx:
        problems.append(
            f"hotswap: {len(errors_5xx)} 5xx responses during swaps "
            f"(first: {errors_5xx[0]})")
    if replay_errors:
        problems.append(
            f"hotswap: replay connection died mid-stream after "
            f"{replay_count[0]} responses: {replay_errors[0]}")
    # post-swap exactness: live responses == from-scratch retrain now
    invalidate_staging_cache()
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    ref = engine.train(ep)[0]
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    for qi, body in enumerate(bodies + [{"user": "swapper0", "num": 6}]):
        conn.request("POST", "/queries.json", _json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        payload = r.read()
        if r.status != 200:
            problems.append(f"hotswap: post-swap query #{qi} HTTP "
                            f"{r.status}: {payload[:200]!r}")
            continue
        got = canon_http(_json.loads(payload))
        want = canon(algo.predict(ref, query_cls.from_json(body)))
        if got != want:
            problems.append(
                f"hotswap: query #{qi} differs from the post-swap "
                f"from-scratch model:\n  got:  {got}\n  want: {want}")
    conn.close()
    httpd.shutdown()
    httpd.server_close()
    if not problems:
        print(f"hotswap phase: {swaps} mid-stream generation swaps, "
              "zero 5xx, post-swap responses exactly match a "
              "from-scratch retrain")


def plane_phase(engine, ep, query_cls, storage, problems) -> None:
    """Shared-memory model plane: a publisher server (embedded follower
    emitting every generation into the arena) and a pure-consumer
    sibling share one plane dir — the prefork topology minus process
    isolation (tests/test_model_plane.py covers the real-process
    drill).  The corpus replays over HTTP against the CONSUMER while
    generations hot-swap mid-stream (zero 5xx), then: one /reload on
    the consumer must converge the publisher's server too, and
    post-drain responses from the mapped model must EXACTLY match a
    from-scratch retrain — the ``PIO_MODEL_PLANE=off`` in-process
    oracle the earlier phases established.

    Runs with DELTA ARENAS ON (the default) and a short keyframe
    interval, and asserts the fold stream actually published delta
    generations — the consumer's post-drain parity therefore proves
    delta-composed mapped models bit-exact against the oracle, not just
    full arenas."""
    import http.client
    import json as _json
    import shutil
    import tempfile
    import threading
    import time as _time

    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    plane_tmp = tempfile.mkdtemp(prefix="pio_parity_plane")
    os.environ["PIO_MODEL_PLANE_POLL_S"] = "0.05"
    # delta arenas ON with a short keyframe interval: the fold stream
    # below must cross a keyframe boundary AND publish deltas, so the
    # replay exercises full→delta→keyframe→delta compose transitions
    os.environ.pop("PIO_MODEL_PLANE_DELTA", None)
    os.environ["PIO_MODEL_PLANE_FULL_EVERY"] = "4"
    app = storage.apps.get_by_name("parityapp")
    pub = QueryServerState(engine, ep, query_cls, "parity-engine", "1",
                           "default", storage=storage,
                           plane_dir=plane_tmp)
    sub = QueryServerState(engine, ep, query_cls, "parity-engine", "1",
                           "default", storage=storage,
                           plane_dir=plane_tmp)
    follower = None
    httpd = start_server(make_handler(sub), "127.0.0.1", 0,
                         background=True)
    port = httpd.server_address[1]
    bodies = corpus_bodies()
    errors_5xx: list = []
    replay_errors: list = []
    stop = threading.Event()

    def replay_loop():
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            while not stop.is_set():
                for body in bodies:
                    conn.request("POST", "/queries.json",
                                 _json.dumps(body).encode(),
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    payload = r.read()
                    if r.status >= 500:
                        errors_5xx.append((r.status, payload[:200]))
            conn.close()
        except Exception as e:
            replay_errors.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=replay_loop, daemon=True)
    try:
        pub.plane_publish_initial()
        # one /reload on the consumer converges the sibling BEFORE any
        # folding (a reload publishes the PERSISTED instance — running
        # it after fresh folds would legitimately supersede them with
        # the older trained model, exactly as the build-ticket path
        # does in-process)
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/reload", timeout=20) as r:
            rel = _json.loads(r.read())
        gen = int(rel.get("generation") or 0)
        deadline = _time.time() + 10
        while _time.time() < deadline and pub.plane_generation < gen:
            _time.sleep(0.05)
        if not rel.get("reloaded") or gen < 2 \
                or pub.plane_generation < gen:
            problems.append(
                f"plane: one /reload did not converge the sibling "
                f"(reload={rel}, sibling gen={pub.plane_generation})")
        follower = pub.follower = FollowTrainer(
            engine, ep, "parity-engine", storage=storage, interval=0.05,
            on_publish=pub.plane_publish, persist=False)
        follower.start()
        t.start()
        for k in range(5):
            storage.l_events.insert_batch(
                [Event(event="purchase", entity_type="user",
                       entity_id=f"planeswapper{k}",
                       target_entity_type="item",
                       target_entity_id=f"e{j}") for j in (0, 1, 2)],
                app.id)
            _time.sleep(0.12)
        deadline = _time.time() + 20
        while _time.time() < deadline and not (
                follower.last_outcome == "idle"
                and sub.plane_generation == pub.plane_generation
                and sub.plane_generation > 0):
            _time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        if follower is not None:
            follower.stop()
    if sub.plane_generation < 2:
        problems.append(
            "plane: consumer never converged past the initial "
            f"generation (gen={sub.plane_generation}, "
            f"publisher gen={pub.plane_generation})")
    n_delta = len(list(Path(plane_tmp).glob("gen-*.delta")))
    if n_delta == 0:
        problems.append(
            "plane: no delta generation was published — the phase "
            "validated only full arenas (PIO_MODEL_PLANE_DELTA "
            "regression?)")
    if errors_5xx:
        problems.append(
            f"plane: {len(errors_5xx)} 5xx during mapped-generation "
            f"swaps (first: {errors_5xx[0]})")
    if replay_errors:
        problems.append(
            f"plane: replay connection died: {replay_errors[0]}")
    # post-drain exactness: the mapped model == a from-scratch retrain
    invalidate_staging_cache()
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    ref = engine.train(ep)[0]
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    for qi, body in enumerate(bodies + [{"user": "planeswapper0",
                                         "num": 6}]):
        conn.request("POST", "/queries.json", _json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        payload = r.read()
        if r.status != 200:
            problems.append(f"plane: post-drain query #{qi} HTTP "
                            f"{r.status}: {payload[:200]!r}")
            continue
        got = canon_http(_json.loads(payload))
        want = canon(algo.predict(ref, query_cls.from_json(body)))
        if got != want:
            problems.append(
                f"plane: query #{qi} from the mapped model differs from "
                f"the in-process oracle:\n  got:  {got}\n  want: {want}")
    conn.close()
    httpd.shutdown()
    httpd.server_close()
    pub.stop_auto_reload()
    sub.stop_auto_reload()
    shutil.rmtree(plane_tmp, ignore_errors=True)
    if not problems:
        print(f"plane phase: {sub.plane_generation} mapped generations, "
              "zero 5xx mid-swap, one /reload converged both servers, "
              "post-drain responses exactly match the in-process oracle")


def native_phase(engine, ep, query_cls, storage, problems) -> None:
    """Native data-plane cores (ISSUE-18): the corpus replays over HTTP
    against a live deploy running ``PIO_NATIVE=on`` — native HTTP
    parse/assemble plus the native serve fast lane — while an embedded
    follower swaps generations mid-stream.  Zero 5xx; after the drain
    every response must EXACTLY match the ``PIO_NATIVE=off`` Python
    oracle on a from-scratch retrain.  Skips (loudly, success) when no
    C++ toolchain built the cores — the off path IS the behavior then."""
    import http.client
    import json as _json
    import threading
    import time as _time

    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.native import core as ncore
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    if ncore.lib() is None:
        print("native phase: skipped (no C++ toolchain; PIO_NATIVE=off "
              "Python path is the behavior)")
        return
    saved = os.environ.get("PIO_NATIVE")
    os.environ["PIO_NATIVE"] = "on"
    app = storage.apps.get_by_name("parityapp")
    state = QueryServerState(engine, ep, query_cls, "parity-engine", "1",
                             "default", storage=storage)
    follower = state.follower = FollowTrainer(
        engine, ep, "parity-engine", storage=storage, interval=0.05,
        on_publish=state.swap_models, persist=False)
    follower.start()
    httpd = start_server(make_handler(state), "127.0.0.1", 0,
                         background=True)
    port = httpd.server_address[1]
    bodies = corpus_bodies()
    gen_start = state.generation
    calls0 = ncore._M_CALLS.value(core="http")
    errors_5xx: list = []
    replay_errors: list = []
    stop = threading.Event()

    def replay_loop():
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            while not stop.is_set():
                for body in bodies:
                    conn.request("POST", "/queries.json",
                                 _json.dumps(body).encode(),
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    payload = r.read()
                    if r.status >= 500:
                        errors_5xx.append((r.status, payload[:200]))
            conn.close()
        except Exception as e:
            replay_errors.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=replay_loop, daemon=True)
    try:
        t.start()
        for k in range(4):
            storage.l_events.insert_batch(
                [Event(event="purchase", entity_type="user",
                       entity_id=f"natswapper{k}",
                       target_entity_type="item",
                       target_entity_id=f"e{j}") for j in (0, 1, 2)],
                app.id)
            _time.sleep(0.15)
        deadline = _time.time() + 20
        while _time.time() < deadline and (
                state.generation <= gen_start
                or follower.last_outcome != "idle"):
            _time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        follower.stop()
    swaps = state.generation - gen_start
    if swaps < 1:
        problems.append("native: follower never swapped a generation "
                        f"(outcome={follower.last_outcome})")
    if errors_5xx:
        problems.append(
            f"native: {len(errors_5xx)} 5xx responses with PIO_NATIVE=on "
            f"during swaps (first: {errors_5xx[0]})")
    if replay_errors:
        problems.append(
            f"native: replay connection died: {replay_errors[0]}")
    if ncore._M_CALLS.value(core="http") <= calls0:
        problems.append("native: pio_native_calls_total{core=http} never "
                        "moved — the native lane was dark, the phase "
                        "proved nothing")
    # post-drain exactness: oracle answers computed with the native lane
    # OFF (the Python path), then replayed over HTTP with it ON — the
    # deployed server is in-process, so the env flip governs each side
    invalidate_staging_cache()
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    all_bodies = bodies + [{"user": "natswapper0", "num": 6}]
    os.environ["PIO_NATIVE"] = "off"
    try:
        ref = engine.train(ep)[0]
        algo = URAlgorithm(ep.algorithm_params_list[0][1])
        oracle = [canon(algo.predict(ref, query_cls.from_json(b)))
                  for b in all_bodies]
    finally:
        os.environ["PIO_NATIVE"] = "on"
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    for qi, body in enumerate(all_bodies):
        conn.request("POST", "/queries.json", _json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        payload = r.read()
        if r.status != 200:
            problems.append(f"native: post-drain query #{qi} HTTP "
                            f"{r.status}: {payload[:200]!r}")
            continue
        got = canon_http(_json.loads(payload))
        if got != oracle[qi]:
            problems.append(
                f"native: query #{qi} with PIO_NATIVE=on differs from "
                f"the Python oracle:\n  got:  {got}\n"
                f"  want: {oracle[qi]}")
    conn.close()
    httpd.shutdown()
    httpd.server_close()
    if saved is None:
        os.environ.pop("PIO_NATIVE", None)
    else:
        os.environ["PIO_NATIVE"] = saved
    if not problems:
        print(f"native phase: {swaps} mid-stream generation swaps with "
              "PIO_NATIVE=on, zero 5xx, post-drain responses exactly "
              "match the PIO_NATIVE=off oracle")


def cache_phase(engine, ep, query_cls, storage, problems) -> None:
    """Provenance-invalidated response cache over the live front end:
    the corpus replays against a deployed server with the cache ON while
    an embedded follower swaps generations mid-stream (zero 5xx — a hit
    must never observe a half-swapped model either), then every
    post-drain answer — cached hits included — must be bit-identical to
    the ``PIO_SERVE_CACHE=off`` oracle on the same generation, with the
    online audit (every 3rd hit) recording zero mismatches and the cache
    proven live (hit_count > 0, not vacuously dark)."""
    import http.client
    import json as _json
    import threading
    import time as _time

    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.serve import response_cache as rc
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    saved = {k: os.environ.get(k)
             for k in ("PIO_SERVE_CACHE", "PIO_SERVE_CACHE_AUDIT_N",
                       "PIO_FOLLOW_DENSE_RELLR_BYTES")}
    os.environ.pop("PIO_SERVE_CACHE", None)          # cache ON
    os.environ["PIO_SERVE_CACHE_AUDIT_N"] = "3"      # audit every 3rd hit
    # force the pruned sparse re-LLR at toy scale so folds carry serve
    # provenance exactly as the at-scale regime does
    os.environ["PIO_FOLLOW_DENSE_RELLR_BYTES"] = "1"
    cache = rc.get_cache()
    cache.clear()
    cache.hit_count = cache.miss_count = 0
    audit0 = rc._M_AUDIT.value()
    app = storage.apps.get_by_name("parityapp")
    state = QueryServerState(engine, ep, query_cls, "parity-engine", "1",
                             "default", storage=storage)
    follower = state.follower = FollowTrainer(
        engine, ep, "parity-engine", storage=storage, interval=0.05,
        on_publish=state.swap_models, persist=False)
    follower.start()
    httpd = start_server(make_handler(state), "127.0.0.1", 0,
                         background=True)
    port = httpd.server_address[1]
    bodies = corpus_bodies()
    gen_start = state.generation
    errors_5xx: list = []
    replay_errors: list = []
    stop = threading.Event()

    def replay_loop():
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            while not stop.is_set():
                for body in bodies:
                    conn.request("POST", "/queries.json",
                                 _json.dumps(body).encode(),
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    payload = r.read()
                    if r.status >= 500:
                        errors_5xx.append((r.status, payload[:200]))
            conn.close()
        except Exception as e:
            replay_errors.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=replay_loop, daemon=True)
    try:
        t.start()
        for k in range(4):
            storage.l_events.insert_batch(
                [Event(event="purchase", entity_type="user",
                       entity_id=f"cacheswapper{k}",
                       target_entity_type="item",
                       target_entity_id=f"e{j}") for j in (0, 1, 2)],
                app.id)
            _time.sleep(0.15)
        deadline = _time.time() + 20
        while _time.time() < deadline and (
                state.generation <= gen_start
                or follower.last_outcome != "idle"):
            _time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        follower.stop()
    swaps = state.generation - gen_start
    if swaps < 1:
        problems.append("cache: follower never swapped a generation "
                        f"(outcome={follower.last_outcome})")
    if errors_5xx:
        problems.append(
            f"cache: {len(errors_5xx)} 5xx responses with the cache on "
            f"during swaps (first: {errors_5xx[0]})")
    if replay_errors:
        problems.append(
            f"cache: replay connection died: {replay_errors[0]}")
    # post-drain: fill + hit for every body, each bit-identical to the
    # PIO_SERVE_CACHE=off oracle on the SAME generation (the deployed
    # server is in-process, so the env flip governs its lookups too)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)

    def post(body):
        conn.request("POST", "/queries.json", _json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        payload = r.read()
        if r.status != 200:
            return None, f"HTTP {r.status}: {payload[:200]!r}"
        return canon_http(_json.loads(payload)), None

    for qi, body in enumerate(bodies + [{"user": "cacheswapper0",
                                         "num": 6}]):
        first, err = post(body)
        second = None
        if err is None:
            second, err = post(body)           # warm: a cache hit
        if err is None:
            os.environ["PIO_SERVE_CACHE"] = "off"
            try:
                oracle, err = post(body)
            finally:
                os.environ.pop("PIO_SERVE_CACHE", None)
        if err is not None:
            problems.append(f"cache: post-drain query #{qi} {err}")
            continue
        if first != oracle or second != oracle:
            problems.append(
                f"cache: query #{qi} differs from the cache-off oracle:"
                f"\n  fill: {first}\n  hit:  {second}\n  want: {oracle}")
    conn.close()
    httpd.shutdown()
    httpd.server_close()
    if cache.hit_count == 0:
        problems.append("cache: hit_count stayed 0 — the phase never "
                        "served a cached answer (cache dark?)")
    audit_failures = rc._M_AUDIT.value() - audit0
    if audit_failures:
        problems.append(f"cache: {audit_failures} online audit "
                        "mismatches — a cached answer diverged from the "
                        "recomputed tail")
    cache.clear()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if not problems:
        print(f"cache phase: {swaps} mid-stream swaps with the cache on, "
              f"zero 5xx, {cache.hit_count} hits, fill+hit responses "
              "exactly match the cache-off oracle, zero audit mismatches")


def main() -> int:
    # pin the scorer so both tails consume the IDENTICAL signal array and
    # any diff is attributable to the tail under test
    os.environ["PIO_UR_SERVE_SCORER"] = "host"
    # the tail/wire phases replay repeated corpora through armed servers:
    # keep them measuring the TAILS, not the response cache (which gets
    # its own phase below)
    os.environ["PIO_SERVE_CACHE"] = "off"
    build_app()
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        FieldRule, URAlgorithm, URAlgorithmParams, URDataSourceParams,
    )

    engine = UniversalRecommenderEngine.apply()
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="parityapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="parityapp", mesh_dp=1, max_correlators_per_item=8,
            min_llr=0.0, available_date_name="availableDate",
            expire_date_name="expireDate"))],
    )
    models = engine.train(ep)
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    queries = corpus(URQuery, FieldRule)

    runs = {}
    os.environ["PIO_UR_SERVE_CANDIDATES"] = "off"   # dense phase first
    for tail in ("host", "device"):
        os.environ["PIO_UR_SERVE_TAIL"] = tail
        runs[f"{tail}/serial"] = [canon(algo.predict(model, q))
                                  for q in queries]
        runs[f"{tail}/batch"] = [canon(r) for r in
                                 algo.serve_batch_predict(model, queries)]
    # candidate-pruned phase: the sparse host tail must reproduce the
    # dense reference exactly, serial and micro-batched
    os.environ["PIO_UR_SERVE_TAIL"] = "host"
    os.environ["PIO_UR_SERVE_CANDIDATES"] = "on"
    runs["cand/serial"] = [canon(algo.predict(model, q)) for q in queries]
    runs["cand/batch"] = [canon(r) for r in
                          algo.serve_batch_predict(model, queries)]
    problems = []
    reference = runs["device/serial"]
    some_nonempty = any(reference)
    if not some_nonempty:
        problems.append("corpus produced only empty results — the parity "
                        "check would be vacuous (fixture drift?)")
    for name, results in runs.items():
        for qi, (got, want) in enumerate(zip(results, reference)):
            if got != want:
                problems.append(
                    f"query #{qi} differs on {name} vs device/serial:\n"
                    f"  got:  {got}\n  want: {want}")
    # the all-masked query must be an exact empty result everywhere
    if reference[10] != []:
        problems.append(f"all-masked query returned items: {reference[10]}")
    # HTTP phase against the event-loop front end (host tail — the CPU
    # default a deployed server resolves), serial + pipelined wire modes
    os.environ["PIO_UR_SERVE_TAIL"] = "host"
    from predictionio_tpu.storage.locator import get_storage

    if not problems:
        http_phase(engine, ep, URQuery, get_storage(),
                   runs["host/serial"], problems)
    # hot-swap phase: the same corpus under live mid-stream generation
    # swaps (embedded follow-trainer), then post-swap exactness
    os.environ["PIO_UR_SERVE_CANDIDATES"] = "off"
    if not problems:
        hotswap_phase(engine, ep, URQuery, get_storage(), problems)
    # shared-model-plane phase: mapped read-only generations, live
    # hot-swap through the arena, group-converging /reload — responses
    # must equal the PIO_MODEL_PLANE=off oracle established above
    if not problems:
        plane_phase(engine, ep, URQuery, get_storage(), problems)
    # response-cache phase: the same live-swap drill with the cache ON,
    # hits bit-identical to the cache-off oracle
    if not problems:
        cache_phase(engine, ep, URQuery, get_storage(), problems)
    # native-cores phase: the live-swap drill with PIO_NATIVE=on, then
    # post-drain exactness against the Python oracle
    if not problems:
        native_phase(engine, ep, URQuery, get_storage(), problems)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print(f"ok: {len(queries)} queries × (6 serving paths + "
              "http serial/pipelined × candidates on/off + live "
              "hot-swap phase + model-plane phase + response-cache "
              "phase + native-cores phase) identical (items, scores, "
              "order)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
