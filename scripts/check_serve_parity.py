#!/usr/bin/env python
"""Verify host-tail ≡ device-tail serving parity on a trained UR model.

Trains a small Universal Recommender model on deterministic synthetic
commerce data (two clusters, category properties, availability dates),
then replays a fixed query corpus — user, cold user, item-similarity,
itemSet, hard field filter, field boost, blacklist, dateRange,
currentDate avail/expire, an all-masked query, and a no-match empty
result — through BOTH serve tails (``PIO_UR_SERVE_TAIL=host`` vs
``device``) and through ``serve_batch_predict`` vs serial ``predict``
under each tail, diffing results EXACTLY: same items, same float scores,
same order.

The host tail's contract is that it is a bit-exact twin of the device
tail (elementwise f32 mask math matches XLA, host_topk_desc reproduces
``lax.top_k``'s tie order), so any diff here is a real divergence, not
float noise.

Exit 0 = every query identical across all four paths; 1 = any diff
(printed).  Run standalone (``python scripts/check_serve_parity.py``) or
via the tier-1 suite (tests/test_serve_tail.py wraps it), like
check_metrics_names.py and check_snapshot_integrity.py.
"""

from __future__ import annotations

import os
import sys

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the parity contract is backend-independent; CPU keeps the script fast
# and runnable inside tier-1 (PIO_JAX_PLATFORM survives sitecustomize)
os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")


def build_app():
    import numpy as np

    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    storage = Storage(StorageConfig(
        sources={"MEM": {"type": "memory"}},
        repositories={r: "MEM" for r in ("METADATA", "EVENTDATA",
                                         "MODELDATA")},
    ))
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "parityapp"))
    rng = np.random.default_rng(42)
    e_items = [f"e{i}" for i in range(8)]
    b_items = [f"b{i}" for i in range(8)]
    events = []
    for u in range(40):
        mine = e_items if u < 20 else b_items
        for it in mine:
            if rng.random() < 0.7:
                events.append(Event(
                    event="purchase", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
            if rng.random() < 0.9:
                events.append(Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
    for k, it in enumerate(e_items):
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({
                "category": "electronics",
                "availableDate": "2026-01-01T00:00:00",
                "expireDate": f"2026-0{(k % 6) + 1}-15T00:00:00"})))
    for it in b_items:
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({"category": "books",
                                "availableDate": "2026-02-01T00:00:00"})))
    storage.l_events.insert_batch(events, app_id)
    return storage


def corpus(query_cls, field_cls):
    q = query_cls.from_json
    return [
        q({"user": "u2", "num": 6}),
        q({"user": "u25", "num": 6}),
        q({"user": "nobody-cold", "num": 5}),
        q({"item": "e1", "num": 5}),
        q({"itemSet": ["e0", "e2"], "num": 6}),
        q({"user": "u3", "num": 6,
           "fields": [{"name": "category", "values": ["books"],
                       "bias": -1}]}),
        q({"user": "u3", "num": 6,
           "fields": [{"name": "category", "values": ["electronics"],
                       "bias": 4.0}]}),
        q({"user": "u4", "num": 6, "blacklistItems": ["e0", "e1", "e2"]}),
        q({"user": "u5", "num": 6,
           "dateRange": {"name": "expireDate",
                         "after": "2026-02-01T00:00:00"}}),
        q({"user": "u6", "num": 8, "currentDate": "2026-03-01T00:00:00"}),
        # all-masked: no item carries this category value → empty result
        q({"user": "u7", "num": 6,
           "fields": [{"name": "category", "values": ["no-such-cat"],
                       "bias": -1}]}),
        # empty-history user + hard filter (pure backfill under a mask)
        q({"user": "ghost", "num": 4,
           "fields": [{"name": "category", "values": ["books"],
                       "bias": -1}]}),
    ]


def canon(result):
    return [(s.item, float(s.score)) for s in result.item_scores]


def main() -> int:
    # pin the scorer so both tails consume the IDENTICAL signal array and
    # any diff is attributable to the tail under test
    os.environ["PIO_UR_SERVE_SCORER"] = "host"
    build_app()
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        FieldRule, URAlgorithm, URAlgorithmParams, URDataSourceParams,
    )

    engine = UniversalRecommenderEngine.apply()
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="parityapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="parityapp", mesh_dp=1, max_correlators_per_item=8,
            min_llr=0.0, available_date_name="availableDate",
            expire_date_name="expireDate"))],
    )
    models = engine.train(ep)
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    queries = corpus(URQuery, FieldRule)

    runs = {}
    for tail in ("host", "device"):
        os.environ["PIO_UR_SERVE_TAIL"] = tail
        runs[f"{tail}/serial"] = [canon(algo.predict(model, q))
                                  for q in queries]
        runs[f"{tail}/batch"] = [canon(r) for r in
                                 algo.serve_batch_predict(model, queries)]
    problems = []
    reference = runs["device/serial"]
    some_nonempty = any(reference)
    if not some_nonempty:
        problems.append("corpus produced only empty results — the parity "
                        "check would be vacuous (fixture drift?)")
    for name, results in runs.items():
        for qi, (got, want) in enumerate(zip(results, reference)):
            if got != want:
                problems.append(
                    f"query #{qi} differs on {name} vs device/serial:\n"
                    f"  got:  {got}\n  want: {want}")
    # the all-masked query must be an exact empty result everywhere
    if reference[10] != []:
        problems.append(f"all-masked query returned items: {reference[10]}")
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print(f"ok: {len(queries)} queries × 4 serving paths identical "
              "(items, scores, order)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
