#!/usr/bin/env python
"""End-to-end multi-node plane-replication check.

Builds a shared localfs store, trains a small UR model, then runs a
real three-node topology as separate OS processes on one box:

- a PUBLISHER node: ``pio deploy --follow 0.2 --plane-publish
  127.0.0.1:PORT`` — embedded follower folds live events and publishes
  generations into its node-local plane dir, which the in-process
  ``PlaneReplicator`` streams to subscribers;
- two SUBSCRIBER nodes: ``pio deploy --plane-from 127.0.0.1:PORT`` —
  each lands replicated containers into its OWN node-local plane dir
  and serves them through the unchanged watcher/compose/install path.

Asserts over plain HTTP:

- live folds propagate: after a delta batch, the publisher AND both
  subscribers converge on the same plane generation;
- replication parity (zero staleness): the same queries answered by the
  publisher and by each subscriber return identical documents;
- both subscribers converge to ``complete`` lineage records for the
  folded generation (the lineage dir is shared via the common store, so
  each node's merged view spans the publisher's fold/publish stages and
  every node's install/first_serve hops);
- the PUBLISHER's stitched record reaches ``cluster_complete``: every
  expected subscriber node's lane (repl.recv → repl.land → install →
  first_serve) is present with monotone stage starts, and the record
  carries ``cluster.propagationMs``;
- the federation view (``/cluster/metrics.json``, publisher-only)
  reports BOTH subscriber nodes up; after the kill below the dead node
  stays listed at ``up: false`` instead of vanishing;
- freshness reports the replication role on both sides: the publisher
  lists both subscriber sessions at lag 0, each subscriber reports
  role=subscriber, connected, lag 0;
- a subscriber SIGKILLed mid-stream misses a generation, is dropped by
  the publisher, and on restart RESUMES from its last-acked generation
  (the local manifest) — converging back to zero staleness.

Exit 0 = clean; 1 = any assertion failed (printed).  Run standalone
(``python scripts/check_plane_replication.py``) or via the tier-1 suite
(tests/test_plane_replication.py wraps it).
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")

READY_S = 180.0
CONVERGE_S = 120.0
PROBES = (
    {"user": "u2", "num": 5},
    {"user": "probe0", "num": 5},
    {"user": "u4", "num": 4},
    {"item": "i1", "num": 4},
)


def buy(u: str, i: str):
    from predictionio_tpu.events.event import Event

    return Event(event="purchase", entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def build_store(path: str):
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    storage = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": path}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "replapp"))
    events = [buy(f"u{u}", f"i{it}")
              for u in range(12) for it in range(8) if (u * it + u) % 3]
    storage.l_events.insert_batch(events, app_id)
    return storage, app_id


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_json(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def post_query(base: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        base + "/queries.json", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def wait_generation(base: str, want: int, timeout: float,
                    label: str) -> int:
    """Poll GET / until planeGeneration >= want; returns the value."""
    deadline = time.time() + timeout
    gen = -1
    while time.time() < deadline:
        try:
            _, d = get_json(base, "/", timeout=2)
            gen = int(d.get("planeGeneration") or 0)
            if gen >= want:
                return gen
        except Exception:
            pass
        time.sleep(0.05)
    raise RuntimeError(
        f"{label} never reached plane generation {want} in {timeout}s "
        f"(at {gen})")


def main() -> int:
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_workflow import engine_from_variant

    problems = []
    tmp = tempfile.mkdtemp(prefix="pio-plane-repl-")
    store_path = os.path.join(tmp, "store")
    procs: dict = {}
    bases: dict = {}
    try:
        storage, app_id = build_store(store_path)
        variant = {
            "id": "plane-repl",
            "engineFactory": "predictionio_tpu.models."
                             "universal_recommender."
                             "UniversalRecommenderEngine",
            "datasource": {"params": {
                "appName": "replapp", "eventNames": ["purchase"]}},
            "algorithms": [{"name": "ur", "params": {
                "appName": "replapp", "eventNames": [], "meshDp": 1,
                "maxCorrelatorsPerItem": 8}}],
        }
        engine_json = os.path.join(tmp, "engine.json")
        with open(engine_json, "w") as f:
            json.dump(variant, f)
        _factory, engine, ep = engine_from_variant(variant)
        core_workflow.run_train(engine, ep, engine_id="plane-repl",
                                storage=storage)

        repl_port = free_port()
        base_env = {
            **os.environ,
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": store_path,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
            "PIO_JAX_PLATFORM": "cpu",
            "PIO_MODEL_PLANE": "on",
            "PIO_MODEL_PLANE_POLL_S": "0.1",
            "PIO_PLANE_REPL_PING_S": "0.5",
            "PIO_PLANE_REPL_BACKOFF_S": "0.2",
            "PIO_METRICS_FLUSH_S": "0.25",
            "PIO_CLUSTER_SCRAPE_S": "0.25",
            "PIO_CLUSTER_SCRAPE_TIMEOUT_S": "2",
            # this process appends the live-fold events, so the serving
            # nodes never see notify_append: a per-node history cache
            # would hold per-node-staleness user histories and break the
            # byte-exact parity assertion (the documented multi-process-
            # ingest caveat in serve/history_cache.py)
            "PIO_HISTORY_CACHE": "off",
        }

        def spawn(name: str, extra_args, plane_dir: str):
            port = free_port()
            procs[name] = subprocess.Popen(
                [sys.executable, "-m", "predictionio_tpu.cli.main",
                 "deploy", "--engine-json", engine_json,
                 "--ip", "127.0.0.1", "--port", str(port)] + extra_args,
                env={**base_env,
                     "PIO_MODEL_PLANE_DIR": os.path.join(tmp, plane_dir),
                     # a STABLE cluster-node name per logical node: the
                     # restarted subB must rejoin under the same lane,
                     # not appear as a fourth node (the default stamp is
                     # pid-suffixed)
                     "PIO_CLUSTER_NODE": f"node-{name}"})
            bases[name] = f"http://127.0.0.1:{port}"
            return port

        spawn("pub", ["--follow", "0.2",
                      "--plane-publish", f"127.0.0.1:{repl_port}"],
              "plane-pub")
        for sub in ("subA", "subB"):
            spawn(sub, ["--plane-from", f"127.0.0.1:{repl_port}"],
                  f"plane-{sub}")

        # ready: every node answers and has installed a plane generation
        for name in ("pub", "subA", "subB"):
            deadline = time.time() + READY_S
            while True:
                if procs[name].poll() is not None:
                    raise RuntimeError(
                        f"{name} died during startup "
                        f"(rc {procs[name].returncode})")
                if time.time() > deadline:
                    raise RuntimeError(f"{name} not ready in {READY_S}s")
                try:
                    _, d = get_json(bases[name], "/", timeout=2)
                    if int(d.get("planeGeneration") or 0) >= 1:
                        break
                except Exception:
                    pass
                time.sleep(0.1)
        gref = wait_generation(bases["pub"], 1, 10, "pub")

        # -- live folds propagate cluster-wide ---------------------------
        storage.l_events.insert_batch(
            [buy("probe0", "i1")]
            + [buy(f"cob{j}", "i1") for j in range(6)]
            + [buy(f"cob{j}", "fresh_item") for j in range(6)], app_id)
        gen = wait_generation(bases["pub"], gref + 1, CONVERGE_S, "pub")
        for sub in ("subA", "subB"):
            got = wait_generation(bases[sub], gen, CONVERGE_S, sub)
            if got > gen:
                gen = got   # the fold may have ticked again; re-level
                gen = wait_generation(bases["pub"], gen, CONVERGE_S, "pub")

        # quiesce: no new folds mid-parity (events are drained)
        time.sleep(1.0)
        gen = wait_generation(bases["pub"], gen, 10, "pub")
        for sub in ("subA", "subB"):
            wait_generation(bases[sub], gen, CONVERGE_S, sub)

        # -- replication parity (zero staleness) -------------------------
        for q in PROBES:
            _, ref = post_query(bases["pub"], q)
            for sub in ("subA", "subB"):
                _, got = post_query(bases[sub], q)
                if got != ref:
                    problems.append(
                        f"{sub} answered {q} differently from the "
                        f"publisher: {got} != {ref}")

        # -- complete lineage on both subscribers ------------------------
        for sub in ("subA", "subB"):
            doc = None
            deadline = time.time() + 30
            while time.time() < deadline:
                st, d = get_json(bases[sub], f"/lineage/{gen}.json")
                if st == 200:
                    doc = d
                    if d.get("outcome") == "complete":
                        break
                time.sleep(0.25)
            if doc is None:
                problems.append(f"{sub}: /lineage/{gen}.json never "
                                "answered 200")
                continue
            if doc.get("outcome") != "complete":
                problems.append(
                    f"{sub}: generation {gen} lineage outcome="
                    f"{doc.get('outcome')!r}, expected 'complete'")
            names = {s.get("stage") for s in doc.get("stages", ())}
            for need in ("publish", "plane.write", "install",
                         "first_serve"):
                if need not in names:
                    problems.append(f"{sub}: lineage record missing "
                                    f"stage {need!r}")
            installs = {s.get("worker") for s in doc.get("stages", ())
                        if s.get("stage") == "install"}
            if len(installs) < 3:
                problems.append(
                    f"{sub}: install recorded by {sorted(installs)} — "
                    "expected the publisher and both subscriber nodes")

        # -- the publisher's STITCHED record: cluster_complete with a
        #    monotone per-node lane (repl.recv -> repl.land -> install
        #    -> first_serve) for BOTH subscriber nodes -------------------
        LANE_ORDER = ("repl.recv", "repl.land", "install", "first_serve")
        doc = None
        deadline = time.time() + 30
        while time.time() < deadline:
            st, d = get_json(bases["pub"], f"/lineage/{gen}.json")
            if st == 200:
                doc = d
                if d.get("outcome") == "cluster_complete":
                    break
            time.sleep(0.25)
        if doc is None or doc.get("outcome") != "cluster_complete":
            problems.append(
                f"pub: generation {gen} stitched record outcome="
                f"{(doc or {}).get('outcome')!r}, expected "
                f"'cluster_complete' (cluster="
                f"{(doc or {}).get('cluster')!r})")
        else:
            cl = doc.get("cluster") or {}
            if sorted(cl.get("expected") or []) != \
                    ["node-subA", "node-subB"]:
                problems.append(
                    f"pub: stitched record expects {cl.get('expected')}, "
                    "wanted both subscriber nodes")
            if not cl.get("propagationMs"):
                problems.append(
                    f"pub: cluster_complete record without "
                    f"propagationMs: {cl!r}")
            for node in ("node-subA", "node-subB"):
                starts = {}
                for s in doc.get("stages", ()):
                    if s.get("node") == node and \
                            s.get("stage") in LANE_ORDER:
                        starts.setdefault(s["stage"],
                                          float(s.get("start") or 0))
                missing = [n for n in LANE_ORDER if n not in starts]
                if missing:
                    problems.append(
                        f"pub: stitched lane for {node} missing "
                        f"{missing} (has {sorted(starts)})")
                    continue
                seq = [starts[n] for n in LANE_ORDER]
                if seq != sorted(seq):
                    problems.append(
                        f"pub: {node} lane stage starts not monotone: "
                        + ", ".join(f"{n}={starts[n]:.6f}"
                                    for n in LANE_ORDER))

        # -- federation: every subscriber node up on the publisher -------
        cl_doc = None
        deadline = time.time() + 20
        while time.time() < deadline:
            st, d = get_json(bases["pub"], "/cluster/metrics.json")
            if st == 200:
                cl_doc = d
                nodes = d.get("nodes") or {}
                # the scraped view lags by one tsdb sample: wait for
                # up-ness AND the converged generation to show through
                if len(nodes) >= 2 and all(
                        n.get("up") and n.get("generation") == gen
                        for n in nodes.values()):
                    break
            time.sleep(0.25)
        nodes = (cl_doc or {}).get("nodes") or {}
        if sorted(nodes) != ["node-subA", "node-subB"]:
            problems.append(
                f"pub /cluster/metrics.json lists {sorted(nodes)}, "
                "expected both subscriber nodes")
        for nm, st_ in nodes.items():
            if not st_.get("up"):
                problems.append(
                    f"pub /cluster/metrics.json: {nm} not up: "
                    f"{st_.get('error')!r}")
            elif st_.get("generation") != gen:
                problems.append(
                    f"pub /cluster/metrics.json: {nm} at generation "
                    f"{st_.get('generation')}, cluster is at {gen}")

        # -- freshness reports the replication role ----------------------
        _, stats = get_json(bases["pub"], "/stats.json")
        rep = (stats.get("freshness") or {}).get("replication") or {}
        if rep.get("role") != "publisher":
            problems.append(f"publisher freshness.replication={rep!r}")
        else:
            subs = rep.get("subscribers") or []
            if len(subs) != 2:
                problems.append(
                    f"publisher reports {len(subs)} subscribers, "
                    "expected 2")
            elif any(s.get("lagGenerations") for s in subs):
                problems.append(
                    f"subscriber lag nonzero after convergence: {subs}")
        for sub in ("subA", "subB"):
            _, stats = get_json(bases[sub], "/stats.json")
            rep = (stats.get("freshness") or {}).get("replication") or {}
            if (rep.get("role") != "subscriber"
                    or not rep.get("connected")
                    or rep.get("lagGenerations")):
                problems.append(
                    f"{sub} freshness.replication={rep!r} — expected "
                    "connected subscriber at lag 0")

        # -- kill one subscriber mid-stream, re-sync with zero staleness -
        procs["subB"].send_signal(signal.SIGKILL)
        procs["subB"].wait(timeout=15)
        storage.l_events.insert_batch(
            [buy(f"cob{j}", "fresh_item2") for j in range(6)]
            + [buy(f"cob{j}", "i2") for j in range(6)], app_id)
        gen2 = wait_generation(bases["pub"], gen + 1, CONVERGE_S, "pub")
        wait_generation(bases["subA"], gen2, CONVERGE_S, "subA")
        # the dead node must stay LISTED at up=false, not vanish
        deadline = time.time() + 20
        dead_seen = False
        while time.time() < deadline:
            st, d = get_json(bases["pub"], "/cluster/metrics.json")
            nodes = (d or {}).get("nodes") or {}
            if st == 200 and "node-subB" in nodes \
                    and not nodes["node-subB"].get("up"):
                dead_seen = True
                break
            time.sleep(0.25)
        if not dead_seen:
            problems.append(
                "pub /cluster/metrics.json never reported the killed "
                "node-subB as up=false (it must stay visible, stale-"
                f"flagged): {sorted(nodes)}")
        # restart B on the SAME plane dir + port: its first sync frame
        # must carry have=<last flipped generation> (resume, not cold)
        portB = int(bases["subB"].rsplit(":", 1)[1])
        procs["subB"] = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli.main",
             "deploy", "--engine-json", engine_json,
             "--ip", "127.0.0.1", "--port", str(portB),
             "--plane-from", f"127.0.0.1:{repl_port}"],
            env={**base_env,
                 "PIO_MODEL_PLANE_DIR": os.path.join(tmp, "plane-subB"),
                 "PIO_CLUSTER_NODE": "node-subB"})
        # settle on the publisher's CURRENT generation (folds may have
        # ticked during the restart), then re-assert parity everywhere
        gen2 = wait_generation(bases["pub"], gen2, 10, "pub")
        time.sleep(1.0)
        gen2 = wait_generation(bases["pub"], gen2, 10, "pub")
        for sub in ("subA", "subB"):
            wait_generation(bases[sub], gen2, CONVERGE_S, sub)
        for q in PROBES + ({"user": "cob1", "num": 5},):
            _, ref = post_query(bases["pub"], q)
            for sub in ("subA", "subB"):
                _, got = post_query(bases[sub], q)
                if got != ref:
                    problems.append(
                        f"{sub} stale after kill/re-sync on {q}: "
                        f"{got} != {ref}")
        _, stats = get_json(bases["subB"], "/stats.json")
        rep = (stats.get("freshness") or {}).get("replication") or {}
        if rep.get("lagGenerations"):
            problems.append(
                f"subB lag nonzero after re-sync: {rep!r}")
    except Exception as e:  # noqa: BLE001 - the harness wants one rc
        problems.append(f"replication check aborted: {e!r}")
    finally:
        for name, proc in procs.items():
            base = bases.get(name)
            if proc.poll() is None and base:
                try:
                    with urllib.request.urlopen(base + "/stop",
                                                timeout=5) as r:
                        r.read()
                except Exception:
                    pass
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        from predictionio_tpu.storage.locator import set_storage

        set_storage(None)
        shutil.rmtree(tmp, ignore_errors=True)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print("ok: publisher + 2 subscribers converged (live folds, "
              "complete lineage on both subscriber nodes, stitched "
              "cluster_complete record with monotone per-node lanes, "
              "federation reporting every node up, byte-equal "
              "responses), SIGKILLed subscriber stayed visible as "
              "up=false and resumed from its last-acked generation "
              "with zero staleness")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
