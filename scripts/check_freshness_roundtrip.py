#!/usr/bin/env python
"""End-to-end streaming-freshness roundtrip check.

Builds a localfs store, trains a small UR model, deploys it behind the
event-loop front end with an EMBEDDED follow-trainer (the
``pio deploy --follow`` path), then over several rounds:

1. appends events through the storage layer: co-buyers purchase a seed
   item the probe user already owns PLUS a BRAND-NEW item — invisible
   to any stale model, since the recommendable catalog comes from the
   model (serving history comes from the live store, so an own-purchase
   probe would reflect even without a fold — the new-item probe cannot);
2. waits for the follower to fold them (polls the HTTP /stats.json
   ``freshness`` key — generation, covered events — the SDK contract)
   and records the append→reflected wall latency;
3. asserts exact parity: the deployed model's responses for a fixed
   probe corpus are identical — same items, same float scores, same
   order — to a from-scratch ``engine.train`` over the same events.

Draining is DETERMINISTIC: the script tracks how many events it
inserted and waits until ``freshness.follower.coveredEvents`` reaches
that count with an idle outcome — a bare "idle" can be a tick that ran
before an append became visible (a race this script used to lose under
CPU contention).

Any 5xx anywhere, a fold that never lands, or a single float of
divergence fails the script.  Exit 0 = clean.  Run standalone
(``python scripts/check_freshness_roundtrip.py``) or via the tier-1
suite (tests/test_streaming_follow.py wraps it).

Modes:

- default: 12-user / 8-item shape, 3 rounds.
- ``--storage sharded [--shards N]``: the same roundtrip over the
  sharded, replicated event store — the proof that delta staging and
  ``pio deploy --follow`` work unchanged when events are
  hash-partitioned.
- ``--large``: the large-catalog smoke (PR 11 tentpole gate): a
  4000-item catalog under a deliberately small
  PIO_FOLLOW_STATE_BYTES=32MiB budget.  The legacy dense fold state
  (4000² × 4 B = 64 MiB per event type) would demote to
  retrain-per-tick; the sorted-COO sparse state must stay in fold mode
  (asserted via ``freshness.follower.stateMode == "sparse"`` and
  ``mode == "fold"``), reflect an append, and keep exact parity.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")
os.environ.setdefault("PIO_UR_SERVE_SCORER", "host")

ROUNDS = 3
WAIT_S = 20.0

STORAGE_TYPE = "localfs"
SHARDS = 2
LARGE = "--large" in sys.argv
if "--storage" in sys.argv:
    STORAGE_TYPE = sys.argv[sys.argv.index("--storage") + 1]
if "--shards" in sys.argv:
    SHARDS = int(sys.argv[sys.argv.index("--shards") + 1])
if STORAGE_TYPE == "sharded" and SHARDS > 1:
    # the parallel-path assertion at the end reads the workers gauge;
    # pin the pool width (capped at the shard count anyway) so a
    # single-core CI host doesn't legitimately default to 1 and fail
    os.environ.setdefault("PIO_SCAN_WORKERS", "2")

# the large smoke pins the budget low enough that the DENSE state could
# not hold this catalog (I² × 4 B = 64 MiB > 32 MiB) while the sparse
# state (O(nnz)) fits with room to spare
LARGE_ITEMS = 4000
LARGE_BUDGET = 32 << 20
if LARGE:
    ROUNDS = 2
    os.environ["PIO_FOLLOW_STATE_BYTES"] = str(LARGE_BUDGET)


def buy(u: str, i: str):
    from predictionio_tpu.events.event import Event

    return Event(event="purchase", entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def seed_events():
    if LARGE:
        # one purchase per item puts all LARGE_ITEMS in the catalog;
        # u0..u99 each own a 40-item slice, so cross-joins stay tiny
        evs = [buy(f"u{k % 100}", f"i{k}") for k in range(LARGE_ITEMS)]
        # a correlated cluster for the probe rounds
        evs += [buy(f"u{u}", f"i{it}") for u in range(12)
                for it in range(8) if (u * it + u) % 3]
        return evs
    return [buy(f"u{u}", f"i{it}")
            for u in range(12) for it in range(8) if (u * it + u) % 3]


def build_store(path: str):
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    src = {"type": STORAGE_TYPE, "path": path}
    if STORAGE_TYPE == "sharded":
        src["shards"] = str(SHARDS)
    storage = Storage(StorageConfig(
        sources={"FS": src},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "freshapp"))
    events = seed_events()
    for s in range(0, len(events), 5000):
        storage.l_events.insert_batch(events[s:s + 5000], app_id)
    return storage, app_id, len(events)


def canon(doc: dict):
    return [(r["item"], float(r["score"])) for r in doc["itemScores"]]


def main() -> int:
    import http.client

    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm, URAlgorithmParams, URDataSourceParams,
    )
    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    tmp = tempfile.mkdtemp(prefix="pio-fresh-")
    problems = []
    httpd = None
    follower = None
    try:
        storage, app_id, n_events = build_store(tmp)
        engine = UniversalRecommenderEngine.apply()
        ap = URAlgorithmParams(app_name="freshapp", mesh_dp=1,
                               max_correlators_per_item=8)
        ep = EngineParams(
            data_source_params=URDataSourceParams(
                app_name="freshapp", event_names=["purchase"]),
            algorithm_params_list=[("ur", ap)])
        core_workflow.run_train(engine, ep, engine_id="fresh-engine",
                                storage=storage)
        state = QueryServerState(
            engine, ep, UniversalRecommenderEngine.query_class,
            "fresh-engine", "1", "default", storage=storage)
        follower = state.follower = FollowTrainer(
            engine, ep, "fresh-engine", storage=storage, interval=0.1,
            on_publish=state.swap_models, persist=False)
        if follower.mode != "fold":
            problems.append(f"follower resolved mode={follower.mode}, "
                            "expected fold on a localfs UR deployment")
        follower.start()
        httpd = start_server(make_handler(state), "127.0.0.1", 0,
                             background=True)
        port = httpd.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)

        def http_json(method, path, body=None):
            conn.request(method, path,
                         json.dumps(body).encode() if body else None,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            payload = r.read()
            if r.status >= 500:
                problems.append(f"{method} {path}: HTTP {r.status} "
                                f"{payload[:200]!r}")
            return r.status, json.loads(payload)

        def follower_stats():
            _, stats = http_json("GET", "/stats.json")
            return stats.get("freshness", {}).get("follower", {})

        def drain(timeout: float = WAIT_S) -> bool:
            """Wait until the follower's resident state covers EVERY
            event this script inserted AND the last tick found nothing
            new — deterministic, unlike a bare lastOutcome poll."""
            end = time.time() + timeout
            while time.time() < end:
                fr = follower_stats()
                covered = fr.get("coveredEvents")
                caught_up = covered is None or covered >= n_events
                if caught_up and fr.get("lastOutcome") in ("idle",
                                                           "disabled"):
                    return True
                time.sleep(0.02)
            return False

        latencies = []
        algo = URAlgorithm(ap)
        if not drain():
            problems.append("follower never drained after bootstrap "
                            f"(outcome={follower.last_outcome})")
        if LARGE:
            fr = follower_stats()
            if fr.get("mode") != "fold":
                problems.append(
                    f"large-catalog: follower demoted to {fr.get('mode')} "
                    f"under PIO_FOLLOW_STATE_BYTES={LARGE_BUDGET} — the "
                    "sparse state must hold fold mode here")
            if fr.get("stateMode") != "sparse":
                problems.append(
                    f"large-catalog: stateMode={fr.get('stateMode')}, "
                    "expected sparse")
            sb = fr.get("stateBytes") or 0
            dense_equiv = LARGE_ITEMS * LARGE_ITEMS * 4
            if not 0 < sb <= LARGE_BUDGET:
                problems.append(
                    f"large-catalog: stateBytes={sb} outside "
                    f"(0, {LARGE_BUDGET}]")
            if dense_equiv <= LARGE_BUDGET:
                problems.append("large-catalog smoke misconfigured: the "
                                "dense state would also fit the budget")
        for rnd in range(ROUNDS):
            seed_item = "i1"
            new_item = f"fresh_item_{rnd}"
            probe_user = f"probe{rnd}"
            # the probe user's history holds seed_item BEFORE the round,
            # so reflection == the brand-new co-occurring item appearing
            # in their response — impossible on any stale model, whose
            # catalog cannot contain new_item
            storage.l_events.insert_batch([buy(probe_user, seed_item)],
                                          app_id)
            n_events += 1
            drain()
            t0 = time.time()
            cobuyers = [f"cob{rnd}_{j}" for j in range(6)]
            storage.l_events.insert_batch(
                [buy(u, seed_item) for u in cobuyers]
                + [buy(u, new_item) for u in cobuyers], app_id)
            n_events += 12
            reflected = None
            while time.time() - t0 < WAIT_S:
                st, doc = http_json("POST", "/queries.json",
                                    {"user": probe_user, "num": 30})
                if st == 200 and any(r["item"] == new_item
                                     for r in doc["itemScores"]):
                    reflected = time.time() - t0
                    break
                time.sleep(0.02)
            if reflected is None:
                problems.append(
                    f"round {rnd}: append not reflected within {WAIT_S}s "
                    f"(follower outcome={follower.last_outcome})")
                break
            latencies.append(reflected)
            # the new-item proof covers the append's visibility; drain so
            # the parity model covers the whole batch before comparing
            # vs a from-scratch retrain over the same events
            if not drain():
                problems.append(f"round {rnd}: drain after append timed "
                                "out")
            invalidate_staging_cache()
            ref = engine.train(ep)[0]
            probes = ([{"user": f"u{u}", "num": 6} for u in range(0, 12, 3)]
                      + [{"user": probe_user, "num": 5},
                         {"user": "nobody", "num": 4},
                         {"item": "i2", "num": 5}])
            for body in probes:
                st, doc = http_json("POST", "/queries.json", body)
                if st != 200:
                    problems.append(f"round {rnd}: probe {body} HTTP {st}")
                    continue
                want = [(s.item, float(s.score)) for s in algo.predict(
                    ref, URQuery.from_json(body)).item_scores]
                got = canon(doc)
                if got != want:
                    problems.append(
                        f"round {rnd}: probe {body} diverges from "
                        f"from-scratch retrain:\n  got:  {got}\n"
                        f"  want: {want}")
        if LARGE and not problems:
            # pruned re-LLR + incremental emit engagement (ISSUE 13): a
            # brand-new user buying an EXISTING item bumps N — Dunning
            # G² couples every cell to N, so this is exactly the full
            # re-LLR the selection-stability certificate prunes — then
            # the counters must show certified rows and carried/patched
            # serving-state emits, with parity still exact below
            from predictionio_tpu.obs.metrics import get_registry

            reg = get_registry()
            cert0 = reg.counter("pio_follow_rellr_rows_total",
                                "x").value(outcome="certified")
            storage.l_events.insert_batch(
                [buy("nbump_user", "i1")], app_id)
            n_events += 1
            if not drain():
                problems.append("large-catalog: N-bump round never "
                                "drained")
            cert = reg.counter("pio_follow_rellr_rows_total",
                               "x").value(outcome="certified")
            if not cert > cert0:
                problems.append(
                    "large-catalog: the pruned re-LLR certified no rows "
                    f"on an N-bump fold (certified {cert0} -> {cert}) — "
                    "certification is not engaging")
            emit_inc = 0.0
            for comp in ("inverted", "pop_order", "popularity",
                         "user_seen", "seen_by_event", "props"):
                for path in ("carried", "patched"):
                    emit_inc += reg.counter(
                        "pio_follow_emit_total",
                        "x").value(component=comp, path=path)
            if not emit_inc > 0:
                problems.append(
                    "large-catalog: no incremental serving-state emit "
                    "engaged (pio_follow_emit_total carried/patched all "
                    "zero)")
            invalidate_staging_cache()
            ref = engine.train(ep)
            for body in [{"user": "u1", "num": 6},
                         {"user": "nbump_user", "num": 5}]:
                st, doc = http_json("POST", "/queries.json", body)
                want = [(s.item, float(s.score)) for s in algo.predict(
                    ref[0], URQuery.from_json(body)).item_scores]
                if st != 200 or canon(doc) != want:
                    problems.append(
                        f"large-catalog: post-N-bump probe {body} "
                        "diverges from the from-scratch retrain")
        conn.close()
        if STORAGE_TYPE == "sharded" and SHARDS > 1:
            # the roundtrip must have exercised the PARALLEL cross-shard
            # scan pipeline, not a silent serial fallback: every merged
            # scan records its pool width on the workers gauge
            from predictionio_tpu.storage.sharded import _M_SCAN_WORKERS

            w = _M_SCAN_WORKERS.value()
            if w <= 1:
                problems.append(
                    f"sharded roundtrip ran with scan workers={w:g} — the "
                    "parallel cross-shard scan pipeline was not exercised "
                    "(PIO_SCAN_WORKERS forced to 1, or a 1-core fallback)")
        if not problems:
            lat = ", ".join(f"{v * 1e3:.0f}ms" for v in latencies)
            extra = ""
            if LARGE:
                extra = (f", {LARGE_ITEMS}-item catalog held fold mode "
                         f"sparse under a {LARGE_BUDGET >> 20} MiB budget")
            print(f"ok: {ROUNDS} append→fold→reflected rounds "
                  f"(latencies {lat}), responses exactly equal a "
                  f"from-scratch retrain each round, zero 5xx{extra}")
    finally:
        if follower is not None:
            follower.stop()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        shutil.rmtree(tmp, ignore_errors=True)
        from predictionio_tpu.storage.locator import set_storage

        set_storage(None)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
