#!/usr/bin/env python
"""End-to-end streaming-freshness roundtrip check.

Builds a localfs store, trains a small UR model, deploys it behind the
event-loop front end with an EMBEDDED follow-trainer (the
``pio deploy --follow`` path), then over several rounds:

1. appends events through the storage layer (a brand-new user's
   purchases — invisible to any stale model);
2. waits for the follower to fold them (polls the HTTP /stats.json
   ``freshness.generation`` counter — the SDK's contract);
3. asserts the live HTTP /queries.json response REFLECTS the append
   (the new user gets personalized signal scores, not just backfill)
   and records the append→reflected wall latency;
4. asserts exact parity: the deployed model's responses for a fixed
   probe corpus are identical — same items, same float scores, same
   order — to a from-scratch ``engine.train`` over the same events.

Any 5xx anywhere, a fold that never lands, or a single float of
divergence fails the script.  Exit 0 = clean.  Run standalone
(``python scripts/check_freshness_roundtrip.py``) or via the tier-1
suite (tests/test_streaming_follow.py wraps it).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")
os.environ.setdefault("PIO_UR_SERVE_SCORER", "host")

ROUNDS = 3
WAIT_S = 20.0

# --storage sharded [--shards N] runs the same roundtrip over the
# sharded, replicated event store — the proof that delta staging and
# `pio deploy --follow` work unchanged when events are hash-partitioned
STORAGE_TYPE = "localfs"
SHARDS = 2
if "--storage" in sys.argv:
    STORAGE_TYPE = sys.argv[sys.argv.index("--storage") + 1]
if "--shards" in sys.argv:
    SHARDS = int(sys.argv[sys.argv.index("--shards") + 1])


def buy(u: str, i: str):
    from predictionio_tpu.events.event import Event

    return Event(event="purchase", entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def build_store(path: str):
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    src = {"type": STORAGE_TYPE, "path": path}
    if STORAGE_TYPE == "sharded":
        src["shards"] = str(SHARDS)
    storage = Storage(StorageConfig(
        sources={"FS": src},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "freshapp"))
    events = [buy(f"u{u}", f"i{it}")
              for u in range(12) for it in range(8) if (u * it + u) % 3]
    storage.l_events.insert_batch(events, app_id)
    return storage, app_id


def canon(doc: dict):
    return [(r["item"], float(r["score"])) for r in doc["itemScores"]]


def main() -> int:
    import http.client

    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm, URAlgorithmParams, URDataSourceParams,
    )
    from predictionio_tpu.api.http_util import start_server
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import (
        QueryServerState, make_handler,
    )

    tmp = tempfile.mkdtemp(prefix="pio-fresh-")
    problems = []
    httpd = None
    follower = None
    try:
        storage, app_id = build_store(tmp)
        engine = UniversalRecommenderEngine.apply()
        ap = URAlgorithmParams(app_name="freshapp", mesh_dp=1,
                               max_correlators_per_item=8)
        ep = EngineParams(
            data_source_params=URDataSourceParams(
                app_name="freshapp", event_names=["purchase"]),
            algorithm_params_list=[("ur", ap)])
        core_workflow.run_train(engine, ep, engine_id="fresh-engine",
                                storage=storage)
        state = QueryServerState(
            engine, ep, UniversalRecommenderEngine.query_class,
            "fresh-engine", "1", "default", storage=storage)
        follower = state.follower = FollowTrainer(
            engine, ep, "fresh-engine", storage=storage, interval=0.1,
            on_publish=state.swap_models, persist=False)
        if follower.mode != "fold":
            problems.append(f"follower resolved mode={follower.mode}, "
                            "expected fold on a localfs UR deployment")
        follower.start()
        httpd = start_server(make_handler(state), "127.0.0.1", 0,
                             background=True)
        port = httpd.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)

        def http_json(method, path, body=None):
            conn.request(method, path,
                         json.dumps(body).encode() if body else None,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            payload = r.read()
            if r.status >= 500:
                problems.append(f"{method} {path}: HTTP {r.status} "
                                f"{payload[:200]!r}")
            return r.status, json.loads(payload)

        def drain(timeout: float = WAIT_S) -> bool:
            """Wait for the follower to fold everything pending (a tick
            that found nothing new)."""
            end = time.time() + timeout
            while time.time() < end:
                _, stats = http_json("GET", "/stats.json")
                fr = stats.get("freshness", {}).get("follower", {})
                if fr.get("lastOutcome") in ("idle", "disabled"):
                    return True
                time.sleep(0.02)
            return False

        latencies = []
        algo = URAlgorithm(ap)
        if not drain():
            problems.append("follower never drained after bootstrap "
                            f"(outcome={follower.last_outcome})")
        for rnd in range(ROUNDS):
            fresh_user = f"fresh{rnd}"
            t0 = time.time()
            storage.l_events.insert_batch(
                [buy(fresh_user, "i1"), buy(fresh_user, "i2")], app_id)
            reflected = None
            while time.time() - t0 < WAIT_S:
                st, doc = http_json("POST", "/queries.json",
                                    {"user": fresh_user, "num": 5})
                # reflection == the fresh user's own purchase (i1, top
                # of every stale model's backfill) DISAPPEARING from
                # their response via the own-purchase blacklist — a
                # model that hasn't folded this append cannot produce
                # that.  (A positive score or a generation bump can't
                # tell: backfill scores are positive for unknown users,
                # and the bootstrap publish can race the first append.)
                if st == 200 and all(r["item"] != "i1"
                                     for r in doc["itemScores"]):
                    reflected = time.time() - t0
                    break
                time.sleep(0.02)
            if reflected is None:
                problems.append(
                    f"round {rnd}: append not reflected within {WAIT_S}s "
                    f"(follower outcome={follower.last_outcome})")
                break
            latencies.append(reflected)
            # the i1-blacklist proof covers the append's first event;
            # drain so the parity model covers the whole batch before
            # comparing vs a from-scratch retrain over the same events
            drain()
            invalidate_staging_cache()
            ref = engine.train(ep)[0]
            probes = ([{"user": f"u{u}", "num": 6} for u in range(0, 12, 3)]
                      + [{"user": fresh_user, "num": 5},
                         {"user": "nobody", "num": 4},
                         {"item": "i2", "num": 5}])
            for body in probes:
                st, doc = http_json("POST", "/queries.json", body)
                if st != 200:
                    problems.append(f"round {rnd}: probe {body} HTTP {st}")
                    continue
                want = [(s.item, float(s.score)) for s in algo.predict(
                    ref, URQuery.from_json(body)).item_scores]
                got = canon(doc)
                if got != want:
                    problems.append(
                        f"round {rnd}: probe {body} diverges from "
                        f"from-scratch retrain:\n  got:  {got}\n"
                        f"  want: {want}")
        conn.close()
        if not problems:
            lat = ", ".join(f"{v * 1e3:.0f}ms" for v in latencies)
            print(f"ok: {ROUNDS} append→fold→reflected rounds "
                  f"(latencies {lat}), responses exactly equal a "
                  "from-scratch retrain each round, zero 5xx")
    finally:
        if follower is not None:
            follower.stop()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        shutil.rmtree(tmp, ignore_errors=True)
        from predictionio_tpu.storage.locator import set_storage

        set_storage(None)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
