#!/usr/bin/env python
"""End-to-end generation-lineage roundtrip check.

Builds a localfs store, trains a small UR model, then deploys it as a
REAL ``pio deploy --workers 2 --follow`` prefork group in model-plane
mode — so the process that OPENS each lineage record (the dedicated
plane publisher, tag ``pub-*``) is never one of the processes that
serve ``/lineage.json`` (tags ``w0-*``/``w1-*``).  Appends a delta,
waits for the fold to converge every worker, makes sure BOTH workers
answered a query on the new generation, then asserts over plain HTTP:

- ``/lineage.json`` indexes the folded generation and the serving
  worker's tag differs from the record's origin (the cross-process
  proof: a worker that did not produce the generation can explain it);
- ``/lineage/<gen>.json`` returns the merged record with outcome
  ``complete``: the publisher-side stages (append_observed, fold.*,
  publish, plane.write), the watcher hops (watcher_wake, compose), an
  ``install`` from BOTH serving workers, the ``cache_invalidation``
  child parented under install, and at least one ``first_serve``;
- stage start times are monotone along the freshness waterfall
  (append_observed → publish → plane.write → watcher_wake → compose →
  install → first_serve);
- ``/lineage/<lid>.json`` (id-keyed fetch) returns the same record;
- ``/healthz`` answers HTTP 200 with a non-``burning`` verdict and
  ``/metrics/history.json`` serves at least one TSDB sample.

Exit 0 = roundtrip complete; 1 = any assertion failed (printed).  Run
standalone (``python scripts/check_lineage_roundtrip.py``) or via the
tier-1 suite (tests/test_lineage.py wraps it), like
check_trace_roundtrip.py.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")

WORKERS = 2
READY_S = 180.0
CONVERGE_S = 120.0
# the publisher-side stages every record must carry, in waterfall order
# (fold.* phases vary with the fold's shape and are asserted separately)
ORDERED = ("append_observed", "publish", "plane.write", "watcher_wake",
           "compose", "install", "first_serve")


def buy(u: str, i: str):
    from predictionio_tpu.events.event import Event

    return Event(event="purchase", entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def build_store(path: str):
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    storage = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": path}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "lineageapp"))
    events = [buy(f"u{u}", f"i{it}")
              for u in range(12) for it in range(8) if (u * it + u) % 3]
    storage.l_events.insert_batch(events, app_id)
    return storage, app_id


def get_json(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def post_query(base: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        base + "/queries.json", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def main() -> int:
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_workflow import engine_from_variant

    problems = []
    tmp = tempfile.mkdtemp(prefix="pio-lineage-rt-")
    store_path = os.path.join(tmp, "store")
    proc = None
    base = None
    try:
        storage, app_id = build_store(store_path)
        variant = {
            "id": "lineage-rt",
            "engineFactory": "predictionio_tpu.models."
                             "universal_recommender."
                             "UniversalRecommenderEngine",
            "datasource": {"params": {
                "appName": "lineageapp", "eventNames": ["purchase"]}},
            "algorithms": [{"name": "ur", "params": {
                "appName": "lineageapp", "eventNames": [], "meshDp": 1,
                "maxCorrelatorsPerItem": 8}}],
        }
        engine_json = os.path.join(tmp, "engine.json")
        with open(engine_json, "w") as f:
            json.dump(variant, f)
        _factory, engine, ep = engine_from_variant(variant)
        core_workflow.run_train(engine, ep, engine_id="lineage-rt",
                                storage=storage)

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = {
            **os.environ,
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": store_path,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
            "PIO_JAX_PLATFORM": "cpu",
            "PIO_MODEL_PLANE": "on",
            "PIO_MODEL_PLANE_POLL_S": "0.1",
            "PIO_METRICS_FLUSH_S": "0.25",
            "PIO_TSDB_INTERVAL_S": "0.5",
        }
        proc = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli.main",
             "deploy", "--engine-json", engine_json,
             "--ip", "127.0.0.1", "--port", str(port),
             "--workers", str(WORKERS), "--follow", "0.2"],
            env=env)
        base = f"http://127.0.0.1:{port}"

        # ready = every worker pid visible AND on the publisher's
        # bootstrap generation (>= 2: 1 is the parent's initial publish)
        pids: dict = {}
        deadline = time.time() + READY_S
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"deploy died during startup (rc {proc.returncode})")
            if time.time() > deadline:
                raise RuntimeError(f"group not ready in {READY_S}s ({pids})")
            try:
                _, d = get_json(base, "/", timeout=2)
                pids[d["pid"]] = int(d.get("planeGeneration") or 0)
            except Exception:
                time.sleep(0.1)
                continue
            if len(pids) >= WORKERS and all(g >= 2 for g in pids.values()):
                break
            time.sleep(0.05)
        gref = max(pids.values())

        # the delta: co-buyers couple a brand-new item to i1
        storage.l_events.insert_batch(
            [buy("probe0", "i1")]
            + [buy(f"cob{j}", "i1") for j in range(6)]
            + [buy(f"cob{j}", "fresh_item") for j in range(6)], app_id)

        conv: dict = {}
        deadline = time.time() + CONVERGE_S
        while time.time() < deadline:
            try:
                _, d = get_json(base, "/", timeout=2)
                conv[d["pid"]] = int(d.get("planeGeneration") or 0)
            except Exception:
                pass
            if len(conv) >= WORKERS and all(g > gref for g in conv.values()):
                break
            time.sleep(0.05)
        if len(conv) < WORKERS or not all(g > gref for g in conv.values()):
            raise RuntimeError(
                f"fold never converged the group in {CONVERGE_S}s "
                f"(gref={gref}, seen={conv})")
        gen = max(conv.values())

        # make BOTH workers answer on the new generation, so each one
        # records its first_serve hop (SO_REUSEPORT balances fresh
        # connections across the group eventually)
        served = set()
        deadline = time.time() + 60
        while len(served) < WORKERS and time.time() < deadline:
            try:
                _, d = get_json(base, "/", timeout=2)
                st, _doc = post_query(base, {"user": "probe0", "num": 5})
                if st == 200:
                    served.add(d["pid"])
            except Exception:
                pass
            time.sleep(0.02)
        if len(served) < WORKERS:
            problems.append(
                f"only {len(served)}/{WORKERS} workers answered queries "
                "(cannot assert both first_serve hops)")

        # the record needs a persist cycle (0.5 s throttle) to cross
        # processes; poll for completeness instead of sleeping blind
        doc = None
        deadline = time.time() + 30
        while time.time() < deadline:
            st, d = get_json(base, f"/lineage/{gen}.json")
            if st == 200:
                doc = d
                installs = {s.get("worker") for s in d.get("stages", ())
                            if s.get("stage") == "install"}
                if (d.get("outcome") == "complete"
                        and len(installs) >= WORKERS):
                    break
            time.sleep(0.25)
        if doc is None:
            raise RuntimeError(f"/lineage/{gen}.json never answered 200")

        stages = doc.get("stages", ())
        names = {s.get("stage") for s in stages}
        if doc.get("outcome") != "complete":
            problems.append(f"generation {gen} record outcome="
                            f"{doc.get('outcome')!r}, expected 'complete'")
        for need in ORDERED:
            if need not in names:
                problems.append(f"record is missing stage {need!r}")
        if not any(n.startswith("fold.") for n in names):
            problems.append("record carries no fold.* phase stage")
        cache_kids = [s for s in stages
                      if s.get("stage") == "cache_invalidation"]
        if not cache_kids:
            problems.append("no cache_invalidation stage (serve cache is "
                            "on by default — the install hook is broken)")
        elif any(s.get("parent") != "install" for s in cache_kids):
            problems.append("cache_invalidation not parented under install")
        installs = {s.get("worker") for s in stages
                    if s.get("stage") == "install"}
        if len(installs) < WORKERS:
            problems.append(
                f"install recorded by {sorted(installs)} — expected all "
                f"{WORKERS} serving workers")
        serves = {s.get("worker") for s in stages
                  if s.get("stage") == "first_serve"}
        if not serves:
            problems.append("no first_serve stage recorded")
        origin = doc.get("origin") or ""
        if not origin.startswith("pub-"):
            problems.append(
                f"record origin {origin!r} is not the plane publisher — "
                "the fold stages came from the wrong process")
        if origin in installs | serves:
            problems.append(
                f"origin {origin!r} also recorded install/first_serve — "
                "the publisher must not serve")
        # waterfall monotonicity on earliest start per ordered stage
        starts = {}
        for s in stages:
            n = s.get("stage")
            if n in ORDERED:
                t = float(s.get("start") or 0)
                starts[n] = min(starts.get(n, t), t)
        seq = [(n, starts[n]) for n in ORDERED if n in starts]
        for (a, ta), (b, tb) in zip(seq, seq[1:]):
            if tb < ta - 1e-3:
                problems.append(
                    f"stage {b} starts before {a} ({tb:.6f} < {ta:.6f})")
        for s in stages:
            if not (0 <= float(s.get("duration_s") or 0) <= 300):
                problems.append(f"stage {s.get('stage')!r} has a bogus "
                                f"duration {s.get('duration_s')!r}")

        # index + id-keyed fetch + cross-process serving proof
        _, index = get_json(base, "/lineage.json")
        entry = next((e for e in index.get("records", ())
                      if e.get("generation") == gen), None)
        if entry is None:
            problems.append(f"/lineage.json does not index generation {gen}")
        elif entry.get("lid") != doc.get("lid"):
            problems.append("/lineage.json indexes a different lid than "
                            "the generation fetch returned")
        server_tag = index.get("worker") or ""
        if not server_tag or server_tag == origin:
            problems.append(
                f"/lineage.json served by {server_tag!r} — must be a "
                "worker that did NOT produce the record")
        st, by_lid = get_json(base, f"/lineage/{doc.get('lid')}.json")
        if st != 200 or by_lid.get("lid") != doc.get("lid"):
            problems.append("id-keyed /lineage/<lid>.json fetch failed")

        # the two lineage consumers answer on the same sockets
        st, hz = get_json(base, "/healthz")
        if st != 200:
            problems.append(f"/healthz answered HTTP {st}")
        if hz.get("status") == "burning":
            problems.append(f"/healthz reports burning on an idle "
                            f"deploy: {hz}")
        st, hist = get_json(base, "/metrics/history.json")
        if st != 200 or not hist.get("samples"):
            problems.append("/metrics/history.json has no TSDB samples")
    except Exception as e:  # noqa: BLE001 - the harness wants one rc
        problems.append(f"roundtrip aborted: {e!r}")
    finally:
        if proc is not None and base is not None:
            for _ in range(16):
                try:
                    with urllib.request.urlopen(base + "/stop",
                                                timeout=5) as r:
                        r.read()
                    time.sleep(0.3)
                except Exception:
                    break
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        from predictionio_tpu.storage.locator import set_storage

        set_storage(None)
        shutil.rmtree(tmp, ignore_errors=True)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print(f"ok: generation {gen} lineage complete across "
              f"{WORKERS} serving workers + publisher "
              f"(origin {origin}, installs {sorted(installs)}), "
              "waterfall monotone, /healthz + /metrics/history.json live")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
