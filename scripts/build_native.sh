#!/usr/bin/env bash
# Eagerly build the native data-plane cores into predictionio_tpu/native/_build.
#
# Everything this script does also happens lazily on first use; run it at
# image-build or deploy time so the first serve/scan request never pays the
# compile.  Artifacts are keyed by a SHA-256 of the C++ source CONTENT
# (native/build.py), so a rebuild after any edit is automatic and a stale
# .so can never be served; re-running with unchanged sources is a no-op.
#
# Exits non-zero when no C++ toolchain is on PATH — callers that want the
# graceful-degradation behavior (tier-1 runs without a toolchain) simply
# don't run this script; the Python oracle serves everything.
set -euo pipefail

cd "$(dirname "$0")/.."

python - <<'PY'
from pathlib import Path

from predictionio_tpu.native import build

root = Path("predictionio_tpu/native")
targets = [
    (root / "eventlog_scanner.cpp", "libeventscan"),
    (root / "data_plane.cpp", "libdataplane"),
]
cxx = build.compiler()
if cxx is None:
    raise SystemExit("build_native.sh: no C++ compiler on PATH "
                     "(g++/c++/clang++); the Python oracle will serve")
for src, stem in targets:
    so = build.build(src, stem)
    print(f"built {so} ({cxx})")
PY
