#!/usr/bin/env python
"""Verify columnar event-store snapshots against their JSONL ground truth.

For every ``snapshot/manifest.json`` under a localfs/sharedfs store root
(``<root>/events/app_*/<channel>/snapshot/``):

- re-parse each covered byte range of each covered segment, drop the
  tombstone ids the manifest says were applied, and diff the resulting
  event COUNT against both the manifest's event-count watermark and the
  snapshot file's row count;
- diff the re-derived eventId SET against the snapshot's id column;
- row-verify a sample prefix: event verb, entityType, entityId, target
  and timestamp columns must decode back to exactly what the JSONL says.

Exit 0 = every snapshot matches; 1 = any diff (printed).  Run standalone
(``python scripts/check_snapshot_integrity.py <store_root>...``) or via
the tier-1 suite (tests/test_snapshot.py wraps it), like
check_metrics_names.py.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SAMPLE_ROWS = 500


def _parse_covered(seg: Path, end: int, applied: set, truth: list) -> None:
    with open(seg, "rb") as f:
        data = f.read(end)
    for line in data.split(b"\n"):
        if not line.strip():
            continue
        ev = json.loads(line)
        if ev.get("eventId") in applied:
            continue
        truth.append(ev)


def _shard_primary(shard_dir: Path) -> str:
    try:
        topo = json.loads((shard_dir / "topology.json").read_text())
        return topo.get("primary", "a")
    except (OSError, json.JSONDecodeError):
        return "a"


def check_channel(d: Path, store_root: Path = None) -> list:
    """Problems for one channel dir with a snapshot (empty = clean).

    A MERGED cross-shard manifest (the sharded store's root-level
    snapshot; covered keys are ``"<shard>|<segment>"``) re-derives its
    ground truth from every shard's primary node in shard order — the
    merged file's row-order contract — and needs ``store_root`` to
    resolve the shard directories."""
    from predictionio_tpu.store.columnar import read_batch
    from predictionio_tpu.storage.snapshot import load_manifest

    problems = []
    m = load_manifest(d)
    if m is None:
        return [f"{d}: unreadable/invalid manifest"]
    try:
        batch, ids, _meta = read_batch(d / "snapshot" / m["snapshot"])
    except (OSError, ValueError) as e:
        return [f"{d}: snapshot unreadable: {e}"]
    if ids is None:
        return [f"{d}: snapshot has no id column"]
    applied = set(m.get("tombstones_applied", ()))
    truth = []   # wire dicts in builder order (sorted covered segments)
    if m.get("merged"):
        if store_root is None:
            return [f"{d}: merged manifest outside a sharded store root"]
        per_shard: dict = {}
        for key, end in m["covered"].items():
            k, sep, name = key.partition("|")
            if not sep or not k.isdigit():
                problems.append(f"{d}: malformed merged covered key {key!r}")
                continue
            per_shard.setdefault(int(k), {})[name] = end
        for k in sorted(per_shard):
            sd = store_root / f"shard_{k:02d}"
            chan = (sd / _shard_primary(sd) / "events"
                    / d.parent.name / d.name)
            for name in sorted(per_shard[k]):
                seg = chan / name
                if not seg.exists():
                    problems.append(
                        f"{d}: covered segment {k}|{name} missing "
                        "(stale manifest — snapshot would be bypassed)")
                    continue
                _parse_covered(seg, per_shard[k][name], applied, truth)
    else:
        for name in sorted(m["covered"]):
            seg = d / name
            if not seg.exists():
                problems.append(f"{d}: covered segment {name} missing "
                                "(stale manifest — snapshot would be "
                                "bypassed)")
                continue
            _parse_covered(seg, m["covered"][name], applied, truth)
    if len(truth) != m.get("events"):
        problems.append(
            f"{d}: JSONL recount {len(truth)} != manifest watermark "
            f"{m.get('events')}")
    if len(batch) != len(truth):
        problems.append(
            f"{d}: snapshot rows {len(batch)} != JSONL recount {len(truth)}")
    id_truth = {e.get("eventId") for e in truth}
    id_snap = set(ids.tolist())
    if id_truth != id_snap:
        missing = list(id_truth - id_snap)[:3]
        extra = list(id_snap - id_truth)[:3]
        problems.append(
            f"{d}: eventId set diff (missing {missing}, extra {extra})")
    from predictionio_tpu.events.event import parse_time

    # merged manifests verify sample rows by id alignment (multi-writer
    # segment-name interleaving can make the cross-shard parse order
    # differ from the build-time order without being wrong); per-shard
    # manifests keep the strict prefix-order check
    row_of = None
    if m.get("merged"):
        row_of = {eid: j for j, eid in enumerate(ids.tolist())}
    for j, ev in enumerate(truth[:SAMPLE_ROWS]):
        if row_of is not None:
            j = row_of.get(ev.get("eventId"), -1)
            if j < 0:
                continue      # already reported by the id-set diff
        if j >= len(batch):
            break
        got = (
            batch.event_dict.str(int(batch.event_codes[j])),
            batch.entity_type_dict.str(int(batch.entity_type_codes[j])),
            batch.entity_dict.str(int(batch.entity_ids[j])),
            (batch.target_dict.str(int(batch.target_ids[j]))
             if batch.target_ids[j] >= 0 else None),
            int(batch.times_us[j]),
        )
        want = (
            ev["event"], ev["entityType"], str(ev["entityId"]),
            (str(ev["targetEntityId"])
             if ev.get("targetEntityId") is not None else None),
            int(parse_time(ev["eventTime"]).timestamp() * 1e6),
        )
        if got != want:
            problems.append(f"{d}: row {j} mismatch: {got} != {want}")
            break
    return problems


def _channel_ids(chan: Path) -> set:
    """Live eventIds in a channel dir: complete lines of every segment,
    minus the unioned tombstones (torn tails skipped, like the scans)."""
    dead = set()
    for t in chan.glob("tombstones*.txt"):
        dead.update(t.read_text().split())
    ids = set()
    for seg in sorted(chan.glob("seg-*.jsonl")):
        data = seg.read_bytes()
        lines = data.split(b"\n")
        if lines and not data.endswith(b"\n"):
            lines = lines[:-1]          # torn tail: never acknowledged
        for line in lines:
            if not line.strip():
                continue
            try:
                eid = json.loads(line).get("eventId")
            except json.JSONDecodeError:
                continue
            if eid and eid not in dead:
                ids.add(eid)
    return ids


def check_sharded_root(root: Path) -> list:
    """Sharded-store invariants: every shard's PRIMARY node is a normal
    localfs tree (its snapshots are verified by check_channel like any
    other), and the merged cross-shard eventId sets per (app, channel)
    must be pairwise DISJOINT — an id in two shards means routing broke
    or a failover duplicated data."""
    problems = []
    shards = sorted(p for p in root.glob("shard_*") if p.is_dir())
    per_chan: dict = {}           # (app/chan relpath) -> {shard: ids}
    for sd in shards:
        try:
            topo = json.loads((sd / "topology.json").read_text())
            primary = topo.get("primary", "a")
        except (OSError, json.JSONDecodeError):
            primary = "a"
        evroot = sd / primary / "events"
        if not evroot.exists():
            continue
        for chan in sorted(evroot.glob("app_*/*")):
            if not chan.is_dir():
                continue
            key = f"{chan.parent.name}/{chan.name}"
            per_chan.setdefault(key, {})[sd.name] = _channel_ids(chan)
    for key, by_shard in sorted(per_chan.items()):
        owner: dict = {}
        for shard_name, ids in sorted(by_shard.items()):
            for eid in ids:
                if eid in owner:
                    problems.append(
                        f"{root}: {key}: eventId {eid!r} present in BOTH "
                        f"{owner[eid]} and {shard_name} (cross-shard "
                        "duplicate)")
                    break               # one example per shard pair
                owner[eid] = shard_name
    return problems


def main(argv) -> int:
    if not argv:
        print("usage: check_snapshot_integrity.py <store_root>...",
              file=sys.stderr)
        return 2
    problems = []
    checked = 0
    for root in argv:
        events = Path(root) / "events"
        for manifest in sorted(events.glob("app_*/*/snapshot/manifest.json")):
            checked += 1
            problems.extend(check_channel(manifest.parent.parent,
                                          store_root=Path(root)))
        # sharded layout: per-shard per-node manifests + the cross-shard
        # merged eventId disjointness sweep
        for manifest in sorted(Path(root).glob(
                "shard_*/*/events/app_*/*/snapshot/manifest.json")):
            checked += 1
            problems.extend(check_channel(manifest.parent.parent))
        if any(Path(root).glob("shard_*")):
            problems.extend(check_sharded_root(Path(root)))
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print(f"ok: {checked} snapshot(s) match their JSONL ground truth")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
