#!/usr/bin/env python
"""Verify the flight-recorder round trip on a real deployed worker.

Trains a small Universal Recommender on the same deterministic commerce
fixture as check_serve_parity, deploys it (one worker, localfs storage),
fires a forced-slow query — ``PIO_TRACE_SLOW_MS=0`` makes EVERY request
exceed the slow threshold, the honest analogue of a production p99
straggler — and asserts its full waterfall is retrievable and
stage-complete:

- the response echoes our X-Request-ID;
- ``/traces/<rid>.json`` returns the trace, kept for reason ``slow``;
- the waterfall carries the ``ur_predict`` span and its five stage
  children (history → score → mask → topk → assemble), each parented
  under ``ur_predict`` with non-negative durations inside the request
  envelope;
- ``/traces.json`` indexes the same rid;
- the request-latency histogram in ``/metrics`` carries a trace-id
  exemplar (the metrics→traces link).

Exit 0 = round trip complete; 1 = any assertion failed (printed).  Run
standalone (``python scripts/check_trace_roundtrip.py``) or via the
tier-1 suite (tests/test_tracing.py wraps it), like
check_serve_parity.py.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

# runnable from any cwd without an installed package
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")
# forced-slow: every request's duration exceeds the threshold, so the
# query below is retained exactly the way a production straggler would be
os.environ["PIO_TRACE_SLOW_MS"] = "0"
os.environ["PIO_TRACE_SAMPLE_N"] = "0"

RID = f"trace-rt-{os.getpid()}"
STAGES = ("history", "score", "mask", "topk", "assemble")


def main() -> int:
    import shutil
    import tempfile

    from check_serve_parity import build_app

    problems = []
    tmp = tempfile.mkdtemp(prefix="pio_trace_rt")
    try:
        from predictionio_tpu.obs import tracing as obs_tracing
        from predictionio_tpu.workflow import core_workflow
        from predictionio_tpu.workflow.create_server import deploy

        # a fresh recorder so an armed one from earlier imports (or a
        # shared ~/.cache dir) can't satisfy the assertions for us
        obs_tracing.set_recorder(obs_tracing.FlightRecorder())
        storage = build_app()
        variant = {
            "id": "trace-rt",
            "engineFactory": "predictionio_tpu.models."
                             "universal_recommender."
                             "UniversalRecommenderEngine",
            "datasource": {"params": {
                "appName": "parityapp",
                "eventNames": ["purchase", "view"]}},
            "algorithms": [{"name": "ur", "params": {
                "appName": "parityapp", "eventNames": [], "meshDp": 1,
                "maxCorrelatorsPerItem": 8}}],
        }
        engine_json = os.path.join(tmp, "engine.json")
        with open(engine_json, "w") as f:
            json.dump(variant, f)
        from predictionio_tpu.workflow.create_workflow import (
            engine_from_variant,
        )

        _factory, engine, ep = engine_from_variant(variant)
        core_workflow.run_train(engine, ep, engine_id="trace-rt",
                                storage=storage)
        httpd = deploy(engine_json=engine_json, host="127.0.0.1", port=0,
                       storage=storage, background=True)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            req = urllib.request.Request(
                base + "/queries.json",
                data=json.dumps({"user": "u2", "num": 5}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-ID": RID})
            with urllib.request.urlopen(req, timeout=30) as r:
                if r.status != 200:
                    problems.append(f"query answered HTTP {r.status}")
                if r.headers.get("X-Request-ID") != RID:
                    problems.append("response did not echo our request id")
                r.read()
            # retention happens in the middleware tail AFTER the response
            # bytes are flushed — a pool sibling can serve our immediate
            # fetch before the POST's thread has indexed the trace, so
            # poll briefly (normally lands within a few ms)
            doc = None
            deadline = time.time() + 5.0
            while True:
                try:
                    with urllib.request.urlopen(
                            base + f"/traces/{RID}.json", timeout=10) as r:
                        doc = json.loads(r.read())
                    break
                except urllib.error.HTTPError as e:
                    if e.code != 404 or time.time() > deadline:
                        raise
                    time.sleep(0.01)
            if doc.get("reason") != "slow":
                problems.append(
                    f"kept for {doc.get('reason')!r}, expected 'slow'")
            if doc.get("status") != 200 or doc.get("route") != "/queries.json":
                problems.append(f"trace envelope wrong: {doc.get('status')} "
                                f"{doc.get('route')!r}")
            by_name = {s["name"]: s for s in doc.get("spans", ())}
            ur = by_name.get("ur_predict")
            if ur is None:
                problems.append("waterfall is missing the ur_predict span")
            for name in STAGES:
                s = by_name.get(name)
                if s is None:
                    problems.append(f"waterfall is missing stage {name!r}")
                    continue
                if ur is not None and s.get("parent") != ur.get("id"):
                    problems.append(
                        f"stage {name!r} not parented under ur_predict")
                if not (0 <= s.get("duration_s", -1) <= 60):
                    problems.append(f"stage {name!r} has a bogus duration")
            with urllib.request.urlopen(base + "/traces.json",
                                        timeout=10) as r:
                index = json.loads(r.read())
            if RID not in {t.get("rid") for t in index.get("traces", ())}:
                problems.append("/traces.json does not index the request")
            from predictionio_tpu.obs.exposition import parse_exemplars

            with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
                exemplars = parse_exemplars(r.read().decode())
            linked = {rid for _lb, rid, _v in exemplars.get(
                "pio_http_request_duration_seconds_bucket", ())}
            if not linked:
                problems.append(
                    "no trace-id exemplar on the request-latency histogram")
        finally:
            httpd.shutdown()
            httpd.server_close()
    finally:
        from predictionio_tpu.storage.locator import set_storage

        set_storage(None)
        shutil.rmtree(tmp, ignore_errors=True)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print("ok: forced-slow query retained, waterfall stage-complete "
              f"({', '.join(STAGES)}), indexed, exemplar-linked")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
