#!/usr/bin/env python
"""Lint the metric-name AND trace-span-name contracts.

Imports every module that declares instruments (they register at import
time) and verifies each registered metric:

- name matches ``pio_[a-z0-9_]+`` (the registry enforces this at
  registration too — the lint catches a registry regression and any
  metric that dodges the registry);
- carries a non-empty help string;
- histograms have strictly increasing bucket boundaries.

Then statically scans the package source (AST, not regex — multiline
calls and nesting are handled) for flight-recorder/journal span calls —
``.span("name", attr=...)``, ``trace_span("name", ...)``,
``timed("name")``, ``add_span("name", ...)`` — and lints every literal
span name and attr keyword against ``obs.tracing.SPAN_NAME_PATTERN``
(lowercase snake with optional dots: the pio_-style contract minus the
prefix), so waterfall rows and span-based dashboards stay greppable and
stable.

Run standalone (``python scripts/check_metrics_names.py``) or via the
tier-1 suite (tests/test_obs_metrics.py wraps it), exit 0 = clean.
"""

from __future__ import annotations

import ast
import importlib
import os
import re
import sys

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# every module that declares instruments at import time; a new
# instrumented module must be added here (the test fails otherwise only
# if its names are bad AND it happens to be imported transitively)
INSTRUMENTED_MODULES = [
    "predictionio_tpu.obs.metrics",
    "predictionio_tpu.obs.tracing",
    "predictionio_tpu.api.http_util",
    "predictionio_tpu.api.event_server",
    "predictionio_tpu.api.dashboard",
    "predictionio_tpu.storage.localfs",
    "predictionio_tpu.storage.sharded",
    "predictionio_tpu.storage.snapshot",
    "predictionio_tpu.workflow.core_workflow",
    "predictionio_tpu.workflow.create_server",
    "predictionio_tpu.models.universal_recommender.engine",
    "predictionio_tpu.streaming.follow",
    "predictionio_tpu.streaming.fold",
    "predictionio_tpu.streaming.plane",
    "predictionio_tpu.streaming.replicate",
    "predictionio_tpu.serve.response_cache",
    "predictionio_tpu.serve.history_cache",
    "predictionio_tpu.native.core",
    "predictionio_tpu.obs.lineage",
    "predictionio_tpu.obs.tsdb",
    "predictionio_tpu.obs.slo",
    "predictionio_tpu.obs.cluster",
]


# contract names external dashboards/alerts key on: the HTTP middleware
# family must survive any front-end rewrite (the event-loop migration is
# exactly the kind of change that could silently drop one)
REQUIRED_METRICS = frozenset({
    "pio_http_requests_total",
    "pio_http_request_duration_seconds",
    "pio_http_requests_in_flight",
    "pio_http_connections",
    "pio_serve_batch_size",
    "pio_events_ingested_total",
    # candidate-pruned serving contract (PR 7): dashboards key on the
    # pruned/fallback outcome mix and the candidate-fraction histogram
    "pio_ur_serve_candidate_total",
    "pio_ur_serve_candidate_frac",
    "pio_ur_host_inverted_bytes",
    # streaming-freshness contract (PR 8): the follow-trainer's fold
    # outcomes/lag and the hot-swap generation counter every serving
    # cache invalidates on
    "pio_follow_folds_total",
    "pio_follow_fold_duration_seconds",
    "pio_follow_lag_events",
    "pio_follow_last_publish_timestamp_seconds",
    "pio_model_generation",
    # sparse fold state (PR 11): capacity alerting keys on the resident
    # state footprint and the sparse|dense|retrain mode flag
    "pio_follow_state_bytes",
    "pio_follow_state_mode",
    # fold-tick phases + pruned re-LLR (PR 13): the freshness sweep's
    # per-phase columns and the roundtrip's pruning/incremental-emit
    # engagement assertions key on these
    "pio_follow_fold_phase_duration_seconds",
    "pio_follow_rellr_rows_total",
    "pio_follow_emit_total",
    # sharded/replicated store contract (PR 9): the failover drill and
    # replica-lag alerting key on these
    "pio_store_shard_events_total",
    "pio_store_replica_lag_events",
    "pio_store_promotions_total",
    # parallel cross-shard scan pipeline (PR 12): the bench's recovery
    # guard and the freshness roundtrip's parallel-path assertion key on
    # the worker gauge; per-shard durations feed the straggler view
    "pio_store_scan_shard_duration_seconds",
    "pio_store_scan_workers",
    "pio_store_scan_merged_events_per_sec",
    # shared-memory model plane (PR 14): the bench's memory guard and
    # the group-convergence probes key on the per-worker generation/rss
    # gauges; GC visibility on the counter
    "pio_model_plane_generation",
    "pio_model_plane_bytes",
    "pio_model_plane_map_seconds",
    "pio_model_plane_gc_total",
    "pio_process_rss_bytes",
    # delta arenas (PR 15): the bench's write-amplification guard and
    # publish-side observability key on the per-path byte counter; the
    # blob-store/chain gauges feed disk-sizing and restart-cost views
    "pio_model_plane_publish_bytes_total",
    "pio_model_plane_blob_count",
    "pio_model_plane_chain_len",
    # provenance-invalidated response cache (PR 16): hit-rate dashboards
    # key on the outcome counter; the zero-staleness alert keys on the
    # audit-mismatch counter staying 0
    "pio_serve_cache_total",
    "pio_serve_cache_invalidations_total",
    "pio_serve_cache_entries",
    "pio_serve_cache_audit_mismatch_total",
    # generation lineage + local TSDB + SLO engine (PR 17): the
    # roundtrip check keys on the record counter; dashboards and
    # /healthz key on the burn gauges; sibling-eviction visibility on
    # the stale counter
    "pio_lineage_records_total",
    "pio_obs_stale_siblings_total",
    "pio_slo_burn_rate",
    # native data-plane cores + history cache (PR 18): the fallback
    # runbook keys on the reason counter, capacity/rollout dashboards on
    # the active gauge and per-core call counter; history-cache hit-rate
    # and staleness views on the outcome counter and entries gauge
    "pio_native_active",
    "pio_native_calls_total",
    "pio_native_fallback_total",
    "pio_history_cache_total",
    "pio_history_cache_entries",
    # multi-node plane replication (PR 19): fleet-health alerting keys
    # on the per-subscriber lag and session gauges; network sizing on
    # the dir/kind byte counter; resync visibility on the reason counter
    "pio_plane_repl_bytes_total",
    "pio_plane_repl_lag_generations",
    "pio_plane_repl_subscribers",
    "pio_plane_repl_resyncs_total",
    # cluster observability fabric (PR 20): fleet dashboards key on the
    # federated liveness gauges; the cluster SLOs read the propagation
    # histogram (stitched lineage truth) and the divergence gauges
    "pio_cluster_nodes",
    "pio_cluster_node_up",
    "pio_cluster_scrapes_total",
    "pio_cluster_propagation_seconds",
    "pio_cluster_qps_divergence",
    "pio_cluster_p95_divergence",
})

SPAN_CALL_NAMES = frozenset({"span", "trace_span", "timed", "add_span"})
# lineage stage calls name their stage in args[1] (args[0] is the
# lineage id); their attr kwargs follow the same naming contract
STAGE_CALL_NAMES = frozenset({"stage"})
# control kwargs, not attr names
_EXEMPT_KWARGS = ("parent", "attrs", "start", "duration_s", "flush",
                  "node")
# span attrs assigned post-hoc (rec["attrs"] = {...}) use literal dict
# keys; f-string keys (dynamic stage suffixes) are checked on their
# literal prefix parts only
_ATTRS_SUBSCRIPT = "attrs"


def lint_span_names(pkg_root: str) -> list:
    """Every literal span name and attr key in ``pkg_root`` must match
    SPAN_NAME_PATTERN."""
    from predictionio_tpu.obs.tracing import SPAN_NAME_PATTERN

    name_re = re.compile(SPAN_NAME_PATTERN)
    problems = []

    def check(value: str, where: str) -> None:
        if not name_re.match(value):
            problems.append(
                f"{where}: span/attr name {value!r} violates "
                f"{SPAN_NAME_PATTERN}")

    for dirpath, _dirs, files in os.walk(pkg_root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as e:
                    problems.append(f"{path}: unparseable: {e}")
                    continue
            rel = os.path.relpath(path, os.path.dirname(pkg_root))
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                         else node.func.id if isinstance(node.func, ast.Name)
                         else None)
                if fname in SPAN_CALL_NAMES:
                    name_idx = 0
                elif fname in STAGE_CALL_NAMES:
                    name_idx = 1
                else:
                    continue
                where = f"{rel}:{node.lineno}"
                if (len(node.args) > name_idx
                        and isinstance(node.args[name_idx], ast.Constant)
                        and isinstance(node.args[name_idx].value, str)):
                    check(node.args[name_idx].value, where)
                for kw in node.keywords:
                    if kw.arg and kw.arg not in _EXEMPT_KWARGS:
                        check(kw.arg, where)
    return problems


def lint_docs_catalog(repo_root: str, registered: set) -> list:
    """Cross-check the docs metric-catalog table against the code:
    every REQUIRED metric must appear in the table, and every pio_ name
    the table documents must be registered or at least declared in the
    package source (some gauges register lazily on first publish)."""
    path = os.path.join(repo_root, "docs", "operations.md")
    if not os.path.exists(path):
        return [f"{path}: missing (the metric catalog lives there)"]
    name_re = re.compile(r"pio_[a-z0-9_]+")
    docs_names = set()
    with open(path) as f:
        for line in f:
            if line.startswith("| `pio_"):
                docs_names.update(name_re.findall(line))
    declared = set(registered)
    pkg_root = os.path.join(repo_root, "predictionio_tpu")
    for dirpath, _dirs, files in os.walk(pkg_root):
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn)) as f:
                    declared.update(name_re.findall(f.read()))
    problems = []
    for miss in sorted(REQUIRED_METRICS - docs_names):
        problems.append(
            f"docs/operations.md: required metric {miss} missing from "
            "the metric-catalog table")
    for ghost in sorted(docs_names - declared):
        problems.append(
            f"docs/operations.md: catalog documents {ghost} but no such "
            "metric exists in the package")
    return problems


def main() -> int:
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from predictionio_tpu.obs.metrics import NAME_RE, Histogram, get_registry

    problems = []
    pkg_root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "predictionio_tpu")
    problems += lint_span_names(pkg_root)
    metrics = get_registry().metrics()
    for m in metrics:
        if not NAME_RE.match(m.name):
            problems.append(f"{m.name}: name violates {NAME_RE.pattern}")
        if not m.help or not m.help.strip():
            problems.append(f"{m.name}: missing help string")
        if isinstance(m, Histogram):
            if list(m.buckets) != sorted(set(m.buckets)):
                problems.append(f"{m.name}: buckets not strictly increasing")
    if not metrics:
        problems.append("no metrics registered — imports broken?")
    names = {m.name for m in metrics}
    for req in sorted(REQUIRED_METRICS - names):
        problems.append(
            f"required metric {req} not registered (middleware contract "
            "broken by a front-end change?)")
    problems += lint_docs_catalog(os.path.dirname(pkg_root), names)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print(f"ok: {len(metrics)} metrics + span-name scan, "
              "names and help strings clean")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
