#!/usr/bin/env python
"""Lint the metric-name contract.

Imports every module that declares instruments (they register at import
time) and verifies each registered metric:

- name matches ``pio_[a-z0-9_]+`` (the registry enforces this at
  registration too — the lint catches a registry regression and any
  metric that dodges the registry);
- carries a non-empty help string;
- histograms have strictly increasing bucket boundaries.

Run standalone (``python scripts/check_metrics_names.py``) or via the
tier-1 suite (tests/test_obs_metrics.py wraps it), exit 0 = clean.
"""

from __future__ import annotations

import importlib
import os
import sys

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# every module that declares instruments at import time; a new
# instrumented module must be added here (the test fails otherwise only
# if its names are bad AND it happens to be imported transitively)
INSTRUMENTED_MODULES = [
    "predictionio_tpu.obs.metrics",
    "predictionio_tpu.api.http_util",
    "predictionio_tpu.api.event_server",
    "predictionio_tpu.api.dashboard",
    "predictionio_tpu.storage.localfs",
    "predictionio_tpu.storage.snapshot",
    "predictionio_tpu.workflow.core_workflow",
    "predictionio_tpu.workflow.create_server",
    "predictionio_tpu.models.universal_recommender.engine",
]


def main() -> int:
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from predictionio_tpu.obs.metrics import NAME_RE, Histogram, get_registry

    problems = []
    metrics = get_registry().metrics()
    for m in metrics:
        if not NAME_RE.match(m.name):
            problems.append(f"{m.name}: name violates {NAME_RE.pattern}")
        if not m.help or not m.help.strip():
            problems.append(f"{m.name}: missing help string")
        if isinstance(m, Histogram):
            if list(m.buckets) != sorted(set(m.buckets)):
                problems.append(f"{m.name}: buckets not strictly increasing")
    if not metrics:
        problems.append("no metrics registered — imports broken?")
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print(f"ok: {len(metrics)} metrics, names and help strings clean")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
