#!/bin/bash
# The first-TPU-session drill (VERDICT r4 #1): land the hardware record
# BEFORE any experiment that can compile for minutes.  Run each step to
# completion — NEVER timeout-kill a TPU-attached process (a SIGTERM
# mid-compile wedges the tunnel for the whole session; see PERF.md).
#
# Usage: bash scripts/tpu_drill.sh   (from the repo root, box otherwise idle)
set -u
cd "$(dirname "$0")/.."

echo "== 1. relay sanity (do NOT wait on jax init to learn this) =="
ss -tln || true
echo "   (a listener alone is not proof — round 5 had one and the claim"
echo "    leg still failed UNAVAILABLE; the probe below is the real test)"

echo "== 2. probe: devices + one real readback (~1 min healthy; if it"
echo "   blocks >10 min the session has no TPU — fall back to CPU work) =="
python - << 'PY'
import time, numpy as np, jax
t0 = time.time()
print("devices:", jax.devices(), f"init {time.time()-t0:.0f}s")
import jax.numpy as jnp
t0 = time.time()
s = float(np.asarray(jnp.ones((256, 256), jnp.bfloat16) @ jnp.ones((256, 256), jnp.bfloat16)).sum())
print(f"readback ok sum={s} rtt={time.time()-t0:.2f}s")
PY
[ $? -ne 0 ] && { echo "NO TPU — stop here, do CPU work"; exit 1; }

echo "== 3. THE RECORD: full bench, solo, before anything else =="
python bench.py | tee /tmp/bench_tpu_record.json

echo "== 4. profile: section 7 prints the Pallas-merge FLIP/KEEP verdict,"
echo "   section 8 the MFU/roofline.  If FLIP: change topk_impl() auto in"
echo "   ops/cco.py to pallas-on-TPU and re-run the ablation. =="
python profile_tpu.py

echo "== 5. serving A/B on TPU (micro-batch validation, VERDICT r4 #4) =="
for mode in off auto; do
  echo "-- PIO_SERVE_BATCH=$mode --"
  PIO_SERVE_BATCH=$mode python bench.py --only http | tail -1
done
echo "-- p50@100k with the device gather scorer --"
python bench.py --only serve100k | tail -1

echo "== drill complete: record BENCH + FLIP/KEEP + serving table in PERF.md =="
