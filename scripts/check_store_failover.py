#!/usr/bin/env python
"""Fault-injection drill for the sharded, replicated event store.

Proves the failover contract end to end on a real store (shards=2,
replicas=2, PIO_FSYNC=always):

1. **Kill a primary mid-group-commit**: a real OS-process writer ingests
   through the semi-sync replication barrier, printing every ACKED event
   id; it is SIGKILLed mid-stream, then every shard's primary node
   directory is yanked away.  A fresh store instance must promote each
   replica and serve every acked event exactly once — zero acked-event
   loss, zero duplicates, with the un-acked tail either absent or present
   at most once (at-least-once is the ingest contract).
2. **Torn replica tail**: garbage is appended past a replica segment's
   acknowledged offset and an acknowledged suffix is torn off another;
   the follower must heal both (truncate / re-copy) and ingest must keep
   acking — replica bytes end up byte-identical to the primary.
3. **Partition mid-scan**: a shard's primary directory is renamed away
   while a fan-out scan is mid-flight; the scan must promote, resume on
   the replica, and still return every surviving event exactly once.
4. **Re-sync drains**: after all of the above, ``topology_status`` (the
   /stats.json ``storeTopology`` document) must show every shard's
   ``replicaLagEvents`` at 0 — the ``pio_store_replica_lag_events``
   gauge's source of truth.

Exit 0 = every phase clean; 1 = any failure (printed).  Run standalone
(``python scripts/check_store_failover.py``) or via the tier-1 suite
(tests/test_store_failover.py wraps it), like check_serve_parity.py.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap
import time
from pathlib import Path

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SHARDS = 2
APP_ID = 1


def writer_script(root: str, tag: str, n: int, shards: int = SHARDS,
                  app_id: int = APP_ID) -> str:
    """A real OS-process writer into the replicated store: each event id
    (``<tag>-<k>``) is printed only AFTER the insert returned — i.e.
    after the semi-sync replication barrier acknowledged it on both
    nodes.  The ONE copy of the kill-a-primary drill's writer — the
    bench ``store_failover`` phase and
    test_multiworker_ingest.py's replicated SIGKILL test import it, so
    ack-contract or layout changes happen in one place."""
    return textwrap.dedent(f"""
        import os
        os.environ["PIO_FSYNC"] = "always"
        os.environ["PIO_WRITER_TAG"] = {tag!r}
        from predictionio_tpu.storage import localfs
        localfs.SEGMENT_MAX_BYTES = 4096   # constant rotation
        from predictionio_tpu.storage.sharded import ShardedEvents
        ev = ShardedEvents({root!r}, shards={shards}, replicas=2)
        for k in range({n}):
            r = ev.insert_json_batch(
                [{{"event": "buy", "entityType": "user",
                   "entityId": "u%d" % k,
                   "eventId": "{tag}-%d" % k}}], {app_id})
            assert r[0]["status"] == 201, r
            print("{tag}-%d" % k, flush=True)
        print("DONE", flush=True)
    """)


def phase_kill_primary(root: str, problems: list) -> set:
    """SIGKILL a writer mid-commit, yank every primary node dir, verify
    promotion preserves exactly the acked set."""
    from predictionio_tpu.storage.sharded import ShardedEvents

    p = subprocess.Popen(
        [sys.executable, "-c", writer_script(root, "wK", 100_000)],
        stdout=subprocess.PIPE, text=True)
    acked = []
    for line in p.stdout:
        acked.append(line.strip())
        if len(acked) >= 80:
            break
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=30)
    # yank the primary node of every shard (the "node died" injection)
    for k in range(SHARDS):
        pdir = Path(root) / f"shard_{k:02d}" / "a"
        if pdir.exists():
            shutil.move(str(pdir), str(pdir) + ".lost")
    ev = ShardedEvents(root, shards=SHARDS, replicas=2)
    got = [e.event_id for e in ev.scan(APP_ID)]
    missing = set(acked) - set(got)
    if missing:
        problems.append(
            f"kill-primary: {len(missing)} acked events lost after "
            f"promotion (e.g. {sorted(missing)[:3]})")
    dups = {i for i in got if got.count(i) > 1} if len(got) != len(
        set(got)) else set()
    if dups:
        problems.append(f"kill-primary: duplicated events {sorted(dups)[:3]}")
    topo = ev.topology_status()
    promoted = [s for s in topo["perShard"] if s["epoch"] >= 1
                and s["primary"] == "b"]
    if len(promoted) != SHARDS:
        problems.append(f"kill-primary: expected {SHARDS} promoted shards, "
                        f"topology={topo}")
    # ingestion continues through the promotion: new events ack again
    # (the follower re-creates + re-syncs the yanked node)
    res = ev.insert_json_batch(
        [{"event": "buy", "entityType": "user", "entityId": f"p{k}",
          "eventId": f"post-{k}"} for k in range(40)], APP_ID)
    bad = [r for r in res if r.get("status") != 201]
    if bad:
        problems.append(f"kill-primary: post-promotion ingest NACKed: {bad[:2]}")
    got2 = {e.event_id for e in ev.scan(APP_ID)}
    if not {f"post-{k}" for k in range(40)} <= got2:
        problems.append("kill-primary: post-promotion events not readable")
    ev.close()
    if not problems:
        print(f"ok: kill-primary — {len(acked)} acked events survived "
              f"promotion exactly once, ingest continued")
    return set(acked) | {f"post-{k}" for k in range(40)}


def phase_torn_replica(root: str, acked_ids: set, problems: list) -> set:
    """Tear replica tails both ways; the follower heals and ingest keeps
    acking; replica ends byte-identical to primary."""
    from predictionio_tpu.storage.sharded import ShardedEvents

    ev = ShardedEvents(root, shards=SHARDS, replicas=2)
    before = len(problems)
    # current primaries are node b (promoted in phase 1); replicas are a
    topo = ev.topology_status()
    segs = []
    for s in topo["perShard"]:
        k = s["shard"]
        rep = "a" if s["primary"] == "b" else "b"
        rdir = Path(root) / f"shard_{k:02d}" / rep
        segs.extend(sorted(rdir.glob("events/app_*/*/seg-*.jsonl")))
    if len(segs) < 2:
        problems.append(f"torn-replica: expected ≥2 replica segments, "
                        f"found {len(segs)}")
        ev.close()
        return acked_ids
    # injection 1: garbage appended past the acked offset (torn copy)
    with open(segs[0], "ab") as f:
        f.write(b'{"eventId": "torn-garbage", "event": "bu')
    # injection 2: tear an acked suffix off (replica lost durable bytes)
    sz = segs[1].stat().st_size
    with open(segs[1], "rb+") as f:
        f.truncate(max(0, sz - 17))
    res = ev.insert_json_batch(
        [{"event": "buy", "entityType": "user", "entityId": f"t{k}",
          "eventId": f"torn-{k}"} for k in range(30)], APP_ID)
    if any(r.get("status") != 201 for r in res):
        problems.append("torn-replica: ingest NACKed while healing")
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(s["replicaLagEvents"] == 0
               for s in ev.topology_status()["perShard"]):
            break
        time.sleep(0.05)
    # replica must be byte-identical to the primary's complete lines
    for s in ev.topology_status()["perShard"]:
        k = s["shard"]
        pri, rep = s["primary"], ("a" if s["primary"] == "b" else "b")
        proot = Path(root) / f"shard_{k:02d}" / pri
        rroot = Path(root) / f"shard_{k:02d}" / rep
        for seg in sorted(proot.glob("events/app_*/*/seg-*.jsonl")):
            rel = seg.relative_to(proot)
            want = seg.read_bytes()
            got = (rroot / rel).read_bytes() if (rroot / rel).exists() else b""
            if got != want:
                problems.append(
                    f"torn-replica: {rel} diverges "
                    f"(replica {len(got)}B vs primary {len(want)}B)")
    got = [e.event_id for e in ev.scan(APP_ID)]
    if "torn-garbage" in got:
        problems.append("torn-replica: injected garbage line surfaced")
    missing = (acked_ids | {f"torn-{k}" for k in range(30)}) - set(got)
    if missing:
        problems.append(f"torn-replica: events lost: {sorted(missing)[:3]}")
    ev.close()
    if len(problems) == before:
        print("ok: torn-replica — both tears healed, replica byte-identical, "
              "ingest kept acking")
    return acked_ids | {f"torn-{k}" for k in range(30)}


def phase_partition_mid_scan(root: str, acked_ids: set,
                             problems: list) -> None:
    """Rename a shard's primary away while a fan-out scan is mid-flight:
    the scan promotes and still yields every surviving event once."""
    from predictionio_tpu.storage.sharded import ShardedEvents

    ev = ShardedEvents(root, shards=SHARDS, replicas=2)
    before = len(problems)
    topo = ev.topology_status()
    victim = topo["perShard"][0]
    vdir = Path(root) / "shard_00" / victim["primary"]
    seen = []
    it = ev.scan(APP_ID)
    for _ in range(5):          # partially consume, then partition
        seen.append(next(it).event_id)
    shutil.move(str(vdir), str(vdir) + ".partitioned")
    try:
        seen.extend(e.event_id for e in it)
    except OSError as e:
        problems.append(f"partition-mid-scan: scan died instead of "
                        f"failing over: {e}")
    if len(seen) != len(set(seen)):
        problems.append("partition-mid-scan: duplicates after mid-scan "
                        "failover")
    missing = acked_ids - set(seen)
    if missing:
        problems.append(
            f"partition-mid-scan: {len(missing)} acked events missing "
            f"(e.g. {sorted(missing)[:3]})")
    new_topo = ev.topology_status()
    if new_topo["perShard"][0]["epoch"] <= victim["epoch"]:
        problems.append("partition-mid-scan: shard 0 never promoted")
    # re-sync after the partition drains to 0 on every shard
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(s["replicaLagEvents"] == 0
               for s in ev.topology_status()["perShard"]):
            break
        time.sleep(0.05)
    lags = {s["shard"]: s["replicaLagEvents"]
            for s in ev.topology_status()["perShard"]}
    if any(lags.values()):
        problems.append(f"partition-mid-scan: replica lag never drained "
                        f"to 0: {lags}")
    from predictionio_tpu.storage.sharded import _M_REPL_LAG

    for k in range(SHARDS):
        if _M_REPL_LAG.value(shard=str(k)) != 0:
            problems.append(
                f"pio_store_replica_lag_events{{shard={k}}} != 0 after drain")
    ev.close()
    if len(problems) == before:
        print("ok: partition-mid-scan — scan failed over, exactly-once "
              "preserved, lag drained to 0")


def main() -> int:
    # env mutations live HERE, not at module level: bench.py and the
    # tests import writer_script without inheriting PIO_FSYNC=always
    os.environ["PIO_FSYNC"] = "always"
    os.environ.setdefault("PIO_JAX_PLATFORM", "cpu")
    problems: list = []
    tmp = tempfile.mkdtemp(prefix="pio-failover-")
    try:
        acked = phase_kill_primary(tmp, problems)
        acked = phase_torn_replica(tmp, acked, problems)
        phase_partition_mid_scan(tmp, acked, problems)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if not problems:
        print("ok: store failover drill clean — zero acked-event loss, "
              "zero duplicates, promotion + re-sync verified")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
