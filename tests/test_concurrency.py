"""Concurrency stress: threaded ingest + concurrent indexed reads + storage
metadata races (the reference leans on JVM immutability/Akka — SURVEY.md §5
'race detection: none'; here the locks and the incremental entity index are
exercised directly)."""

import threading

from predictionio_tpu.events.event import Event
from predictionio_tpu.storage.localfs import FSEvents
from predictionio_tpu.storage.sql import SQLClient, SQLApps, SQLEvents
from predictionio_tpu.storage.base import App


N_WRITERS = 4
N_READERS = 4
EVENTS_PER_WRITER = 200


def _mk_event(w: int, k: int) -> Event:
    return Event(event="view", entity_type="user", entity_id=f"u{w}",
                 target_entity_type="item", target_entity_id=f"i{w}-{k}")


def test_localfs_concurrent_ingest_and_indexed_reads(tmp_path):
    ev = FSEvents(tmp_path)
    ev.init(1)
    errors = []
    stop = threading.Event()

    def writer(w: int):
        try:
            for k in range(EVENTS_PER_WRITER):
                ev.insert(_mk_event(w, k), 1)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader(r: int):
        try:
            while not stop.is_set():
                got = list(ev.find(1, entity_type="user", entity_id=f"u{r % N_WRITERS}"))
                # monotone: never see duplicates or foreign entities
                ids = [e.target_entity_id for e in got]
                assert len(ids) == len(set(ids))
                assert all(i.startswith(f"i{r % N_WRITERS}-") for i in ids)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(w,)) for w in range(N_WRITERS)]
    readers = [threading.Thread(target=reader, args=(r,)) for r in range(N_READERS)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors, errors
    # final consistency: every write is indexed
    for w in range(N_WRITERS):
        got = list(ev.find(1, entity_type="user", entity_id=f"u{w}"))
        assert len(got) == EVENTS_PER_WRITER


def test_sql_concurrent_ingest(tmp_path):
    client = SQLClient(str(tmp_path / "ev.db"))
    ev = SQLEvents(client)
    ev.init(1)
    errors = []

    def writer(w: int):
        try:
            ev.insert_batch([_mk_event(w, k) for k in range(EVENTS_PER_WRITER)], 1)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(N_WRITERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(list(ev.find(1))) == N_WRITERS * EVENTS_PER_WRITER


def test_sql_app_insert_race_unique_names(tmp_path):
    """Concurrent duplicate app creates: exactly one wins, the rest get None
    and the connection is left usable (rollback path)."""
    client = SQLClient(str(tmp_path / "meta.db"))
    apps = SQLApps(client)
    results = []

    def create():
        results.append(apps.insert(App(0, "TheApp")))

    threads = [threading.Thread(target=create) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [r for r in results if r is not None]
    assert len(winners) == 1
    # connection still healthy after rollbacks
    assert apps.get_by_name("TheApp").id == winners[0]
    assert apps.insert(App(0, "Another")) is not None
