"""Event-loop front-end behavior the thread-per-connection stack never
had to state explicitly: slow-client robustness (a stalled connection
must cost a buffer, not a thread, and must never stall other
connections) and keep-alive/pipelining semantics (ordered responses,
per-request X-Request-IDs, Connection: close honored mid-pipeline,
errors never advertising keep-alive)."""

import json
import socket
import struct
import time

import pytest

from predictionio_tpu.api.event_server import run_event_server
from predictionio_tpu.storage import AccessKey, App


@pytest.fixture()
def es(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "asyncapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    yield {"port": httpd.server_address[1], "key": key}
    httpd.shutdown()
    httpd.server_close()


def _connect(port):
    s = socket.create_connection(("127.0.0.1", port))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _post_bytes(key, eid="u1", body_extra=""):
    body = json.dumps({"event": "buy", "entityType": "user",
                       "entityId": eid, "targetEntityType": "item",
                       "targetEntityId": "i1"}).encode()
    return (b"POST /events.json?accessKey=" + key.encode() +
            b" HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(body) + body)


def _read_responses(sock, n, timeout=20.0):
    """Read exactly n HTTP responses off the socket; returns a list of
    (status, headers_dict, body_bytes) in wire order."""
    sock.settimeout(timeout)
    buf = b""
    out = []
    while len(out) < n:
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise AssertionError(
                    f"connection closed after {len(out)}/{n} responses")
            buf += chunk
        head, _, buf = buf.partition(b"\r\n\r\n")
        lines = head.decode("latin-1").split("\r\n")
        status = int(lines[0].split(" ", 2)[1])
        headers = {}
        for ln in lines[1:]:
            name, _, value = ln.partition(":")
            headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", 0))
        while len(buf) < length:
            chunk = sock.recv(65536)
            if not chunk:
                raise AssertionError("closed mid-body")
            buf += chunk
        out.append((status, headers, buf[:length]))
        buf = buf[length:]
    return out


# -- slow clients -------------------------------------------------------------

def test_slowloris_partial_header_does_not_stall_others(es):
    """A connection dribbling half a request line holds only its own
    buffer; requests on other connections are served immediately (the
    old stack parked a whole thread on the slow read — survivable; an
    event loop that blocked on it would stall EVERY connection)."""
    slow = _connect(es["port"])
    slow.sendall(b"GET / HT")          # partial request line, no CRLF
    fast = _connect(es["port"])
    t0 = time.perf_counter()
    fast.sendall(_post_bytes(es["key"]))
    (status, _h, _b), = _read_responses(fast, 1)
    elapsed = time.perf_counter() - t0
    assert status == 201
    assert elapsed < 5.0, f"fast request stalled {elapsed:.1f}s behind slowloris"
    # the slow connection is still open (idle reap is minutes by default)
    slow.sendall(b"TP/1.1\r\nHost: x\r\n\r\n")
    (status, _h, _b), = _read_responses(slow, 1)
    assert status == 200               # dribbled request completes fine
    slow.close()
    fast.close()


def test_partial_body_completes_and_others_proceed(es):
    """Headers + half the body, long pause mid-POST: other connections
    proceed; the dribbled body still lands as one event."""
    body = json.dumps({"event": "buy", "entityType": "user",
                       "entityId": "slowbody", "targetEntityType": "item",
                       "targetEntityId": "i9"}).encode()
    head = (b"POST /events.json?accessKey=" + es["key"].encode() +
            b" HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(body))
    slow = _connect(es["port"])
    slow.sendall(head + body[: len(body) // 2])
    fast = _connect(es["port"])
    fast.sendall(_post_bytes(es["key"], eid="fastu"))
    (status, _h, _b), = _read_responses(fast, 1)
    assert status == 201
    fast.close()
    slow.sendall(body[len(body) // 2:])
    (status, _h, payload), = _read_responses(slow, 1)
    assert status == 201 and b"eventId" in payload
    slow.close()


def test_idle_connection_reaped_by_loop(mem_storage, monkeypatch):
    """With a short PIO_HTTP_IDLE_S, a parked connection (here: one that
    never finishes its headers) is closed by the loop's reap pass — no
    per-connection reaper thread involved."""
    monkeypatch.setenv("PIO_HTTP_IDLE_S", "1")
    mem_storage.apps.insert(App(0, "reapapp"))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    try:
        s = _connect(httpd.server_address[1])
        s.sendall(b"GET / HT")        # stalled slowloris partial
        s.settimeout(10)
        t0 = time.perf_counter()
        assert s.recv(1024) == b""    # server closes us, no response owed
        assert time.perf_counter() - t0 < 8.0
        s.close()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_mid_response_disconnect_does_not_poison_server(es):
    """A client that sends a request and resets the connection without
    reading the response must not wedge or crash the loop: subsequent
    connections serve normally."""
    for _ in range(3):
        c = _connect(es["port"])
        c.sendall(_post_bytes(es["key"], eid="ghost"))
        # SO_LINGER 0: close() sends RST — the write side of the response
        # will fail inside the server
        c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        c.close()
    time.sleep(0.2)
    ok = _connect(es["port"])
    ok.sendall(_post_bytes(es["key"], eid="alive"))
    (status, _h, _b), = _read_responses(ok, 1)
    assert status == 201
    ok.close()


# -- pipelining + keep-alive semantics ---------------------------------------

def test_pipelined_responses_ordered_with_distinct_rids(es):
    """Mixed-method pipelined requests are answered strictly in request
    order, and every response carries its OWN minted X-Request-ID."""
    wire = (_post_bytes(es["key"], eid="p1")
            + b"GET / HTTP/1.1\r\nHost: x\r\n\r\n"
            + b"GET /nope.json HTTP/1.1\r\nHost: x\r\n\r\n"
            + _post_bytes(es["key"], eid="p2"))
    s = _connect(es["port"])
    s.sendall(wire)
    resps = _read_responses(s, 4)
    # /nope.json is 401 on the event server: auth precedes routing
    assert [r[0] for r in resps] == [201, 200, 401, 201]
    rids = [r[1].get("x-request-id") for r in resps]
    assert all(rids), rids
    assert len(set(rids)) == 4, f"request ids not per-request: {rids}"
    s.close()


def test_pipelined_client_rids_echoed_in_order(es):
    """Client-supplied X-Request-IDs on pipelined requests come back on
    exactly their own responses."""
    reqs = b""
    for k in range(5):
        reqs += (b"GET / HTTP/1.1\r\nHost: x\r\nX-Request-ID: pipe-%d\r\n"
                 b"\r\n" % k)
    s = _connect(es["port"])
    s.sendall(reqs)
    resps = _read_responses(s, 5)
    assert [r[1]["x-request-id"] for r in resps] == [
        f"pipe-{k}" for k in range(5)]
    s.close()


def test_connection_close_honored_mid_pipeline(es):
    """A Connection: close request mid-pipeline is the LAST one served:
    its response says close, the socket closes, and the pipelined
    requests after it are never answered (and never executed)."""
    wire = (b"GET / HTTP/1.1\r\nHost: x\r\n\r\n"
            + b"GET / HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
            + _post_bytes(es["key"], eid="never-processed"))
    s = _connect(es["port"])
    s.sendall(wire)
    resps = _read_responses(s, 2)
    assert resps[0][0] == 200
    assert resps[0][1]["connection"] == "keep-alive"
    assert resps[1][0] == 200
    assert resps[1][1]["connection"] == "close"
    s.settimeout(10)
    assert s.recv(1024) == b"", "socket should close after the close response"
    s.close()


def test_malformed_pipeline_errors_never_advertise_keepalive(es):
    """PR 1 contract preserved by the loop rewrite: early-error responses
    (malformed request line, bad Content-Length, oversized headers) say
    Connection: close and the socket actually closes."""
    cases = [
        b"GARBAGE\r\n\r\n",
        (b"POST /events.json HTTP/1.1\r\nHost: x\r\n"
         b"Content-Length: 1_0\r\n\r\n"),
        b"GET / HTTP/1.1\r\nHost: x\r\n" +
        b"".join(b"X-F-%d: y\r\n" % i for i in range(150)) + b"\r\n",
        # obs-fold continuation: would otherwise strip() into a fresh
        # header and desync the body boundary (smuggling vector)
        (b"POST /events.json HTTP/1.1\r\nHost: x\r\n"
         b"Content-Length: 27\r\nX-Foo: bar\r\n Content-Length: 7\r\n\r\n"),
        # conflicting repeated Content-Length: first-CL-wins proxies
        # would disagree with our last-wins dict
        (b"POST /events.json HTTP/1.1\r\nHost: x\r\n"
         b"Content-Length: 27\r\nContent-Length: 7\r\n\r\n"),
    ]
    for wire in cases:
        s = _connect(es["port"])
        s.sendall(wire)
        (status, headers, _b), = _read_responses(s, 1)
        assert status == 400, wire[:30]
        assert headers["connection"] == "close", wire[:30]
        s.settimeout(10)
        assert s.recv(1024) == b"", wire[:30]
        s.close()


def test_pipeline_after_close_marked_request_is_discarded(es):
    """Bytes pipelined after a Connection: close request must not be
    parsed as requests (no smuggled execution): the event that request
    would have created never lands."""
    wire = (b"GET / HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
            + _post_bytes(es["key"], eid="smuggled"))
    s = _connect(es["port"])
    s.sendall(wire)
    (status, headers, _b), = _read_responses(s, 1)
    assert status == 200 and headers["connection"] == "close"
    s.settimeout(10)
    assert s.recv(1024) == b""
    s.close()
    # the smuggled POST never executed
    check = _connect(es["port"])
    check.sendall(
        b"GET /events.json?accessKey=" + es["key"].encode() +
        b"&entityId=smuggled&entityType=user HTTP/1.1\r\nHost: x\r\n\r\n")
    (status, _h, payload), = _read_responses(check, 1)
    assert status == 200 and json.loads(payload) == []
    check.close()


def test_expect_100_continue_interim_response(es):
    """A deferred body behind Expect: 100-continue gets the interim
    response first, then the final one — both in order on the wire."""
    body = json.dumps({"event": "buy", "entityType": "user",
                       "entityId": "expects", "targetEntityType": "item",
                       "targetEntityId": "i1"}).encode()
    s = _connect(es["port"])
    s.sendall(b"POST /events.json?accessKey=" + es["key"].encode() +
              b" HTTP/1.1\r\nHost: x\r\nExpect: 100-continue\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: %d\r\n\r\n" % len(body))
    s.settimeout(10)
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(65536)
    assert buf.startswith(b"HTTP/1.1 100 Continue")
    s.sendall(body)
    (status, _h, payload), = _read_responses(s, 1)
    assert status == 201 and b"eventId" in payload
    s.close()


def test_oversized_body_refused_without_buffering(mem_storage, monkeypatch):
    """A Content-Length over PIO_HTTP_MAX_BODY is refused with 413 +
    close at header-parse time — the loop never buffers the body."""
    monkeypatch.setenv("PIO_HTTP_MAX_BODY", "1024")
    app_id = mem_storage.apps.insert(App(0, "bigapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    try:
        s = _connect(httpd.server_address[1])
        s.sendall(b"POST /events.json?accessKey=" + key.encode() +
                  b" HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Length: 10485760\r\n\r\n")
        (status, headers, _b), = _read_responses(s, 1)
        assert status == 413
        assert headers["connection"] == "close"
        s.close()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_pipelined_queries_batch_parity(tmp_path, mem_storage, monkeypatch):
    """Cross-request micro-batching fed by a pipelined client: queries in
    flight on ONE socket coalesce through the batcher (PIO_SERVE_BATCH=on)
    and the responses still come back in order, matching the unbatched
    answers item-for-item."""
    import numpy as np

    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.models.recommendation import RecommendationEngine
    from predictionio_tpu.sdk import EngineClient
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import deploy

    app_id = mem_storage.apps.insert(App(0, "pipeq"))
    rng = np.random.default_rng(11)
    events = []
    for u in range(20):
        for i in rng.integers(0, 30, 8):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))})))
    mem_storage.l_events.insert_batch(events, app_id)
    variant = {
        "id": "pipeq-engine",
        "engineFactory":
            "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "pipeq"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 4, "numIterations": 3, "lambda": 0.05, "meshDp": 1}}],
    }
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps(variant))
    engine = RecommendationEngine.apply()
    ep = engine.engine_params_from_variant(variant)
    core_workflow.run_train(engine, ep, engine_id="pipeq-engine",
                            storage=mem_storage)

    def run(batch_mode):
        monkeypatch.setenv("PIO_SERVE_BATCH", batch_mode)
        httpd = deploy(engine_json=str(ej), host="127.0.0.1", port=0,
                       storage=mem_storage, background=True)
        try:
            assert (httpd.pio_state.batcher is not None) == (
                batch_mode == "on")
            client = EngineClient(
                f"http://127.0.0.1:{httpd.server_address[1]}")
            with client.pipeline(depth=20) as p:
                handles = [p.send_query({"user": f"u{u}", "num": 5})
                           for u in range(20)]
            return [[r["item"] for r in h.result()["itemScores"]]
                    for h in handles]
        finally:
            httpd.shutdown()
            httpd.server_close()

    assert run("on") == run("off")
