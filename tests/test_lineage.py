"""Generation-lineage coverage: cross-process record merge and outcome
computation, SIGKILLed-publisher abandonment via supersession, stale
sibling-file eviction from every merge (lineage + traces), the RSS seed
at worker start, the local metrics time-series ring, the SLO burn-rate
engine's verdicts, and the 2-worker prefork lineage roundtrip script."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from predictionio_tpu.obs import lineage as obs_lineage
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs.lineage import LineageRecorder, merge_records

REPO = Path(__file__).resolve().parent.parent


def _frag(lid, start, stages, outcome=None, origin=None, generation=None):
    doc = {"lid": lid, "start": start, "stages": stages}
    if outcome:
        doc["outcome"] = outcome
    if origin:
        doc["origin"] = origin
    if generation is not None:
        doc["generation"] = generation
    return doc


def _stage(name, start, worker="w", duration_s=0.01, **extra):
    return {"stage": name, "start": start, "duration_s": duration_s,
            "worker": worker, **extra}


class TestMergeRecords:
    def test_complete_needs_publish_install_and_first_serve(self):
        recs = merge_records([
            _frag("ln-a", 100.0,
                  [_stage("append_observed", 100.0, "pub"),
                   _stage("publish", 100.5, "pub")],
                  outcome="published", origin="pub", generation=7),
            _frag("ln-a", 100.0,
                  [_stage("install", 101.0, "w1"),
                   _stage("first_serve", 101.2, "w1")]),
        ])
        assert len(recs) == 1
        rec = recs[0]
        assert rec["outcome"] == "complete"
        assert rec["generation"] == 7
        assert rec["origin"] == "pub"
        assert rec["workers"] == ["pub", "w1"]
        # end-to-end duration spans the last stage's end
        assert rec["durationMs"] == pytest.approx(
            (101.2 + 0.01 - 100.0) * 1e3, abs=1.0)

    def test_published_without_worker_stages(self):
        recs = merge_records([
            _frag("ln-b", 50.0, [_stage("publish", 50.4, "pub")],
                  outcome="published")])
        assert recs[0]["outcome"] == "published"

    def test_open_record_superseded_by_newer_publish_is_abandoned(self):
        recs = merge_records([
            _frag("ln-dead", 10.0,
                  [_stage("append_observed", 10.0, "pub")]),
            _frag("ln-live", 20.0, [_stage("publish", 20.3, "pub")],
                  outcome="published"),
        ])
        by = {r["lid"]: r for r in recs}
        assert by["ln-dead"]["outcome"] == "abandoned"
        assert by["ln-live"]["outcome"] == "published"
        # newest first
        assert [r["lid"] for r in recs] == ["ln-live", "ln-dead"]

    def test_newest_open_record_stays_open(self):
        recs = merge_records([
            _frag("ln-old", 10.0, [_stage("publish", 10.1, "pub")],
                  outcome="published"),
            _frag("ln-new", 30.0,
                  [_stage("append_observed", 30.0, "pub")]),
        ])
        by = {r["lid"]: r for r in recs}
        assert by["ln-new"]["outcome"] == "open"

    def test_stage_dedupe_across_own_file_reread(self):
        s = _stage("publish", 5.0, "pub")
        recs = merge_records([
            _frag("ln-c", 5.0, [s], outcome="published"),
            _frag("ln-c", 5.0, [dict(s)]),   # same stage via file re-read
        ])
        assert len(recs[0]["stages"]) == 1


class TestRecorderCrossProcess:
    def test_sibling_merge_reunites_publisher_and_worker(self, tmp_path):
        pub = LineageRecorder(directory=tmp_path, tag="pub-1", enabled=True)
        worker = LineageRecorder(directory=tmp_path, tag="w1-1",
                                 enabled=True)
        lid = pub.new_id()
        t0 = time.time()
        pub.begin(lid, start=t0)
        pub.stage(lid, "append_observed", start=t0, duration_s=0.01)
        pub.stage(lid, "publish", start=t0 + 0.1, duration_s=0.02)
        pub.note_generation(lid, 3)
        pub.close(lid, outcome="published")
        worker.stage(lid, "install", start=t0 + 0.3, duration_s=0.01,
                     flush=True)
        worker.stage(lid, "cache_invalidation", parent="install",
                     start=t0 + 0.3, duration_s=0.001, flush=True)
        worker.stage(lid, "first_serve", start=t0 + 0.4, duration_s=0.005,
                     flush=True)
        # either side's merged view sees the whole record
        for rec in (pub, worker):
            doc = rec.get(lid)
            assert doc is not None
            assert doc["outcome"] == "complete"
            assert doc["generation"] == 3
            assert doc["origin"] == "pub-1"
            assert set(doc["workers"]) == {"pub-1", "w1-1"}
            kids = [s for s in doc["stages"]
                    if s["stage"] == "cache_invalidation"]
            assert kids and kids[0]["parent"] == "install"
        assert worker.get_generation(3)["lid"] == lid
        entry = worker.index()["records"][0]
        assert entry["lid"] == lid and entry["outcome"] == "complete"
        text = obs_lineage.render_lineage_text(worker.get(lid))
        for name in ("publish", "install", "first_serve"):
            assert name in text

    def test_disabled_recorder_records_nothing(self, tmp_path):
        rec = LineageRecorder(directory=tmp_path, tag="w", enabled=False)
        lid = rec.new_id()
        rec.begin(lid)
        rec.stage(lid, "publish", flush=True)
        assert rec.merged() == []
        assert list(tmp_path.glob("*.json")) == []

    def test_sigkill_publisher_leaves_abandoned_record(self, tmp_path):
        """A publisher SIGKILLed mid-publish must leave its open record
        on disk; the merge closes it as ``abandoned`` once the NEXT
        generation reaches publish — no cooperation from the corpse."""
        child_src = (
            "import sys, time\n"
            "sys.path.insert(0, %r)\n"
            "from predictionio_tpu.obs.lineage import LineageRecorder\n"
            "rec = LineageRecorder(directory=%r, tag='pub-dead', "
            "enabled=True)\n"
            "lid = rec.new_id()\n"
            "rec.begin(lid, start=time.time())\n"
            "rec.stage(lid, 'append_observed', duration_s=0.01, "
            "flush=True)\n"
            "print(lid, flush=True)\n"
            "time.sleep(120)\n" % (str(REPO), str(tmp_path)))
        proc = subprocess.Popen(
            [sys.executable, "-c", child_src],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "PIO_METRICS": "off"})
        try:
            dead_lid = proc.stdout.readline().strip()
            assert dead_lid.startswith("ln-")
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        # the next fold tick in a fresh publisher supersedes it
        nxt = LineageRecorder(directory=tmp_path, tag="pub-2", enabled=True)
        lid2 = nxt.new_id()
        nxt.begin(lid2, start=time.time())
        nxt.stage(lid2, "publish", duration_s=0.02)
        nxt.note_generation(lid2, 9)
        nxt.close(lid2, outcome="published")
        worker = LineageRecorder(directory=tmp_path, tag="w0-2",
                                 enabled=True)
        worker.stage(lid2, "install", duration_s=0.01, flush=True)
        worker.stage(lid2, "first_serve", duration_s=0.01, flush=True)
        by = {r["lid"]: r for r in worker.merged()}
        assert by[dead_lid]["outcome"] == "abandoned"
        assert by[dead_lid]["origin"] == "pub-dead"
        assert by[lid2]["outcome"] == "complete"


class TestStaleSiblingEviction:
    def _age(self, path: Path, by_s: float = 3600.0):
        old = time.time() - by_s
        os.utime(path, (old, old))

    def test_lineage_merge_evicts_dead_sibling(self, tmp_path):
        dead = tmp_path / "w9-dead.json"
        dead.write_text(json.dumps({
            "worker": "w9-dead",
            "records": [_frag("ln-ghost", 1.0,
                              [_stage("publish", 1.0, "w9-dead")],
                              outcome="published")]}))
        self._age(dead)
        before = obs_metrics.STALE_SIBLINGS.value(kind="lineage")
        rec = LineageRecorder(directory=tmp_path, tag="w0", enabled=True)
        lids = {r["lid"] for r in rec.merged()}
        assert "ln-ghost" not in lids
        assert not dead.exists()
        assert obs_metrics.STALE_SIBLINGS.value(kind="lineage") == before + 1

    def test_lineage_merge_never_evicts_own_file(self, tmp_path):
        rec = LineageRecorder(directory=tmp_path, tag="w0", enabled=True)
        lid = rec.new_id()
        rec.begin(lid)
        own = tmp_path / "w0.json"
        assert own.exists()
        self._age(own)
        rec.merged()
        assert own.exists()

    def test_trace_merge_evicts_dead_sibling(self, tmp_path):
        from predictionio_tpu.obs.tracing import FlightRecorder

        dead = tmp_path / "w9-dead.json"
        dead.write_text(json.dumps({
            "worker": "w9-dead",
            "traces": [{"rid": "ghost-rid", "start": 1.0, "durationMs": 1,
                        "spans": []}]}))
        self._age(dead)
        before = obs_metrics.STALE_SIBLINGS.value(kind="traces")
        rec = FlightRecorder(directory=tmp_path, tag="w0", enabled=True)
        rids = {t.get("rid") for t in rec._sibling_docs()}
        assert "ghost-rid" not in rids
        assert not dead.exists()
        assert obs_metrics.STALE_SIBLINGS.value(kind="traces") == before + 1


def test_mark_worker_up_seeds_rss():
    if not os.path.exists("/proc/self/statm"):
        pytest.skip("no /proc on this platform")
    obs_metrics.mark_worker_up("rss-seed-test")
    assert obs_metrics.PROCESS_RSS.value(worker="rss-seed-test") > 0


class TestTsdb:
    def test_sampler_ring_reduces_and_bounds(self):
        from predictionio_tpu.obs.tsdb import MetricsSampler

        reg = obs_metrics.get_registry()
        c = reg.counter("pio_lineage_records_total", "x")
        sampler = MetricsSampler(interval=60.0, ring=4)
        c.inc()
        for _ in range(6):
            sampler.sample_now()
        samples = sampler.samples()
        assert len(samples) == 4   # bounded ring
        entry = samples[-1]["m"].get("pio_lineage_records_total")
        assert entry and entry["type"] == "counter"
        assert sum(entry["series"].values()) >= 1
        # histograms keep bucket bounds hoisted per metric, not per sample
        hist = sampler.history(limit=2)
        assert len(hist["samples"]) == 2
        assert "buckets" in hist and "intervalSeconds" in hist
        fold = samples[-1]["m"].get("pio_follow_fold_duration_seconds")
        if fold is not None:
            for v in fold["series"].values():
                assert set(v) == {"counts", "sum", "count"}


class TestSloEngine:
    CACHE_SLO = ({"name": "cache_audit", "kind": "counter_delta",
                  "metric": "pio_serve_cache_audit_mismatch_total",
                  "match": "", "threshold": 0.0, "help": "x"},)
    LAG_SLO = ({"name": "replica_lag", "kind": "gauge_max",
                "metric": "pio_store_replica_lag_events", "match": "",
                "threshold": 10000.0, "help": "x"},)

    @staticmethod
    def _counter_samples(values, t0=1000.0, dt=10.0):
        return [{"t": t0 + i * dt,
                 "m": {"pio_serve_cache_audit_mismatch_total": {
                     "type": "counter", "series": {"{}": float(v)}}}}
                for i, v in enumerate(values)]

    def test_no_data_on_empty_ring(self):
        from predictionio_tpu.obs.slo import SloEngine

        doc = SloEngine(self.CACHE_SLO).evaluate([], {})
        assert doc["status"] == "no_data"
        assert doc["slos"]["cache_audit"]["verdict"] == "no_data"

    def test_flat_counter_is_ok(self):
        from predictionio_tpu.obs.slo import SloEngine

        doc = SloEngine(self.CACHE_SLO).evaluate(
            self._counter_samples([3, 3, 3, 3, 3]), {})
        assert doc["status"] == "ok"

    def test_burning_requires_both_windows(self, monkeypatch):
        from predictionio_tpu.obs.slo import SloEngine

        monkeypatch.setenv("PIO_SLO_FAST_S", "60")
        monkeypatch.setenv("PIO_SLO_SLOW_S", "600")
        # every interval increments the mismatch counter: burn 10x in
        # BOTH windows -> burning
        doc = SloEngine(self.CACHE_SLO).evaluate(
            self._counter_samples([0, 1, 2, 3, 4]), {})
        v = doc["slos"]["cache_audit"]
        assert v["verdict"] == "burning"
        assert v["windows"]["fast"]["burn"] > 1
        assert v["windows"]["slow"]["burn"] > 1
        # violations confined to the OLD part of the ring: the slow
        # window still burns, the fast window is clean -> warn, not
        # burning (the multi-window pattern's whole point)
        values = [0, 5, 10, 15, 15, 15, 15, 15, 15, 15, 15, 15]
        doc = SloEngine(self.CACHE_SLO).evaluate(
            self._counter_samples(values, dt=30.0), {})
        v = doc["slos"]["cache_audit"]
        assert v["verdict"] == "warn"
        assert v["windows"]["fast"]["burn"] <= 1 \
            < v["windows"]["slow"]["burn"]

    def test_counter_restart_not_a_violation(self):
        from predictionio_tpu.obs.slo import SloEngine

        # a worker restart drops the total; delta<0 folds to c1 (=0
        # here), so the restart interval itself does not violate
        doc = SloEngine(self.CACHE_SLO).evaluate(
            self._counter_samples([5, 5, 0, 0, 0]), {})
        assert doc["status"] == "ok"

    def test_gauge_max_threshold(self):
        from predictionio_tpu.obs.slo import SloEngine

        def lag_samples(v):
            return [{"t": 1000.0 + i * 10,
                     "m": {"pio_store_replica_lag_events": {
                         "type": "gauge", "series": {"{}": float(v)}}}}
                    for i in range(5)]

        assert SloEngine(self.LAG_SLO).evaluate(
            lag_samples(500), {})["status"] == "ok"
        assert SloEngine(self.LAG_SLO).evaluate(
            lag_samples(20000), {})["status"] == "burning"

    def test_burn_gauges_exported(self):
        from predictionio_tpu.obs.slo import SloEngine

        SloEngine(self.CACHE_SLO).evaluate(
            self._counter_samples([0, 1, 2, 3]), {})
        reg = obs_metrics.get_registry()
        g = reg.gauge("pio_slo_burn_rate", "x")
        assert g.value(slo="cache_audit", window="fast") > 1


def test_check_lineage_roundtrip_script():
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "check_lineage_roundtrip.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok:" in r.stdout
