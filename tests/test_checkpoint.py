"""Checkpoint/resume + retry + fault injection (framework-added aux
subsystem; the reference only persists completed models — SURVEY.md §5)."""

import numpy as np
import pytest

from predictionio_tpu.utils.checkpoint import CheckpointStore, InjectedFault, maybe_inject


def test_checkpoint_roundtrip_and_prune(tmp_path):
    store = CheckpointStore(tmp_path / "ck", keep=2)
    for step in (1, 2, 3):
        store.save(step, {"w": np.full((2, 2), step, np.float32), "step": step})
    assert store.steps() == [2, 3]  # pruned to keep=2
    step, state = store.latest()
    assert step == 3 and state["step"] == 3
    np.testing.assert_array_equal(state["w"], np.full((2, 2), 3, np.float32))
    assert not (tmp_path / "ck" / "step_1.npz").exists()
    store.clear()
    assert store.latest() is None


def test_fault_injection(monkeypatch):
    monkeypatch.setenv("PIO_FAULT_INJECT", "my.site:2")
    maybe_inject("other.site")         # different site: no-op
    maybe_inject("my.site")            # hit 1 of 2: no-op
    with pytest.raises(InjectedFault):
        maybe_inject("my.site")        # hit 2: fires and disarms
    maybe_inject("my.site")            # disarmed


def test_als_checkpoint_resume_matches_straight_run(tmp_path):
    """5 sweeps + crash + resume to 10 == straight 10-sweep run."""
    from predictionio_tpu.ops.als import als_train, prepare_als_data
    from predictionio_tpu.utils.checkpoint import CheckpointStore

    rng = np.random.default_rng(0)
    n_u, n_i, n_e = 60, 40, 1500
    u = rng.integers(0, n_u, n_e).astype(np.int32)
    i = rng.integers(0, n_i, n_e).astype(np.int32)
    r = rng.integers(1, 6, n_e).astype(np.float32)
    data = prepare_als_data(u, i, r, n_u, n_i, dp=1)

    X_ref, Y_ref = als_train(data, k=6, reg=0.05, iterations=10)

    store = CheckpointStore(tmp_path / "als")
    # run that "dies" after 5 sweeps (snapshot exists)
    als_train(data, k=6, reg=0.05, iterations=5,
              checkpoint=store, checkpoint_every=5)
    assert store.steps() == [5]
    # resumed run completes the remaining sweeps from the snapshot
    X, Y = als_train(data, k=6, reg=0.05, iterations=10,
                     checkpoint=store, checkpoint_every=5)
    assert store.steps() == [5, 10]
    np.testing.assert_allclose(X, X_ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(Y, Y_ref, rtol=2e-4, atol=2e-5)


def test_run_train_retries_through_injected_fault(mem_storage, tmp_path, monkeypatch):
    """PIO_TRAIN_RETRIES + checkpointEvery: a mid-training fault is retried
    and the retry resumes from the snapshot instead of restarting."""
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.models.recommendation import RecommendationEngine
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.workflow import core_workflow

    app_id = mem_storage.apps.insert(App(0, "ckapp"))
    rng = np.random.default_rng(1)
    events = []
    for u in range(16):
        for i in range(10):
            if rng.random() < 0.9:
                liked = (u < 8) == (i < 5)
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": 5.0 if liked else 1.0})))
    mem_storage.l_events.insert_batch(events, app_id)

    variant = {
        "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "ckapp"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 4, "numIterations": 6, "lambda": 0.05, "meshDp": 1,
            "checkpointEvery": 2, "checkpointDir": str(tmp_path / "ck"),
        }}],
    }
    engine = RecommendationEngine.apply()
    ep = engine.engine_params_from_variant(variant)

    # fault fires on the 2nd sweep-chunk of the 1st attempt; retry resumes
    monkeypatch.setenv("PIO_FAULT_INJECT", "als.sweep:2")
    instance = core_workflow.run_train(
        engine, ep, engine_id="ck-engine", storage=mem_storage, retries=1,
    )
    assert instance.status == "COMPLETED"

    # without retries the same fault propagates and records FAILED
    monkeypatch.setenv("PIO_FAULT_INJECT", "als.sweep:1")
    with pytest.raises(InjectedFault):
        core_workflow.run_train(
            engine, ep, engine_id="ck-engine2", storage=mem_storage, retries=0,
        )
    failed = [i for i in mem_storage.engine_instances.get_all()
              if i.engine_id == "ck-engine2"]
    assert failed and failed[0].status == "FAILED"


def test_stale_snapshot_rejected(tmp_path):
    """A snapshot from different data/params (or one at >= iterations) is
    ignored: resume never returns foreign or over-trained factors."""
    from predictionio_tpu.ops.als import als_train, prepare_als_data
    from predictionio_tpu.utils.checkpoint import CheckpointStore

    rng = np.random.default_rng(2)
    u = rng.integers(0, 30, 500).astype(np.int32)
    i = rng.integers(0, 20, 500).astype(np.int32)
    r = rng.integers(1, 6, 500).astype(np.float32)
    data_a = prepare_als_data(u, i, r, 30, 20, dp=1)
    data_b = prepare_als_data(u, i, (6 - r), 30, 20, dp=1)  # different content

    store = CheckpointStore(tmp_path / "ck")
    als_train(data_a, k=4, reg=0.05, iterations=4, checkpoint=store, checkpoint_every=2)
    # same shapes, different ratings -> fingerprint mismatch -> fresh run
    X_b, _ = als_train(data_b, k=4, reg=0.05, iterations=4,
                       checkpoint=store, checkpoint_every=2)
    X_b_ref, _ = als_train(data_b, k=4, reg=0.05, iterations=4)
    np.testing.assert_allclose(X_b, X_b_ref, rtol=2e-4, atol=2e-5)

    # snapshot at step 4 >= iterations=2 -> fresh 2-sweep run, not stale factors
    X_2, _ = als_train(data_b, k=4, reg=0.05, iterations=2,
                       checkpoint=store, checkpoint_every=2)
    X_2_ref, _ = als_train(data_b, k=4, reg=0.05, iterations=2)
    np.testing.assert_allclose(X_2, X_2_ref, rtol=2e-4, atol=2e-5)


def test_fault_counter_keyed_by_config(monkeypatch):
    """A new PIO_FAULT_INJECT config starts counting from zero even after a
    previous config accumulated hits without firing."""
    monkeypatch.setenv("PIO_FAULT_INJECT", "a:3")
    maybe_inject("a"); maybe_inject("a")      # 2 hits, no fire
    monkeypatch.setenv("PIO_FAULT_INJECT", "b:2")
    maybe_inject("b")                          # hit 1 of 2: must NOT fire
    with pytest.raises(InjectedFault):
        maybe_inject("b")                      # hit 2: fires
