"""Storage backend tests (reference analogues: LEventsSpec, PEventsSpec,
metadata specs — SURVEY.md §4). Both backends run through the same suite."""

import datetime as dt

import pytest

from predictionio_tpu.events import DataMap, Event
from predictionio_tpu.storage import AccessKey, App, Channel, EngineInstance
from predictionio_tpu.storage.locator import Storage, StorageConfig


def ts(h):
    return dt.datetime(2026, 1, 1, h, tzinfo=dt.timezone.utc)


@pytest.fixture(params=["memory", "localfs", "sql", "sqlfile", "sharedfs",
                        "sharded"])
def storage(request, tmp_path):
    if request.param == "memory":
        src = {"type": "memory"}
    elif request.param == "localfs":
        src = {"type": "localfs", "path": str(tmp_path / "store")}
    elif request.param == "sql":
        src = {"type": "sql", "path": ":memory:"}
    elif request.param == "sharedfs":
        src = {"type": "sharedfs", "path": str(tmp_path / "shared")}
    elif request.param == "sharded":
        # 3 shards × 2 replicas: every generic storage test also runs
        # through entity routing, fan-out merge, and the semi-sync
        # replication barrier
        src = {"type": "sharded", "path": str(tmp_path / "sharded"),
               "shards": "3", "replicas": "2"}
    else:
        src = {"type": "sql", "path": str(tmp_path / "pio.db")}
    cfg = StorageConfig(
        sources={"S": src},
        repositories={"METADATA": "S", "EVENTDATA": "S", "MODELDATA": "S"},
    )
    st = Storage(cfg)
    yield st
    ev = st.l_events
    if hasattr(ev, "close"):
        ev.close()      # stop replication follower threads


def test_apps_crud(storage):
    app_id = storage.apps.insert(App(0, "myapp", "desc"))
    assert app_id is not None
    assert storage.apps.get(app_id).name == "myapp"
    assert storage.apps.get_by_name("myapp").id == app_id
    assert storage.apps.insert(App(0, "myapp")) is None  # duplicate name
    app2 = storage.apps.insert(App(0, "other"))
    assert app2 != app_id
    assert {a.name for a in storage.apps.get_all()} == {"myapp", "other"}
    assert storage.apps.delete(app2)
    assert storage.apps.get(app2) is None


def test_access_keys_and_channels(storage):
    app_id = storage.apps.insert(App(0, "a1"))
    key = storage.access_keys.insert(AccessKey("", app_id, ["buy"]))
    assert storage.access_keys.get(key).app_id == app_id
    assert storage.access_keys.get(key).events == ["buy"]
    assert len(storage.access_keys.get_by_app_id(app_id)) == 1

    ch = storage.channels.insert(Channel(0, "backfill", app_id))
    assert storage.channels.get(ch).name == "backfill"
    assert storage.channels.insert(Channel(0, "backfill", app_id)) is None
    assert storage.channels.get_by_app_id(app_id)[0].id == ch


def test_events_crud_and_filters(storage):
    ev = storage.l_events
    ev.init(1)
    events = [
        Event(event="view", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1", event_time=ts(1)),
        Event(event="buy", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i2", event_time=ts(2)),
        Event(event="view", entity_type="user", entity_id="u2",
              target_entity_type="item", target_entity_id="i1", event_time=ts(3)),
        Event(event="$set", entity_type="item", entity_id="i1",
              properties=DataMap({"cat": "x"}), event_time=ts(4)),
    ]
    ids = ev.insert_batch(events, 1)
    assert len(ids) == 4
    got = ev.get(ids[0], 1)
    assert got.event == "view" and got.target_entity_id == "i1"

    assert len(list(ev.find(1))) == 4
    assert len(list(ev.find(1, event_names=["view"]))) == 2
    assert len(list(ev.find(1, entity_type="user", entity_id="u1"))) == 2
    assert len(list(ev.find(1, start_time=ts(2), until_time=ts(4)))) == 2
    assert [e.event for e in ev.find(1, reversed_order=True)][0] == "$set"
    assert len(list(ev.find(1, limit=2))) == 2
    assert len(list(ev.find(1, target_entity_id="i1"))) == 2

    # channel isolation
    ev.insert(Event(event="view", entity_type="user", entity_id="u9",
                    event_time=ts(1)), 1, channel_id=7)
    assert len(list(ev.find(1))) == 4
    assert len(list(ev.find(1, channel_id=7))) == 1

    # delete
    assert ev.delete(ids[1], 1)
    assert not ev.delete(ids[1], 1) or storage is None  # second delete may be False
    assert len(list(ev.find(1))) == 3
    assert ev.get(ids[1], 1) is None


def test_aggregate_via_storage(storage):
    ev = storage.l_events
    ev.init(2)
    ev.insert(Event(event="$set", entity_type="item", entity_id="i1",
                    properties=DataMap({"a": 1}), event_time=ts(1)), 2)
    ev.insert(Event(event="$set", entity_type="item", entity_id="i1",
                    properties=DataMap({"b": 2}), event_time=ts(2)), 2)
    ev.insert(Event(event="$set", entity_type="user", entity_id="u1",
                    properties=DataMap({"z": 3}), event_time=ts(1)), 2)
    snap = ev.aggregate_properties(2, "item")
    assert snap == {"i1": {"a": 1, "b": 2}}


def test_engine_instances(storage):
    inst = EngineInstance(
        id="", status="INIT", start_time=ts(1), end_time=None,
        engine_id="e1", engine_version="1", engine_variant="default",
        engine_factory="f",
    )
    iid = storage.engine_instances.insert(inst)
    got = storage.engine_instances.get(iid)
    assert got.status == "INIT"
    got.status = "COMPLETED"
    got.end_time = ts(2)
    assert storage.engine_instances.update(got)
    latest = storage.engine_instances.get_latest_completed("e1", "1", "default")
    assert latest is not None and latest.id == iid
    # a later completed instance wins
    inst2 = EngineInstance(
        id="", status="COMPLETED", start_time=ts(5), end_time=ts(6),
        engine_id="e1", engine_version="1", engine_variant="default",
        engine_factory="f",
    )
    iid2 = storage.engine_instances.insert(inst2)
    assert storage.engine_instances.get_latest_completed("e1", "1", "default").id == iid2


def test_models_blob_store(storage):
    storage.models.insert("abc123", b"\x00\x01binary")
    assert storage.models.get("abc123") == b"\x00\x01binary"
    assert storage.models.delete("abc123")
    assert storage.models.get("abc123") is None


def test_sql_backend_durable_across_reopen(tmp_path):
    """Reference JDBC parity: a second client over the same database sees
    everything the first wrote (no in-process-only state)."""
    from predictionio_tpu.storage.sql import SQLSource

    db = str(tmp_path / "pio.db")
    s1 = SQLSource(db)
    app_id = s1.apps.insert(App(0, "durable"))
    s1.events.insert(
        Event(event="buy", entity_type="user", entity_id="u1", event_time=ts(1)),
        app_id,
    )
    s1.models.insert("m1", b"blob")
    s1.client.conn.close()

    s2 = SQLSource(db)
    assert s2.apps.get_by_name("durable").id == app_id
    assert len(list(s2.events.find(app_id))) == 1
    assert s2.models.get("m1") == b"blob"


def test_pevents_find_batches(storage):
    ev = storage.l_events
    ev.init(3)
    for k in range(10):
        ev.insert(Event(event="view", entity_type="user", entity_id=f"u{k % 3}",
                        target_entity_type="item", target_entity_id=f"i{k % 4}",
                        event_time=ts(k % 23)), 3)
    batches = list(storage.p_events.find_batches(3, batch_size=4))
    if hasattr(storage.p_events, "topology_status"):
        # the sharded backend serves snapshot-first: one merged columnar
        # batch per scan (same contract as localfs with a built snapshot)
        assert sum(len(b) for b in batches) == 10
    else:
        assert [len(b) for b in batches] == [4, 4, 2]
    assert all(b.target_ids.min() >= 0 for b in batches)


def test_localfs_entity_index(tmp_path):
    """Per-entity find uses the incremental index: correct across appends
    from a second FSEvents handle (another process), segment rotations, and
    tombstones."""
    import predictionio_tpu.storage.localfs as lfs
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.storage.localfs import FSEvents

    old = lfs.SEGMENT_MAX_BYTES
    lfs.SEGMENT_MAX_BYTES = 600  # force rotation
    try:
        ev = FSEvents(tmp_path)
        ev.init(1)
        for k in range(40):
            ev.insert(Event(event="view", entity_type="user", entity_id=f"u{k % 4}",
                            target_entity_type="item", target_entity_id=f"i{k}"), 1)
        got = list(ev.find(1, entity_type="user", entity_id="u1"))
        assert len(got) == 10
        assert all(e.entity_id == "u1" for e in got)

        # appends through a different handle (simulates the ingest process)
        writer = FSEvents(tmp_path)
        writer.insert(Event(event="view", entity_type="user", entity_id="u1",
                            target_entity_type="item", target_entity_id="i99"), 1)
        got = list(ev.find(1, entity_type="user", entity_id="u1"))
        assert len(got) == 11
        assert any(e.target_entity_id == "i99" for e in got)

        # tombstoned events disappear from indexed reads
        victim = got[0].event_id
        assert ev.delete(victim, 1)
        got = list(ev.find(1, entity_type="user", entity_id="u1"))
        assert len(got) == 10 and victim not in [e.event_id for e in got]

        # limit + reversed ordering still applies on the indexed path
        latest = list(ev.find(1, entity_type="user", entity_id="u1",
                              limit=3, reversed_order=True))
        assert len(latest) == 3
        times = [e.event_time for e in latest]
        assert times == sorted(times, reverse=True)
    finally:
        lfs.SEGMENT_MAX_BYTES = old


def test_localfs_entity_index_survives_reimport(tmp_path):
    """data-delete + re-import from another handle must not leave the index
    pointing into dead bytes (regression guard: pre-index code re-scanned)."""
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.storage.localfs import FSEvents

    reader = FSEvents(tmp_path)
    reader.init(1)
    writer = FSEvents(tmp_path)   # separate handle = separate process
    writer.insert_batch(
        [Event(event="view", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id=f"old{k}")
         for k in range(20)], 1)
    assert len(list(reader.find(1, entity_type="user", entity_id="u1"))) == 20

    # operator wipes and re-imports a smaller log through the other handle
    writer.remove(1)
    writer.init(1)
    writer.insert_batch(
        [Event(event="view", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id="new0")], 1)
    got = list(reader.find(1, entity_type="user", entity_id="u1"))
    assert [e.target_entity_id for e in got] == ["new0"]

    # re-import a LARGER log (old offsets would point mid-file)
    writer.remove(1)
    writer.init(1)
    writer.insert_batch(
        [Event(event="view", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id=f"big{k}")
         for k in range(40)], 1)
    got = list(reader.find(1, entity_type="user", entity_id="u1"))
    assert len(got) == 40 and all(e.target_entity_id.startswith("big") for e in got)


def test_segment_writer_rotation_and_fsync_policies(tmp_path, monkeypatch):
    """The kept-open writer rotates segments at the size cap and honors
    every PIO_FSYNC durability policy without losing events."""
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.storage import localfs as lf

    monkeypatch.setattr(lf, "SEGMENT_MAX_BYTES", 4096)
    for policy in ("rotate", "always", "interval:5", "never"):
        monkeypatch.setenv("PIO_FSYNC", policy)
        root = tmp_path / f"s_{policy.replace(':', '_')}"
        ev = lf.FSEvents(root)
        ids = []
        for k in range(40):
            ids.extend(ev.insert_batch(
                [Event(event="buy", entity_type="user", entity_id=f"u{k}",
                       target_entity_type="item", target_entity_id=f"i{j}")
                 for j in range(5)], app_id=1))
        segs = ev.segment_paths(1)
        assert len(segs) > 1, f"no rotation under {policy}"
        got = sum(1 for _ in ev._iter_raw(1, None))
        assert got == 200 and len(set(ids)) == 200


# -- sharedfs: multi-host system-of-record ----------------------------------


def _shared_events(tmp_path, tag, monkeypatch=None):
    from predictionio_tpu.storage import sharedfs

    # a writer on another host = an instance with its own writer tag
    return sharedfs.SharedFSEvents(tmp_path / "shared", writer_tag=tag)


def test_sharedfs_concurrent_writers_one_log(tmp_path, monkeypatch):
    """Two writer processes (different hosts) ingest into the SAME (app,
    channel) concurrently; every reader sees the union, and segments never
    collide (per-writer naming)."""
    w1 = _shared_events(tmp_path, "hostA-1", monkeypatch)
    w2 = _shared_events(tmp_path, "hostB-2", monkeypatch)
    for k in range(30):
        w = w1 if k % 2 else w2
        w.insert_batch([Event(event="buy", entity_type="user",
                              entity_id=f"u{k}", target_entity_type="item",
                              target_entity_id=f"i{k % 7}", event_time=ts(k % 20))],
                       app_id=1)
    # a fresh reader (third host) sees all 30
    from predictionio_tpu.storage import sharedfs

    reader = sharedfs.SharedFSEvents(tmp_path / "shared")
    assert sum(1 for _ in reader._iter_raw(1, None)) == 30
    segs = reader.segment_paths(1)
    tags = {s.name.split("-")[1] for s in segs}
    assert tags == {"hostA", "hostB"}
    # tombstone from one writer hides the event for every reader
    victim = next(reader._iter_raw(1, None)).event_id
    assert w2.delete(victim, 1)
    assert all(e.event_id != victim for e in reader._iter_raw(1, None))


def test_sharedfs_host_sharded_scan_covers_log(tmp_path, monkeypatch):
    """distributed.shard_segments over sharedfs segments: every process
    reads a disjoint share and the union is the full log."""
    from predictionio_tpu.parallel import distributed as dist
    from predictionio_tpu.storage import localfs as lf, sharedfs

    monkeypatch.setattr(lf, "SEGMENT_MAX_BYTES", 2048)  # force rotations
    w1 = _shared_events(tmp_path, "hostA-1", monkeypatch)
    w2 = _shared_events(tmp_path, "hostB-2", monkeypatch)
    for k in range(200):
        (w1 if k % 2 else w2).insert_batch(
            [Event(event="buy", entity_type="user", entity_id=f"u{k}",
                   target_entity_type="item", target_entity_id=f"i{k % 11}")],
            app_id=1)
    reader = sharedfs.SharedFSEvents(tmp_path / "shared")
    segs = reader.segment_paths(1)
    assert len(segs) >= 4
    seen = []
    for pid in range(3):
        mine = dist.shard_segments(segs, n_processes=3, process_id=pid)
        for seg in mine:
            seen.extend(l for l in seg.read_text().splitlines() if l.strip())
    assert len(seen) == 200
    # disjoint: no segment assigned twice
    all_assigned = [s for pid in range(3)
                    for s in dist.shard_segments(segs, n_processes=3, process_id=pid)]
    assert len(all_assigned) == len(set(all_assigned)) == len(segs)


def test_sharedfs_native_scan_and_training(tmp_path, monkeypatch):
    """The native scanner + UR training run unchanged over per-writer
    sharedfs segments."""
    pytest.importorskip("predictionio_tpu.native")
    from predictionio_tpu.native import native_available
    if not native_available():
        pytest.skip("native scanner unavailable")
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage
    from predictionio_tpu.store.event_store import PEventStore

    storage = Storage(StorageConfig(
        sources={"S": {"type": "sharedfs", "path": str(tmp_path / "shared")}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA", "MODELDATA")},
    ))
    app_id = storage.apps.insert(App(0, "shapp"))
    evs = [Event(event="buy", entity_type="user", entity_id=f"u{k % 9}",
                 target_entity_type="item", target_entity_id=f"i{k % 5}")
           for k in range(60)]
    storage.l_events.insert_batch(evs, app_id)
    batch = PEventStore.batch("shapp", storage=storage)
    assert len(batch) == 60 and batch.prop_columns is not None


def test_sharedfs_app_insert_crash_recovery(tmp_path):
    """A crash between the name claim and the id claim leaves a repairable
    record: retrying the insert completes it instead of wedging the name."""
    from predictionio_tpu.storage import sharedfs

    apps = sharedfs.SharedApps(tmp_path / "shared")
    # simulate the crash: phase-1 record exists with id 0, no id claim
    from predictionio_tpu.storage.sharedfs import _safe_name

    apps._names.put_new(_safe_name("wedged"), {"id": 0, "name": "wedged",
                                               "description": ""})
    assert apps.get_by_name("wedged") is None  # incomplete → invisible
    app_id = apps.insert(App(0, "wedged", "retried"))
    assert app_id and apps.get_by_name("wedged").id == app_id
    assert apps.get(app_id).name == "wedged"


def test_sharedfs_channel_id_collision_probes(tmp_path, monkeypatch):
    """Two channels whose hash ids collide get DISTINCT ids (probed), so
    their event directories never merge."""
    from predictionio_tpu.storage import sharedfs

    chans = sharedfs.SharedChannels(tmp_path / "shared")
    monkeypatch.setattr(sharedfs.zlib, "crc32", lambda b: 42)  # force collision
    c1 = chans.insert(Channel(0, "one", 1))
    c2 = chans.insert(Channel(0, "two", 1))
    assert c1 and c2 and c1 != c2
    assert chans.get(c1).name == "one" and chans.get(c2).name == "two"


def test_writer_survives_external_data_delete(tmp_path):
    """Events POSTed after another process data-deletes the channel land in
    a fresh segment, not an unlinked inode (kept-open writer regression)."""
    import shutil

    from predictionio_tpu.storage.localfs import FSEvents

    ev = FSEvents(tmp_path)
    ev.insert(Event(event="buy", entity_type="user", entity_id="u1"), 1)
    # another process deletes the app's data out from under the writer
    shutil.rmtree(ev._chan_dir(1, None))
    ev2 = FSEvents(tmp_path)  # reader in a third process
    ev.insert(Event(event="buy", entity_type="user", entity_id="u2"), 1)
    got = [e.entity_id for e in ev2._iter_raw(1, None)]
    assert got == ["u2"]


# -- compaction (SelfCleaningDataSource role) --------------------------------


def test_compact_drops_tombstones_and_expired(tmp_path):
    import predictionio_tpu.storage.localfs as lfs
    from predictionio_tpu.storage.localfs import FSEvents

    old = lfs.SEGMENT_MAX_BYTES
    lfs.SEGMENT_MAX_BYTES = 2048
    try:
        ev = FSEvents(tmp_path)
        ids = []
        for k in range(60):
            ids.extend(ev.insert_batch(
                [Event(event="buy", entity_type="user", entity_id=f"u{k}",
                       target_entity_type="item", target_entity_id=f"i{k % 7}",
                       event_time=ts(k % 23))], 1))
        for eid in ids[:5]:
            assert ev.delete(eid, 1)
        n_segs_before = len(ev.segment_paths(1))
        assert n_segs_before > 1
        stats = ev.compact(1, before=ts(3))  # expire hours 0-2
        live = list(ev._iter_raw(1, None))
        assert stats["kept"] == len(live)
        assert all(e.event_id not in ids[:5] for e in live)
        assert all(e.event_time >= ts(3) for e in live)
        assert stats["expired"] > 0
        # tombstone files gone; per-entity index still correct
        assert not list((tmp_path / "events").rglob("tombstones*.txt"))
        got = list(ev.find(1, entity_type="user", entity_id="u30"))
        assert len(got) == 1
        # ingest continues cleanly after compaction
        ev.insert(Event(event="buy", entity_type="user", entity_id="fresh"), 1)
        assert any(e.entity_id == "fresh" for e in ev._iter_raw(1, None))
    finally:
        lfs.SEGMENT_MAX_BYTES = old


def test_compact_on_sharedfs_multiwriter(tmp_path, monkeypatch):
    from predictionio_tpu.storage import localfs as lfs, sharedfs

    monkeypatch.setattr(lfs, "SEGMENT_MAX_BYTES", 2048)
    w1 = sharedfs.SharedFSEvents(tmp_path / "sh", writer_tag="hostA-1")
    w2 = sharedfs.SharedFSEvents(tmp_path / "sh", writer_tag="hostB-2")
    for k in range(40):
        (w1 if k % 2 else w2).insert_batch(
            [Event(event="buy", entity_type="user", entity_id=f"u{k}",
                   target_entity_type="item", target_entity_id=f"i{k % 5}")], 1)
    victim = next(w1._iter_raw(1, None)).event_id
    assert w2.delete(victim, 1)
    stats = w1.compact(1)
    assert stats["kept"] == 39
    reader = sharedfs.SharedFSEvents(tmp_path / "sh")
    assert sum(1 for _ in reader._iter_raw(1, None)) == 39


def test_compact_cli(tmp_path, monkeypatch):
    from predictionio_tpu.cli.main import main as pio_main
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage

    storage = Storage(StorageConfig(
        sources={"S": {"type": "localfs", "path": str(tmp_path / "store")}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA", "MODELDATA")},
    ))
    set_storage(storage)
    try:
        app_id = storage.apps.insert(App(0, "capp"))
        storage.l_events.insert_batch(
            [Event(event="buy", entity_type="user", entity_id=f"u{k}",
                   event_time=ts(k % 20)) for k in range(30)], app_id)
        rc = pio_main(["app", "compact", "capp", "--before",
                       ts(10).isoformat()])
        assert rc == 0
        left = list(storage.l_events.find(app_id))
        assert all(e.event_time >= ts(10) for e in left) and left
    finally:
        set_storage(None)


def test_compact_crash_recovery_both_phases(tmp_path):
    """A compaction killed mid-run self-heals on the next read: 'prepare'
    rolls back to the original log, 'commit' rolls forward to the
    compacted one — never duplicates, never loses."""
    import json as _json

    from predictionio_tpu.storage.localfs import FSEvents

    ev = FSEvents(tmp_path)
    ids = ev.insert_batch(
        [Event(event="buy", entity_type="user", entity_id=f"u{k}")
         for k in range(20)], 1)
    assert ev.delete(ids[0], 1)
    d = ev._chan_dir(1, None)

    # simulate a crash in phase PREPARE: intent + partial hidden output
    (d / ev._COMPACT_INTENT).write_text(_json.dumps(
        {"phase": "prepare", "tag": "deadbeef",
         "old": [p.name for p in ev._list_segments(d)]}))
    (d / ".seg-deadbeef-00000.jsonl.tmp").write_text("partial garbage\n")
    reader = FSEvents(tmp_path)
    got = list(reader._iter_raw(1, None))
    assert len(got) == 19                       # original log intact
    assert not list(d.glob("*deadbeef*"))       # partial output rolled back
    assert not (d / ev._COMPACT_INTENT).exists()

    # simulate a crash in phase COMMIT: full hidden output + commit intent
    lines = "".join(e.to_json_line() + "\n" for e in got[:7])
    (d / ".seg-cafe0001-00000.jsonl.tmp").write_text(lines)
    (d / ev._COMPACT_INTENT).write_text(_json.dumps(
        {"phase": "commit", "tag": "cafe0001",
         "old": [p.name for p in ev._list_segments(d)]}))
    reader2 = FSEvents(tmp_path)
    got2 = list(reader2._iter_raw(1, None))
    assert len(got2) == 7                       # rolled FORWARD
    assert not (d / ev._COMPACT_INTENT).exists()
    assert all(p.name.startswith("seg-cafe0001-")
               for p in reader2._list_segments(d))


def test_compact_all_backends(storage):
    """compact() exists on every backend: segment backends rewrite the log;
    memory/SQL (in-place deletes) implement the TTL trim."""
    ev = storage.l_events
    ev.init(9)
    ev.insert_batch(
        [Event(event="buy", entity_type="user", entity_id=f"u{k}",
               event_time=ts(k % 20)) for k in range(20)], 9)
    stats = ev.compact(9, before=ts(10))
    assert stats["expired"] > 0
    left = list(ev.find(9))
    assert left and all(e.event_time >= ts(10) for e in left)
    assert stats["kept"] == len(left)


def test_recovery_never_touches_live_compaction(tmp_path):
    """A reader that sees the intent of a LIVE compaction (flock held) must
    leave it alone — recovering an in-progress compact would delete its
    output and lose the whole log at commit."""
    import fcntl
    import json as _json

    from predictionio_tpu.storage.localfs import FSEvents

    ev = FSEvents(tmp_path)
    ev.insert_batch([Event(event="buy", entity_type="user", entity_id=f"u{k}")
                     for k in range(10)], 1)
    d = ev._chan_dir(1, None)
    # simulate the live compactor: intent present AND flock held
    (d / ev._COMPACT_INTENT).write_text(_json.dumps(
        {"phase": "prepare", "tag": "live0001",
         "old": [p.name for p in ev._list_segments(d)]}))
    hidden = d / ".seg-live0001-00000.jsonl.tmp"
    hidden.write_text("in progress\n")
    lockf = open(d / ev._COMPACT_LOCK, "a")
    fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
    try:
        reader = FSEvents(tmp_path)
        segs = reader.segment_paths(1)           # triggers the recovery check
        assert hidden.exists()                   # output untouched
        assert (d / ev._COMPACT_INTENT).exists() # intent untouched
        assert len(list(reader._iter_raw(1, None))) == 10
        assert segs  # original log still visible
        # a second compactor is refused while the first runs
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="in progress"):
            reader.compact(1)
    finally:
        fcntl.flock(lockf.fileno(), fcntl.LOCK_UN)
        lockf.close()
    # once the "compactor" is gone, recovery rolls the prepare phase back
    reader2 = FSEvents(tmp_path)
    reader2.segment_paths(1)
    assert not hidden.exists()
    assert not (d / ev._COMPACT_INTENT).exists()
    assert len(list(reader2._iter_raw(1, None))) == 10


def test_insert_after_crashed_commit_recovers_first(tmp_path):
    """An insert arriving after a commit-phase crash must not land in a
    superseded segment that roll-forward recovery then unlinks."""
    import json as _json

    from predictionio_tpu.storage.localfs import FSEvents

    ev = FSEvents(tmp_path)
    ev.insert_batch([Event(event="buy", entity_type="user", entity_id=f"u{k}")
                     for k in range(8)], 1)
    d = ev._chan_dir(1, None)
    survivors = list(ev._iter_raw(1, None))[:5]
    (d / ".seg-cafe0002-00000.jsonl.tmp").write_text(
        "".join(e.to_json_line() + "\n" for e in survivors))
    (d / ev._COMPACT_INTENT).write_text(_json.dumps(
        {"phase": "commit", "tag": "cafe0002",
         "old": [p.name for p in ev._list_segments(d)]}))
    # fresh process: the FIRST operation is an insert
    writer = FSEvents(tmp_path)
    writer.insert(Event(event="buy", entity_type="user",
                        entity_id="POSTCRASH"), 1)
    got = [e.entity_id for e in FSEvents(tmp_path)._iter_raw(1, None)]
    assert "POSTCRASH" in got
    assert len(got) == 6  # 5 compacted survivors + the new event


# -- memory delta-tail protocol (PR 9 satellite) -----------------------------


def _mem_events():
    from predictionio_tpu.storage.memory import MemEvents

    return MemEvents()


def test_memory_delta_tail_roundtrip():
    """MemEvents speaks the delta-tail protocol: a count watermark + a
    generation fingerprint, so `pio deploy --follow` and delta staging
    work on a memory-backed store."""
    ev = _mem_events()
    for k in range(6):
        ev.insert(Event(event="buy", entity_type="user", entity_id=f"u{k}",
                        event_id=f"e{k}"), 1)
    full = ev.scan_tail_from(1, None, {}, base=None, heads=None)
    assert full["events"] == 6
    assert full["watermark"] == {"mem": 6}
    assert sorted(full["ids"].tolist()) == sorted(f"e{k}" for k in range(6))
    # nothing new → empty tail with the same watermark
    tail = ev.scan_tail_from(1, None, full["watermark"],
                             heads=full["heads"])
    assert tail["events"] == 0
    # appends land in the tail only
    ev.insert(Event(event="buy", entity_type="user", entity_id="u9",
                    event_id="new1"), 1)
    tail = ev.scan_tail_from(1, None, full["watermark"],
                             heads=full["heads"])
    assert tail["events"] == 1 and tail["ids"].tolist() == ["new1"]
    assert tail["watermark"] == {"mem": 7}
    # bounded restart read reconstructs exactly the covered prefix
    bound = ev.scan_events_up_to(1, None, full["watermark"],
                                 heads=full["heads"])
    assert bound["events"] == 6
    assert ev.tombstone_state(1) == frozenset()


def test_memory_delta_tail_invalidated_by_mutation():
    """In-place mutations (delete / remove / TTL trim) bump the bucket
    generation: every outstanding watermark then reads None (full
    restage), never a double-read or a stale suffix."""
    ev = _mem_events()
    for k in range(4):
        ev.insert(Event(event="buy", entity_type="user", entity_id=f"u{k}",
                        event_id=f"e{k}", event_time=ts(k + 1)), 1)
    full = ev.scan_tail_from(1, None, {}, base=None, heads=None)
    assert ev.delete("e1", 1)
    assert ev.scan_tail_from(1, None, full["watermark"],
                             heads=full["heads"]) is None
    assert ev.scan_events_up_to(1, None, full["watermark"],
                                heads=full["heads"]) is None
    # restage reflects the delete and a TTL trim invalidates again
    full2 = ev.scan_tail_from(1, None, {}, base=None, heads=None)
    assert full2["events"] == 3
    ev.compact(1, before=ts(3))
    assert ev.scan_tail_from(1, None, full2["watermark"],
                             heads=full2["heads"]) is None
    # remove() clears the bucket AND invalidates
    ev2 = _mem_events()
    ev2.insert(Event(event="buy", entity_type="user", entity_id="u1"), 2)
    f = ev2.scan_tail_from(2, None, {}, base=None, heads=None)
    ev2.remove(2)
    assert ev2.scan_tail_from(2, None, f["watermark"],
                              heads=f["heads"]) is None


def test_delta_tail_capability_helpers():
    """The capability probe + the clear error for backends without the
    delta-tail protocol."""
    import pytest as _pytest

    from predictionio_tpu.storage import base as _base
    from predictionio_tpu.storage.localfs import FSEvents
    from predictionio_tpu.storage.sql import SQLSource

    assert _base.delta_tail_supported(_mem_events())
    assert _base.delta_tail_supported(FSEvents("/tmp/_cap_probe"))
    sql_events = SQLSource(":memory:").events
    assert not _base.delta_tail_supported(sql_events)
    with _pytest.raises(_base.StoreCapabilityError) as ei:
        _base.require_delta_tail(sql_events, "pio deploy --follow")
    assert "scan_tail_from" in str(ei.value)
    assert "SQL" in type(sql_events).__name__ or "sql" in str(ei.value)


def test_cross_shard_merged_scan_keeps_prop_columns(tmp_path):
    """Shard dictionaries disagree by construction (each shard's
    snapshot owns its own per-key prop dicts); the merged scan must
    RE-CODE the property columns into one dictionary instead of
    dropping them — and the folded properties must equal an unsharded
    store's over the same events."""
    import numpy as np

    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.storage.localfs import FSEvents
    from predictionio_tpu.storage.sharded import ShardedEvents
    from predictionio_tpu.store.columnar import fold_properties

    def events():
        out = []
        for k in range(12):
            out.append(Event(event="$set", entity_type="item",
                             entity_id=f"i{k}",
                             properties=DataMap({
                                 "category": f"c{k % 5}",
                                 "tags": [f"t{k % 3}", "common"],
                                 "stock": k,
                             })))
            out.append(Event(event="buy", entity_type="user",
                             entity_id=f"u{k % 4}",
                             target_entity_type="item",
                             target_entity_id=f"i{k}"))
        return out

    sh = ShardedEvents(str(tmp_path / "sh"), shards=3, replicas=1)
    ref = FSEvents(str(tmp_path / "ref"))
    try:
        for ev in (sh, ref):
            ev.init(7)
            ev.insert_batch(events(), 7)
        # per-shard snapshots give every shard its OWN dictionaries
        sh.build_snapshot(7)
        res = sh.snapshot_scan(7)
        assert res is not None
        batch = res["batch"]
        assert batch.prop_columns, \
            "merged cross-shard scan dropped prop_columns"
        got = {k: dict(v)
               for k, v in fold_properties(batch, "item").items()}
        ref_res = ref.scan_tail_from(7, None, {}, base=None, heads=None)
        want = {k: dict(v)
                for k, v in fold_properties(ref_res["batch"],
                                            "item").items()}
        assert got == want
        # re-coded codes must decode through the merged dict: spot-check
        col = batch.prop_columns["category"]
        vals = {col.value_at(j) for j in range(len(col))}
        assert vals == {f"c{k}" for k in range(5)}
        # numeric column survives too
        stock = batch.prop_columns["stock"]
        nums = sorted(int(stock.num[j]) for j in range(len(stock)))
        assert nums == list(range(12))
        assert np.all(np.diff(col.rows) >= 0) or len(col) <= 1
    finally:
        sh.close() if hasattr(sh, "close") else None
