"""Serving hot-path tests (PR 4): host serve tail ≡ device tail exact
parity (items, scores, tie order), batch ≡ serial across tails, the
rule-mask cache (hits, canonicalization, per-generation invalidation,
eviction), the thread-safe LRU lookup caches, the locked host-inverted
build, serve-stage metrics/spans, and the /stats.json 503 contract under
PIO_METRICS=off."""

import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.universal_recommender import (
    UniversalRecommenderEngine,
    URQuery,
)
from predictionio_tpu.models.universal_recommender.engine import (
    URAlgorithm,
    URAlgorithmParams,
    URDataSourceParams,
)
from predictionio_tpu.storage import App

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture()
def rules_app(mem_storage):
    """Two-cluster commerce data with category AND date properties, so
    every business-rule shape (filter, boost, dateRange, currentDate
    avail/expire) has matching items."""
    app_id = mem_storage.apps.insert(App(0, "tailapp"))
    rng = np.random.default_rng(7)
    events = []
    e_items = [f"e{i}" for i in range(6)]
    b_items = [f"b{i}" for i in range(6)]
    for u in range(30):
        mine = e_items if u < 15 else b_items
        for it in mine:
            if rng.random() < 0.7:
                events.append(Event(
                    event="purchase", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
            if rng.random() < 0.9:
                events.append(Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
    for k, it in enumerate(e_items):
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({
                "category": "electronics",
                "availableDate": "2026-01-01T00:00:00",
                "expireDate": f"2026-0{(k % 6) + 1}-15T00:00:00"})))
    for it in b_items:
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({"category": "books",
                                "availableDate": "2026-02-01T00:00:00"})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_ep(**algo_over):
    algo = dict(app_name="tailapp", mesh_dp=1, max_correlators_per_item=8,
                min_llr=0.0, available_date_name="availableDate",
                expire_date_name="expireDate")
    algo.update(algo_over)
    return EngineParams(
        data_source_params=URDataSourceParams(
            app_name="tailapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(**algo))],
    )


@pytest.fixture()
def trained_rules(rules_app):
    engine = UniversalRecommenderEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    return engine, ep, models


def rule_queries():
    q = URQuery.from_json
    return [
        q({"user": "u2", "num": 6}),
        q({"user": "stranger", "num": 5}),
        q({"item": "e1", "num": 4}),
        q({"itemSet": ["e0", "e2"], "num": 5}),
        q({"user": "u3", "num": 6,
           "fields": [{"name": "category", "values": ["books"],
                       "bias": -1}]}),
        q({"user": "u3", "num": 6,
           "fields": [{"name": "category", "values": ["electronics"],
                       "bias": 3.0}]}),
        q({"user": "u4", "num": 6, "blacklistItems": ["e0", "b0"]}),
        q({"user": "u5", "num": 6,
           "dateRange": {"name": "expireDate",
                         "after": "2026-02-01T00:00:00"}}),
        q({"user": "u6", "num": 8, "currentDate": "2026-03-01T00:00:00"}),
        # all-masked: no item carries this value → exact empty result
        q({"user": "u7", "num": 6,
           "fields": [{"name": "category", "values": ["no-such"],
                       "bias": -1}]}),
        q({"user": "u20", "num": 0}),
    ]


def canon(result):
    return [(s.item, float(s.score)) for s in result.item_scores]


def test_host_tail_matches_device_tail_exact(trained_rules, monkeypatch):
    """The host tail is a bit-exact twin of the device tail: same items,
    same float scores, same tie order, for every business-rule shape —
    including the all-masked empty result."""
    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")  # identical signal in
    queries = rule_queries()
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "device")
    dev = [canon(algo.predict(model, q)) for q in queries]
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    host = [canon(algo.predict(model, q)) for q in queries]
    assert any(dev), "fixture produced only empty results"
    for qi, (d, h) in enumerate(zip(dev, host)):
        assert d == h, (qi, d, h)
    assert dev[9] == []          # all-masked
    assert dev[10] == []         # num=0


@pytest.mark.parametrize("tail", ["host", "device"])
@pytest.mark.parametrize("scorer", ["host", "device"])
def test_serve_batch_matches_serial_all_paths(trained_rules, monkeypatch,
                                              tail, scorer):
    """serve_batch_predict ≡ predict exactly, under every scorer × tail
    combination (the micro-batcher must be response-invisible)."""
    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", scorer)
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", tail)
    queries = rule_queries()
    serial = [canon(algo.predict(model, q)) for q in queries]
    batched = [canon(r) for r in algo.serve_batch_predict(model, queries)]
    assert serial == batched


def test_host_topk_desc_matches_lax_top_k():
    """host_topk_desc reproduces lax.top_k exactly — descending values,
    lower-index-first ties (XLA's total order, including -0.0 < +0.0),
    across dense, mostly-constant, -inf-heavy and edge-size inputs."""
    import jax

    from predictionio_tpu.models.common import host_topk_desc

    rng = np.random.default_rng(3)
    sparse = np.zeros(20_000, np.float32)
    sparse[rng.integers(0, 20_000, 500)] = rng.random(500).astype(np.float32)
    ties = np.round(rng.random(5_000).astype(np.float32) * 4) / 2
    ties[rng.integers(0, 5_000, 800)] = -np.inf
    cases = [
        (np.array([0.0, -0.0, 1.0, -0.0, 0.0, 0.5], np.float32), 6),
        (rng.normal(size=3_000).astype(np.float32), 77),
        (sparse, 64),
        (ties, 128),
        (np.full(300, -np.inf, np.float32), 32),
        (rng.normal(size=10).astype(np.float32), 10),   # k == n
        (rng.normal(size=5).astype(np.float32), 9),     # k > n
    ]
    for arr, k in cases:
        sv, si = jax.lax.top_k(arr, min(k, len(arr)))
        hv, hi = host_topk_desc(arr, k)
        np.testing.assert_array_equal(np.asarray(si), hi)
        np.testing.assert_array_equal(np.asarray(sv), hv)
    hv, hi = host_topk_desc(np.ones(4, np.float32), 0)
    assert len(hv) == 0 and len(hi) == 0


def test_rule_mask_cache_hits_and_canonicalization(trained_rules,
                                                   monkeypatch):
    """Repeated business rules hit the composed-mask cache; rule ORDER
    does not fragment it (canonical key), and the no-rule query never
    touches it."""
    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    # dense-tail cache accounting is the subject; the candidate-pruned
    # path probes without populating (tests/test_serve_candidates.py)
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    f1 = {"name": "category", "values": ["books"], "bias": -1}
    f2 = {"name": "category", "values": ["electronics"], "bias": 2.0}
    qa = URQuery.from_json({"user": "u2", "num": 5, "fields": [f1, f2]})
    qb = URQuery.from_json({"user": "u3", "num": 5, "fields": [f2, f1]})
    algo.predict(model, qa)
    cache = model.rule_mask_cache("host")
    assert len(cache) == 1 and cache.misses == 1
    algo.predict(model, qb)          # reversed order → same canonical key
    assert len(cache) == 1 and cache.hits >= 1
    algo.predict(model, URQuery(user="u2", num=5))   # no rules: no lookup
    assert cache.hits + cache.misses == 2


def test_rule_mask_cache_invalidated_per_model_generation(trained_rules,
                                                          monkeypatch):
    """Hot-swap/auto-reload loads a NEW model object; its rule-mask cache
    starts empty (nothing survives pickling)."""
    import pickle

    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    q = URQuery.from_json({"user": "u2", "num": 5, "fields": [
        {"name": "category", "values": ["books"], "bias": -1}]})
    algo.predict(model, q)
    assert len(model.rule_mask_cache("host")) == 1
    swapped = pickle.loads(pickle.dumps(model))
    assert "_rule_mask_host" not in swapped.__dict__
    algo.predict(swapped, q)
    fresh = swapped.rule_mask_cache("host")
    assert fresh.misses == 1 and fresh.hits == 0


def test_rule_mask_cache_eviction_bounded(trained_rules, monkeypatch):
    import pickle

    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    monkeypatch.setenv("PIO_UR_RULE_MASK_CACHE", "2")
    model = pickle.loads(pickle.dumps(models[0]))   # fresh caches
    for bias in (2.0, 3.0, 4.0):
        algo.predict(model, URQuery.from_json({
            "user": "u2", "num": 5,
            "fields": [{"name": "category", "values": ["books"],
                        "bias": bias}]}))
    cache = model.rule_mask_cache("host")
    assert len(cache) == 2 and cache.evictions == 1


def test_lru_cache_touch_on_hit_and_threads():
    from predictionio_tpu.models.common import LRUCache

    events = []
    c = LRUCache(2, on_event=events.append)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1           # touch: a is now most-recent
    c.put("c", 3)                    # evicts b, NOT a
    assert c.get("a") == 1 and c.get("b") is None and c.get("c") == 3
    assert c.evictions == 1 and events.count("evict") == 1

    big = LRUCache(8)
    errors = []

    def hammer(seed):
        try:
            rng = np.random.default_rng(seed)
            for _ in range(2_000):
                k = int(rng.integers(0, 32))
                if big.get(k) is None:
                    big.put(k, k)
        except Exception as e:   # pragma: no cover - the assertion target
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors and len(big) <= 8


def test_host_inverted_builds_once_under_race(trained_rules):
    """Concurrent first queries must share ONE postings-index build: every
    thread gets the identical object, and the build-duration gauge is
    recorded."""
    from predictionio_tpu.models.universal_recommender.engine import (
        _M_INV_BUILD,
    )

    _, _, models = trained_rules
    model = models[0]
    name = next(iter(model.indicator_idx))
    model.__dict__.pop("_host_inv", None)
    got = []
    barrier = threading.Barrier(8)

    def build():
        barrier.wait()
        got.append(model.host_inverted(name))

    threads = [threading.Thread(target=build) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(got) == 8
    assert all(g[0] is got[0][0] for g in got), "race built twice"
    assert _M_INV_BUILD.value(event=name) > 0.0, "build gauge not recorded"


def test_rule_mask_key_quantizes_and_ignores_inert_current_date(
        trained_rules, monkeypatch):
    """currentDate instants quantize to whole seconds in the cache key
    (now()-style traffic shares one entry per second), and a currentDate
    with NO configured avail/expire property is inert: no mask build, no
    cache entry — but still strictly parsed."""
    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    sub_second = [
        URQuery.from_json({"user": "u2", "num": 5,
                           "currentDate": "2026-03-01T00:00:00.200"}),
        URQuery.from_json({"user": "u3", "num": 5,
                           "currentDate": "2026-03-01T00:00:00.400"}),
    ]
    for q in sub_second:
        algo.predict(model, q)
    cache = model.rule_mask_cache("host")
    assert len(cache) == 1 and cache.hits == 1, \
        "sub-second currentDate instants must share one mask entry"

    # no avail/expire configured → currentDate contributes nothing
    inert_algo = URAlgorithm(URAlgorithmParams(
        app_name="tailapp", mesh_dp=1, max_correlators_per_item=8))
    import pickle

    fresh = pickle.loads(pickle.dumps(model))
    inert_algo.predict(fresh, URQuery.from_json(
        {"user": "u2", "num": 5, "currentDate": "2026-03-01T00:00:00"}))
    assert "_rule_mask_host" not in fresh.__dict__, \
        "inert currentDate must not touch the mask cache"
    with pytest.raises(ValueError):
        inert_algo.predict(fresh, URQuery.from_json(
            {"user": "u2", "num": 5, "currentDate": "garbage"}))


def test_value_mask_cache_hit_skips_build(trained_rules, monkeypatch):
    """A value-mask cache HIT must not re-run the O(n_items) mask build
    (regression guard: the build used to run before the lookup)."""
    engine, ep, models = trained_rules
    model = models[0]
    model.host_value_mask("category", "books")
    builds = []
    orig = model._ids_to_mask
    monkeypatch.setattr(model, "_ids_to_mask",
                        lambda ids: builds.append(1) or orig(ids))
    again = model.host_value_mask("category", "books")
    assert builds == [], "cache hit rebuilt the mask"
    assert again.any()


def test_malformed_query_date_rejected_before_cache(trained_rules,
                                                    monkeypatch):
    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    with pytest.raises(ValueError):
        algo.predict(models[0], URQuery.from_json(
            {"user": "u2", "num": 5, "currentDate": "not-a-date"}))
    assert len(models[0].rule_mask_cache("host")) == 0


def test_serve_stage_metrics_and_span_journal(trained_rules, monkeypatch,
                                              tmp_path):
    """predict records per-stage tail timings in the pio_* registry and,
    when a span journal is active, a per-query span whose attrs carry the
    stage breakdown."""
    from predictionio_tpu.models.universal_recommender.engine import _M_STAGE
    from predictionio_tpu.obs.spans import SpanJournal

    engine, ep, models = trained_rules
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    _M_STAGE.clear_series()
    journal = SpanJournal(tmp_path / "serve.jsonl")
    with journal.activate():
        algo.predict(models[0], URQuery(user="u2", num=5))
    snap = _M_STAGE._snapshot_series()
    stages = {s for s in ("history", "score", "mask", "topk", "assemble")
              if any(f'stage="{s}"' in k for k in snap)}
    assert stages == {"history", "score", "mask", "topk", "assemble"}
    spans = [s for s in journal._spans if s["name"] == "ur_predict"]
    assert spans and "topk_ms" in spans[0]["attrs"]
    assert spans[0]["attrs"]["tail"] == "host"


def test_stats_json_503_when_metrics_off(mem_storage, monkeypatch):
    """PIO_METRICS=off: the event server's /stats.json answers 503 (not a
    500 traceback / frozen counters); /metrics still serves."""
    import json as _json
    import urllib.error
    import urllib.request

    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.obs import metrics as obs_metrics
    from predictionio_tpu.storage import AccessKey

    app_id = mem_storage.apps.insert(App(0, "offapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    obs_metrics.set_enabled(False)
    httpd = None
    try:
        httpd = run_event_server(host="127.0.0.1", port=0,
                                 storage=mem_storage, background=True)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            urllib.request.urlopen(f"{base}/stats.json?accessKey={key}")
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert "PIO_METRICS" in _json.loads(e.read())["message"]
        with urllib.request.urlopen(f"{base}/metrics") as r:
            assert r.status == 200
    finally:
        obs_metrics.set_enabled(True)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()


def test_query_server_stats_json_503_when_metrics_off(tmp_path, rules_app,
                                                      monkeypatch):
    """Same contract on the deployed query server."""
    import json as _json
    import urllib.error
    import urllib.request

    from predictionio_tpu.obs import metrics as obs_metrics
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import deploy

    variant = {
        "id": "tail-qs",
        "engineFactory":
            "predictionio_tpu.models.universal_recommender."
            "UniversalRecommenderEngine",
        "datasource": {"params": {"appName": "tailapp",
                                  "eventNames": ["purchase", "view"]}},
        "algorithms": [{"name": "ur", "params": {
            "appName": "tailapp", "eventNames": [], "meshDp": 1,
            "maxCorrelatorsPerItem": 8}}],
    }
    ej = tmp_path / "engine.json"
    ej.write_text(_json.dumps(variant))
    engine = UniversalRecommenderEngine.apply()
    ep = engine.engine_params_from_variant(variant)
    core_workflow.run_train(engine, ep, engine_id="tail-qs",
                            storage=rules_app)
    obs_metrics.set_enabled(False)
    httpd = None
    try:
        httpd = deploy(engine_json=str(ej), host="127.0.0.1", port=0,
                       storage=rules_app, background=True)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            urllib.request.urlopen(f"{base}/stats.json")
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        # /queries.json still serves, and GET / reports the worker pid
        req = urllib.request.Request(
            f"{base}/queries.json",
            data=_json.dumps({"user": "u2", "num": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        with urllib.request.urlopen(base + "/") as r:
            assert "pid" in _json.loads(r.read())
        # freshness is state, not a metric: the SDK contract must
        # survive the kill switch via the GET / fallback
        from predictionio_tpu.sdk import EngineClient

        qc = EngineClient(url=base)
        assert qc.model_generation() >= 1
        assert qc.freshness().get("generation") == qc.model_generation()
    finally:
        obs_metrics.set_enabled(True)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()


def test_check_serve_parity_script():
    """The tier-1 CI wrapper for scripts/check_serve_parity.py (same
    pattern as the metric-name and snapshot-integrity lints): trains a
    small UR model in a fresh process and replays the fixed corpus
    through both tails, serial and batched, diffing exactly."""
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_serve_parity.py")],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
