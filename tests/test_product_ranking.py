"""Product Ranking template tests: ranking a provided list, isOriginal
fallback, unknown-item handling."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import Event
from predictionio_tpu.models.product_ranking import ProductRankingEngine, PRQuery
from predictionio_tpu.models.product_ranking.engine import (
    PRAlgorithmParams,
    PRDataSourceParams,
)
from predictionio_tpu.storage import App

APP = "prapp"


@pytest.fixture()
def pr_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, APP))
    rng = np.random.default_rng(12)
    events = []
    # even users love a-items (repeat buys), odd users love z-items
    for u in range(40):
        love = [f"a{i}" for i in range(4)] if u % 2 == 0 else [f"z{i}" for i in range(4)]
        meh = [f"z{i}" for i in range(4)] if u % 2 == 0 else [f"a{i}" for i in range(4)]
        for it in love:
            for _ in range(3):
                if rng.random() < 0.9:
                    events.append(Event(event="buy", entity_type="user",
                                        entity_id=f"u{u}", target_entity_type="item",
                                        target_entity_id=it))
        for it in meh:
            if rng.random() < 0.2:
                events.append(Event(event="view", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_ep():
    return EngineParams(
        data_source_params=PRDataSourceParams(app_name=APP),
        algorithm_params_list=[("als", PRAlgorithmParams(
            rank=6, num_iterations=12, alpha=2.0, mesh_dp=1))],
    )


def trained():
    engine = ProductRankingEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    return engine, ep, engine.predictor(ep, models), models


def test_ranks_loved_items_first(pr_app):
    _, _, predict, _ = trained()
    res = predict(PRQuery(user="u0", items=["z0", "a1", "z1", "a0"]))
    assert not res.is_original
    order = [s.item for s in res.item_scores]
    assert set(order[:2]) <= {"a0", "a1"}, order
    res = predict(PRQuery(user="u1", items=["z0", "a1", "z1", "a0"]))
    assert set(s.item for s in res.item_scores[:2]) <= {"z0", "z1"}


def test_unknown_user_returns_original_order(pr_app):
    _, _, predict, _ = trained()
    res = predict(PRQuery(user="nobody", items=["z0", "a1", "a0"]))
    assert res.is_original
    assert [s.item for s in res.item_scores] == ["z0", "a1", "a0"]


def test_unknown_items_sink_to_bottom(pr_app):
    _, _, predict, _ = trained()
    res = predict(PRQuery(user="u0", items=["mystery", "a1", "a0"]))
    assert not res.is_original
    assert res.item_scores[-1].item == "mystery"


def test_wire_format(pr_app):
    _, _, predict, _ = trained()
    q = PRQuery.from_json({"user": "u0", "items": ["a0", "z0"]})
    out = predict(q).to_json()
    assert set(out) == {"itemScores", "isOriginal"}


def test_model_roundtrip(pr_app):
    import pickle

    engine, ep, _, models = trained()
    restored = [pickle.loads(pickle.dumps(m)) for m in models]
    q = PRQuery(user="u0", items=["a0", "z0", "a1"])
    assert (engine.predictor(ep, models)(q).to_json()
            == engine.predictor(ep, restored)(q).to_json())


def test_pr_serve_batch_matches_serial(pr_app):
    """serve_batch_predict ≡ predict across rankable, unknown-user, and
    unknown-item queries in one batch."""
    engine, ep, predict, models = trained()
    model = models[0]
    algo = engine.algorithm_classes["als"](
        dict(ep.algorithm_params_list)["als"])
    queries = [
        PRQuery(user="u0", items=["z0", "a1", "z1", "a0"]),
        PRQuery(user="u1", items=["a0", "z0"]),
        PRQuery(user="nobody", items=["z0", "a1"]),
        PRQuery(user="u0", items=["mystery", "a1", "a0"]),
        PRQuery(user="u2", items=["ghost", "phantom"]),    # no known items
    ]
    serial = [algo.predict(model, q) for q in queries]
    batched = algo.serve_batch_predict(model, queries)
    for q, s, b in zip(queries, serial, batched):
        assert s.is_original == b.is_original, q
        s_i = [(r.item, round(r.score, 4)) for r in s.item_scores]
        b_i = [(r.item, round(r.score, 4)) for r in b.item_scores]
        assert s_i == b_i, (q, s_i, b_i)
