"""Streaming freshness: fold exactness, hot-swap invalidation, daemon
crash-restart, and the roundtrip script wrapper.

The fold engine's contract is bit-exactness: after ANY fold sequence the
resident model must answer every query identically to a from-scratch
``engine.train`` over the same events.  These tests drive the real
storage tail (scan_tail_from), real folds, and real hot-swaps through
``QueryServerState.swap_models`` — no mocks on the exactness path.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


# -- helpers -----------------------------------------------------------------


def _buy(u, i, event="purchase"):
    from predictionio_tpu.events.event import Event

    return Event(event=event, entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def _set_item(i, props):
    from predictionio_tpu.events.event import DataMap, Event

    return Event(event="$set", entity_type="item", entity_id=i,
                 properties=DataMap(props))


def _seed_events(n_users=12, n_items=8, seed=1, base_u=0):
    rng = np.random.default_rng(seed)
    out = []
    for u in range(base_u, base_u + n_users):
        for it in range(n_items):
            if rng.random() < 0.45:
                out.append(_buy(f"u{u}", f"i{it}"))
            if rng.random() < 0.6:
                out.append(_buy(f"u{u}", f"i{it}", event="view"))
    return out


def _ur_setup(fs_storage, app_name="sfapp", event_names=("purchase", "view"),
              **algo_kw):
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithmParams, URDataSourceParams,
    )
    from predictionio_tpu.storage.base import App

    app_id = fs_storage.apps.insert(App(0, app_name))
    engine = UniversalRecommenderEngine.apply()
    ap = URAlgorithmParams(app_name=app_name, mesh_dp=1,
                           max_correlators_per_item=6, **algo_kw)
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name=app_name, event_names=list(event_names)),
        algorithm_params_list=[("ur", ap)])
    return app_id, engine, ap, ep


def _canon(res):
    return [(s.item, float(s.score)) for s in res.item_scores]


def _fresh_ref(engine, ep):
    from predictionio_tpu.store.event_store import invalidate_staging_cache

    invalidate_staging_cache()
    return engine.train(ep)[0]


def _assert_model_equals_fresh(model, engine, ep, queries, algo):
    """Model arrays AND responses must equal a from-scratch retrain."""
    ref = _fresh_ref(engine, ep)
    for name in ref.indicator_idx:
        assert np.array_equal(ref.indicator_idx[name],
                              model.indicator_idx[name]), name
        assert np.array_equal(ref.indicator_llr[name],
                              model.indicator_llr[name]), name
        assert (ref.event_item_dicts[name].strings()
                == model.event_item_dicts[name].strings()), name
    assert np.array_equal(ref.popularity, model.popularity)
    assert ref.item_properties == model.item_properties
    for q in queries:
        assert _canon(algo.predict(ref, q)) == _canon(algo.predict(model, q))


@pytest.fixture()
def host_serving(monkeypatch):
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")


def _tail(storage, app_id, wm, base, heads):
    return storage.l_events.scan_tail_from(app_id, None, wm, base=base,
                                           heads=heads)


# -- fold exactness ----------------------------------------------------------


def test_fold_matches_train_across_folds(fs_storage, host_serving):
    """Bootstrap + growth + remap + duplicate-only folds: after every
    fold the model arrays and responses equal a from-scratch train."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )
    from predictionio_tpu.streaming.fold import URFoldState

    app_id, engine, ap, ep = _ur_setup(
        fs_storage, use_llr_weights=True,
        indicator_params={"view": {"maxCorrelatorsPerItem": 4}})
    fs_storage.l_events.insert_batch(_seed_events(seed=1), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item(f"i{k}", {"category": "red" if k < 4 else "blue"})
         for k in range(8)], app_id)
    algo = URAlgorithm(ap)
    queries = ([URQuery(user=f"u{u}", num=6) for u in range(0, 12, 2)]
               + [URQuery(user="nobody", num=4), URQuery(item="i1", num=5),
                  URQuery(user="u1", num=6, fields=[
                      {"name": "category", "values": ["red"], "bias": -1}])])
    tail = _tail(fs_storage, app_id, {}, None, None)
    state = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    wm, heads = tail["watermark"], tail["heads"]
    _assert_model_equals_fresh(state.model, engine, ep, queries, algo)
    deltas = [
        _seed_events(n_users=4, seed=2, base_u=5),        # overlap + new
        _seed_events(n_users=3, seed=3, base_u=50)        # new users
        + [_buy("u50", "a_first_item"),                   # mid-array insert
           _set_item("a_first_item", {"category": "red"})],
        _seed_events(seed=1),                             # pure duplicates
    ]
    for k, evs in enumerate(deltas):
        fs_storage.l_events.insert_batch(evs, app_id)
        tail = _tail(fs_storage, app_id, wm, state.batch, heads)
        assert tail is not None and tail["events"] > 0
        model = state.fold(tail["batch"])
        wm, heads = tail["watermark"], tail["heads"]
        _assert_model_equals_fresh(model, engine, ep, queries, algo)
    # the duplicate-only fold must have skipped every re-LLR
    assert all(s["mode"] == "skip" for s in state.last_fold_stats.values())


def test_fold_sliced_rows_path_is_exact(fs_storage, host_serving):
    """A primary-only delta from an existing user re-LLRs ONLY the
    touched rows of the non-primary type (its marginals are untouched),
    and the sliced recompute is bit-identical to the full one."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )
    from predictionio_tpu.streaming.fold import URFoldState

    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_seed_events(seed=4), app_id)
    tail = _tail(fs_storage, app_id, {}, None, None)
    state = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    wm, heads = tail["watermark"], tail["heads"]
    # one new purchase (u0, i7) where u0 hasn't bought i7: primary rows
    # change; the view type sees only row-local changes
    fs_storage.l_events.insert_batch([_buy("u0", "i7")], app_id)
    tail = _tail(fs_storage, app_id, wm, state.batch, heads)
    assert tail["events"] == 1
    model = state.fold(tail["batch"])
    assert state.last_fold_stats["view"]["mode"] == "sliced"
    assert state.last_fold_stats["purchase"]["mode"] == "full"
    algo = URAlgorithm(ap)
    queries = [URQuery(user=f"u{u}", num=6) for u in range(12)]
    _assert_model_equals_fresh(model, engine, ep, queries, algo)


def test_scan_bounded_reconstructs_covered_prefix(fs_storage):
    """scan_events_up_to parses exactly the events a watermark covers —
    the daemon-restart read — and refuses a recreated segment."""
    from predictionio_tpu.storage.base import App

    app_id = fs_storage.apps.insert(App(0, "boundapp"))
    fs_storage.l_events.insert_batch(
        [_buy(f"u{k}", "i0") for k in range(5)], app_id)
    tail = fs_storage.l_events.scan_tail_from(app_id, None, {}, base=None,
                                              heads=None)
    wm, heads = tail["watermark"], tail["heads"]
    fs_storage.l_events.insert_batch(
        [_buy(f"late{k}", "i0") for k in range(3)], app_id)
    res = fs_storage.l_events.scan_events_up_to(app_id, None, wm,
                                                heads=heads)
    assert res is not None and res["events"] == 5
    names = {res["batch"].entity_dict.str(int(c))
             for c in res["batch"].entity_ids}
    assert names == {f"u{k}" for k in range(5)}
    # a recreated segment reusing a covered name must be rejected
    seg = next(iter(wm))
    d = fs_storage.l_events._chan_dir(app_id, None)
    content = b'{"event":"purchase","entityType":"user","entityId":"x",' \
              b'"targetEntityType":"item","targetEntityId":"i0",' \
              b'"eventId":"zzz","eventTime":"2026-01-01T00:00:00Z"}\n'
    (d / seg).write_bytes(content * 64)
    assert fs_storage.l_events.scan_events_up_to(
        app_id, None, wm, heads=heads) is None


# -- hot-swap invalidation audit ---------------------------------------------
# One test per generation-keyed serving structure: a swapped-in model
# must never serve entries derived from the previous generation.


def _follow_pair(fs_storage, app_id, engine, ap, ep):
    """(state, follower) with the embedded swap wired, bootstrapped."""
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import QueryServerState

    core_workflow.run_train(engine, ep, engine_id="swap-eng",
                            storage=fs_storage)
    state = QueryServerState(
        engine, ep, UniversalRecommenderEngine.query_class, "swap-eng",
        "1", "default", storage=fs_storage)
    follower = state.follower = FollowTrainer(
        engine, ep, "swap-eng", storage=fs_storage, interval=3600,
        on_publish=state.swap_models, persist=False)
    assert follower.mode == "fold"
    assert follower.bootstrap()
    return state, follower


def test_swap_invalidates_rule_mask_cache(fs_storage, host_serving,
                                          monkeypatch):
    """Rule-mask LRU: a field filter composed under generation N must
    not survive a swap that moved the property values."""
    # pruned queries probe the dense mask cache without populating it —
    # pin candidates off so the populated-precondition below is real
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    app_id, engine, ap, ep = _ur_setup(
        fs_storage, available_date_name="", expire_date_name="")
    fs_storage.l_events.insert_batch(_seed_events(seed=5), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item(f"i{k}", {"category": "red"}) for k in range(8)], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    red = {"user": "u1", "num": 8,
           "fields": [{"name": "category", "values": ["red"], "bias": -1}]}
    before = state.predict(red)
    assert before.item_scores, "fixture: red filter should match items"
    old_model = follower._fold.model
    old_cache = old_model.rule_mask_cache("host")
    assert len(old_cache) > 0, "fixture: dense mask cache must populate"
    # move every item to blue; the same red query must now match nothing
    fs_storage.l_events.insert_batch(
        [_set_item(f"i{k}", {"category": "blue"}) for k in range(8)], app_id)
    assert follower.tick() == "fold"
    new_model = follower._fold.model
    assert new_model is not old_model
    assert new_model.rule_mask_cache("host") is not old_cache
    after = state.predict(red)
    assert after.item_scores == [], _canon(after)


def test_swap_invalidates_inverted_csr(fs_storage, host_serving):
    """host_inverted CSR: new co-occurrences must be servable from the
    candidate-pruned path right after the swap (patched or rebuilt, the
    postings must reflect the new generation)."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(
        [_buy(f"u{u}", f"i{it}") for u in range(8) for it in range(4)
         if (u + it) % 2], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    # warm the old inversion
    state.predict({"user": "u1", "num": 4})
    assert follower._fold.model.__dict__.get("_host_inv")
    # i9 is brand new and co-purchased with i1 by several users
    fs_storage.l_events.insert_batch(
        [_buy(f"u{u}", "i9") for u in range(8) if u % 2]
        + [_buy(f"u{u}", "i1") for u in range(8) if u % 2], app_id)
    assert follower.tick() == "fold"
    res = state.predict({"user": "fresh", "num": 4})  # cold: backfill only
    # the real probe: a user whose history is i1 must now see i9
    fs_storage.l_events.insert_batch([_buy("prober", "i1")], app_id)
    assert follower.tick() == "fold"
    res = state.predict({"user": "prober", "num": 6})
    items = [s.item for s in res.item_scores if s.score > 0]
    assert "i9" in items, _canon(res)


def test_swap_invalidates_pop_order(fs_storage, host_serving, monkeypatch):
    """host_pop_order: the pruned tail's backfill merge must walk the NEW
    generation's popularity order after a swap."""
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "on")
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    # iPOP's buyers are DISJOINT from u1's co-occurrence neighborhood, so
    # both iPOP and iNEW can only ever reach u1 via popularity backfill
    fs_storage.l_events.insert_batch(
        [_buy(f"u{u}", f"i{it}") for u in range(6) for it in (0, 1)]
        + [_buy(f"w{k}", "iPOP") for k in range(3)], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    old_model = follower._fold.model
    old_model.host_pop_order()          # warm the old order
    # iNEW becomes by far the most popular item
    fs_storage.l_events.insert_batch(
        [_buy(f"pop{k}", "iNEW") for k in range(30)], app_id)
    assert follower.tick() == "fold"
    new_model = follower._fold.model
    assert "_host_pop_order" not in new_model.__dict__ or not np.array_equal(
        new_model.__dict__["_host_pop_order"],
        old_model.__dict__["_host_pop_order"])
    # a user with history gets backfill padding from the NEW order
    res = state.predict({"user": "u1", "num": 10})
    items = [s.item for s in res.item_scores]
    assert "iNEW" in items, items
    assert items.index("iNEW") < items.index("iPOP"), items


def test_swap_invalidates_value_mask_cache(fs_storage, host_serving):
    """Dense value-mask/date caches: a $set fold rebuilds the property
    indexes; a props-untouched fold carries them over (provably
    identical), and either way responses track the live generation."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=6, n_items=6),
                                     app_id)
    fs_storage.l_events.insert_batch(
        [_set_item("i0", {"tier": "gold"})], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    gold = {"user": "u2", "num": 6,
            "fields": [{"name": "tier", "values": ["gold"], "bias": -1}]}
    before = {s.item for s in state.predict(gold).item_scores}
    assert before <= {"i0"} and before, before
    m1 = follower._fold.model
    m1.host_value_mask("tier", "gold")          # warm the dense mask LRU
    m1.prop_value_index("tier")
    # props-untouched fold: the derived indexes carry over by identity
    fs_storage.l_events.insert_batch([_buy("u0", "i1")], app_id)
    assert follower.tick() == "fold"
    m2 = follower._fold.model
    assert m2.item_properties is m1.item_properties
    assert m2.__dict__.get("_prop_value_index") is \
        m1.__dict__.get("_prop_value_index")
    # props-changing fold: gold moves to i3; the old mask must be gone
    fs_storage.l_events.insert_batch(
        [_set_item("i0", {"tier": "silver"}),
         _set_item("i3", {"tier": "gold"})], app_id)
    assert follower.tick() == "fold"
    m3 = follower._fold.model
    assert m3.item_properties is not m1.item_properties
    assert "_prop_value_index" not in m3.__dict__
    after = {s.item for s in state.predict(gold).item_scores}
    assert after <= {"i3"}, after


def test_patched_inverted_equals_rebuilt(fs_storage, host_serving):
    """The incremental host_inverted row patch must be ARRAY-identical
    to inverting the new indicator table from scratch."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=7, n_users=14),
                                     app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    m1 = follower._fold.model
    m1.host_inverted("purchase")   # warm so the fold has something to patch
    # a duplicate-heavy delta touching ONE pair keeps the changed-row set
    # small enough for the patch path
    fs_storage.l_events.insert_batch([_buy("u0", "i7")], app_id)
    assert follower.tick() == "fold"
    m2 = follower._fold.model
    patched = m2.__dict__.get("_host_inv", {}).get("purchase")
    if patched is None:
        pytest.skip("fold took the rebuild path (too many rows changed)")
    rebuilt_model = follower._fold.model
    rebuilt_model.__dict__.pop("_host_inv")
    fresh = rebuilt_model.host_inverted("purchase")
    for a, b in zip(patched, fresh):
        assert np.array_equal(a, b)


# -- follow-mode edges -------------------------------------------------------


def test_tombstone_mid_follow_forces_restage(fs_storage, host_serving):
    """A tombstone arriving mid-follow invalidates the additive state:
    the next tick must fully restage, and the restaged model must equal
    a from-scratch train (the dead event gone)."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=8), app_id)
    dead_id = fs_storage.l_events.insert(_buy("deadguy", "i0"), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    assert follower.tick() == "idle"
    assert fs_storage.l_events.delete(dead_id, app_id)
    # a snapshot gives the restage AND the reference retrain the same
    # (segment-order) staging source, so the comparison below can be
    # array-exact — with a tombstone and no snapshot the reference falls
    # to the row-object read path, whose batch ORDER (hence item-id
    # assignment) legitimately differs
    fs_storage.l_events.build_snapshot(app_id)
    assert follower.tick() == "restage"
    model = follower._fold.model
    assert model.user_dict.id("deadguy") is None
    algo = URAlgorithm(ap)
    queries = [URQuery(user=f"u{u}", num=6) for u in range(0, 12, 3)]
    _assert_model_equals_fresh(model, engine, ep, queries, algo)


def test_max_lag_breach_restages(fs_storage, host_serving):
    """A delta past PIO_FOLLOW_MAX_LAG_EVENTS rebuilds instead of
    folding — and the rebuild is still exact."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=9), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    follower.max_lag = 2
    fs_storage.l_events.insert_batch(
        [_buy(f"u{k}", "i1") for k in range(20, 26)], app_id)
    assert follower.tick() == "restage"
    algo = URAlgorithm(ap)
    _assert_model_equals_fresh(
        follower._fold.model, engine, ep,
        [URQuery(user="u21", num=5), URQuery(user="u1", num=5)], algo)


def test_state_budget_falls_back_to_retrain(fs_storage, host_serving,
                                            monkeypatch):
    """PIO_FOLLOW_STATE_BYTES breach → FoldUnsupported → the follower
    keeps publishing through full retrains."""
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import QueryServerState

    monkeypatch.setenv("PIO_FOLLOW_STATE_BYTES", "1")
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=10), app_id)
    core_workflow.run_train(engine, ep, engine_id="swap-eng",
                            storage=fs_storage)
    state = QueryServerState(
        engine, ep, UniversalRecommenderEngine.query_class, "swap-eng",
        "1", "default", storage=fs_storage)
    follower = state.follower = FollowTrainer(
        engine, ep, "swap-eng", storage=fs_storage, interval=3600,
        on_publish=state.swap_models, persist=False)
    assert follower.mode == "fold"       # resolves optimistically...
    assert follower.bootstrap()
    assert follower.mode == "retrain"    # ...and demotes on the budget
    gen = state.generation
    fs_storage.l_events.insert_batch([_buy("late", "i1")], app_id)
    assert follower.tick() == "retrain"
    assert state.generation == gen + 1


def test_follow_kill_switch_and_metrics(fs_storage, host_serving,
                                        monkeypatch):
    """PIO_FOLLOW=off idles the loop; outcomes land in
    pio_follow_folds_total and swaps bump pio_model_generation."""
    from predictionio_tpu.obs.metrics import get_registry

    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=11), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    reg = get_registry()
    monkeypatch.setenv("PIO_FOLLOW", "off")
    assert follower.tick() == "disabled"
    monkeypatch.delenv("PIO_FOLLOW")
    before = reg.counter("pio_follow_folds_total", "x").value(outcome="fold")
    fs_storage.l_events.insert_batch([_buy("kk", "i2")], app_id)
    assert follower.tick() == "fold"
    assert reg.counter("pio_follow_folds_total",
                       "x").value(outcome="fold") == before + 1
    assert reg.gauge("pio_model_generation", "x").value() >= 2
    fresh = state.freshness()
    assert fresh["generation"] == state.generation
    assert fresh["follower"]["lastOutcome"] == "fold"


def test_transient_publish_failure_retries_next_tick(fs_storage,
                                                     host_serving):
    """A fold whose publish raises must NOT strand the generation: the
    in-memory watermark has already advanced, so the next (0-event) tick
    must retry the retained publish instead of idling on a stale live
    model."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(
        _seed_events(seed=13) + [_buy("pu", "i0")], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    gen0 = state.generation
    # i9 is brand new, co-purchased with i0 (which probe user "pu" owns)
    fs_storage.l_events.insert_batch(
        [_buy(f"c{j}", t) for j in range(5) for t in ("i0", "i9")], app_id)
    fgen0 = follower.generation
    real = follower.on_publish
    calls = {"n": 0}

    def flaky(models, info):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient swap error")
        return real(models, info)

    follower.on_publish = flaky
    with pytest.raises(OSError):
        follower.tick()
    assert follower.last_outcome == "error"
    assert follower._pending is not None
    # the failed attempt must not consume a generation number
    assert follower.generation == fgen0
    # no new events arrived: without the retry this tick would be "idle"
    assert follower.tick() == "fold"
    assert follower._pending is None
    assert follower.generation == fgen0 + 1
    assert state.generation > gen0
    res = state.predict({"user": "pu", "num": 8})
    assert "i9" in [s.item for s in res.item_scores]


def test_fold_exception_drops_state_and_restages(fs_storage, host_serving,
                                                 monkeypatch):
    """A non-FoldUnsupported error escaping fold() may have partially
    applied the delta — retrying the same suffix on that state would
    double-fold.  The state must be dropped so the next cycle restages."""
    from predictionio_tpu.streaming.fold import URFoldState

    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=17), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    gen0 = state.generation
    fs_storage.l_events.insert_batch([_buy("zz", "i1")], app_id)
    orig = URFoldState.fold

    def boom(self, batch):
        raise MemoryError("transient mid-apply failure")

    monkeypatch.setattr(URFoldState, "fold", boom)
    with pytest.raises(MemoryError):
        follower.tick()
    assert follower._fold is None
    monkeypatch.setattr(URFoldState, "fold", orig)
    assert follower.tick() == "restage"
    assert state.generation > gen0
    res = state.predict({"user": "zz", "num": 8})
    assert res.item_scores, "restaged model must serve the new user"


def test_pipelined_publish_ordering_and_drain(fs_storage, host_serving):
    """ISSUE-13 off-thread warm: with the publisher thread running,
    ticks enqueue emit+publish and return — generations publish strictly
    in fold order, status().coveredEvents reports what the PUBLISHED
    model covers (the drain contract), and the served model ends exactly
    at the from-scratch retrain."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=61), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    n_events = len(follower._fold.batch)
    follower._start_publisher()
    try:
        gens = []
        real = follower.on_publish

        def record(models, info):
            gens.append(info["generation"])
            return real(models, info)

        follower.on_publish = record
        for k in range(4):
            fs_storage.l_events.insert_batch(
                [_buy(f"pipe{k}", "i1")], app_id)
            n_events += 1
            assert follower.tick() == "fold"
        assert follower._flush_publishes(timeout=30)
        # strictly ordered, one generation per fold
        assert gens == sorted(gens) and len(gens) == 4
        assert follower.status()["coveredEvents"] == n_events
        algo = URAlgorithm(ap)
        _assert_model_equals_fresh(
            follower._fold.model, engine, ep,
            [URQuery(user="pipe3", num=5), URQuery(user="u1", num=5)],
            algo)
        # the server really swapped to the last published generation
        res = state.predict({"user": "pipe3", "num": 6})
        assert res.item_scores
    finally:
        follower.stop(timeout=10)


def test_pipelined_publish_failure_restages(fs_storage, host_serving):
    """A generation whose pipelined emit/publish keeps failing is
    abandoned after bounded retries; the loop thread's next tick drops
    the fold state and restages — the follower never wedges silently."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=67), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    follower.interval = 0.01      # fast publisher retry backoff
    follower._start_publisher()
    try:
        real = follower.on_publish
        follower.on_publish = lambda models, info: (_ for _ in ()).throw(
            OSError("permanent swap failure"))
        fs_storage.l_events.insert_batch([_buy("px", "i1")], app_id)
        assert follower.tick() == "fold"
        deadline = time.time() + 30
        while not follower._pub_failed and time.time() < deadline:
            time.sleep(0.05)
        assert follower._pub_failed, "publisher never gave up"
        follower.on_publish = real
        assert follower.tick() == "restage"
        res = state.predict({"user": "px", "num": 6})
        assert res.item_scores is not None
    finally:
        follower.stop(timeout=10)


# -- fold-state checkpoint ---------------------------------------------------


def _persisted_follower(fs_storage, engine, ep, engine_id="ckpt-eng"):
    from predictionio_tpu.streaming.follow import FollowTrainer

    return FollowTrainer(engine, ep, engine_id, storage=fs_storage,
                         interval=3600, persist=True)


def test_checkpoint_restart_skips_covered_prefix(fs_storage, host_serving,
                                                 monkeypatch):
    """A restart with a valid fold-state checkpoint restores the arrays
    and folds ONLY the unapplied suffix — the covered prefix is never
    reparsed (the watermark fallback is patched to prove it's not
    reached), and the published model equals a from-scratch train."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )
    from predictionio_tpu.streaming.follow import FollowTrainer

    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=51), app_id)
    t1 = _persisted_follower(fs_storage, engine, ep)
    assert t1.mode == "fold"
    assert t1.bootstrap()           # publishes + writes the checkpoint
    covered = len(t1._fold.batch)
    npz_path, batch_path = t1._ckpt_paths()
    assert npz_path.exists() and batch_path.exists()
    # "SIGKILL": drop the object; events arrive while down
    suffix = [_buy(f"v{k}", "i1") for k in range(4)] + [_buy("v0", "i2")]
    fs_storage.l_events.insert_batch(suffix, app_id)

    def boom(self, prior):
        raise AssertionError("covered-prefix reparse ran despite a "
                             "valid checkpoint")

    monkeypatch.setattr(FollowTrainer, "_bootstrap_from_watermark", boom)
    t2 = _persisted_follower(fs_storage, engine, ep)
    assert t2.bootstrap()
    assert t2.bootstrap_events == covered
    assert t2.last_fold_events == len(suffix)
    assert t2.last_outcome == "fold"
    algo = URAlgorithm(ap)
    _assert_model_equals_fresh(
        t2._fold.model, engine, ep,
        [URQuery(user="u1", num=5), URQuery(user="v0", num=5)], algo)


def test_checkpoint_env_override_wins(fs_storage, host_serving,
                                      monkeypatch):
    """An EXPLICIT PIO_FOLLOW_STATE that disagrees with the persisted
    representation invalidates the checkpoint — the escape hatch must
    actually switch representations on restart, not be silently
    overridden by the restored state."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=53), app_id)
    t1 = _persisted_follower(fs_storage, engine, ep)
    assert t1.bootstrap()
    assert t1._fold.state_mode == "sparse"
    monkeypatch.setenv("PIO_FOLLOW_STATE", "dense")
    t2 = _persisted_follower(fs_storage, engine, ep)
    assert t2._load_checkpoint() is None     # explicit override refuses
    assert t2.bootstrap()                    # ...and the restage lands
    assert t2._fold.state_mode == "dense"


def test_checkpoint_invalid_falls_back(fs_storage, host_serving):
    """A torn/corrupt checkpoint (truncated npz) and a tombstone change
    while down both fall back to the non-checkpoint restart paths."""
    app_id, engine, ap, ep = _ur_setup(fs_storage, event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=52), app_id)
    dead = fs_storage.l_events.insert(_buy("deadguy", "i0"), app_id)
    t1 = _persisted_follower(fs_storage, engine, ep)
    assert t1.bootstrap()
    # tombstone while "down": the checkpoint must refuse
    assert fs_storage.l_events.delete(dead, app_id)
    t2 = _persisted_follower(fs_storage, engine, ep)
    assert t2._bootstrap_from_checkpoint(t2._load_state()) is False
    # corruption: truncate the npz → loader rejects, full bootstrap
    # still lands through the fallback paths
    npz_path, _ = t1._ckpt_paths()
    npz_path.write_bytes(npz_path.read_bytes()[:64])
    t3 = _persisted_follower(fs_storage, engine, ep)
    assert t3._load_checkpoint() is None
    assert t3.bootstrap()
    assert t3._fold is not None
    assert t3._fold.model.user_dict.id("deadguy") is None


def test_check_freshness_roundtrip_large_catalog():
    """PR-11 tentpole gate: a 4000-item catalog under a 32 MiB budget —
    the dense fold state (64 MiB of counts) would demote to retrain;
    the sparse state must stay in fold mode, reflect appends, and keep
    exact parity (scripts/check_freshness_roundtrip.py --large)."""
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "check_freshness_roundtrip.py"),
         "--large"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


# -- daemon: SIGKILL + watermark restart -------------------------------------


def _daemon_env(tmp_path):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "store"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        "PYTHONPATH": str(REPO),
    })
    return env


def _wait_follow_state(path: Path, timeout=90, min_gen=1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            doc = json.loads(path.read_text())
            if doc.get("generation", 0) >= min_gen:
                return doc
        except (OSError, json.JSONDecodeError):
            pass
        time.sleep(0.2)
    raise AssertionError(f"follow state never reached gen {min_gen}")


def test_daemon_sigkill_restart_refolds_exact_suffix(tmp_path):
    """`pio train --follow` daemon: SIGKILL mid-follow, events appended
    while down, restart — the restart re-reads exactly the covered
    prefix (bootstrapEvents == pre-kill count), folds exactly the
    unapplied suffix (lastFoldEvents == appended count, no double-fold),
    and the published model equals a from-scratch retrain."""
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    variant = {
        "id": "follow-ur",
        "engineFactory": "predictionio_tpu.models.universal_recommender."
                         "UniversalRecommenderEngine",
        "datasource": {"params": {"appName": "DaemonApp",
                                  "eventNames": ["purchase"]}},
        "algorithms": [{"name": "ur", "params": {
            "appName": "DaemonApp", "meshDp": 1,
            "maxCorrelatorsPerItem": 5}}],
    }
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps(variant))
    env = _daemon_env(tmp_path)

    def storage():
        cfg = StorageConfig(
            sources={"FS": {"type": "localfs",
                            "path": str(tmp_path / "store")}},
            repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                            "MODELDATA")})
        st = Storage(cfg)
        set_storage(st)
        return st

    st = storage()
    from predictionio_tpu.storage.base import App

    app_id = st.apps.insert(App(0, "DaemonApp"))
    n_initial = 0
    evs = [_buy(f"u{u}", f"i{it}") for u in range(10) for it in range(5)
           if (u + it) % 2]
    n_initial = len(evs)
    st.l_events.insert_batch(evs, app_id)

    follow_state = (tmp_path / "store" / "follow"
                    / "follow-ur-default.json")
    cmd = [sys.executable, "-m", "predictionio_tpu.cli.main", "train",
           "--engine-json", str(ej), "--follow", "--follow-interval", "0.2"]
    proc = subprocess.Popen(cmd, env=env, cwd=str(tmp_path),
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        doc = _wait_follow_state(follow_state, min_gen=1)
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    gen_killed = doc["generation"]
    # appended while the daemon is DOWN: the unapplied suffix
    suffix = [_buy(f"v{k}", "i1") for k in range(4)] + [_buy("v0", "i2")]
    st.l_events.insert_batch(suffix, app_id)
    proc = subprocess.Popen(cmd, env=env, cwd=str(tmp_path),
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        doc = _wait_follow_state(follow_state, min_gen=gen_killed + 1)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
    # exactly the suffix was re-folded (no double-fold, no blind retrain)
    assert doc["bootstrapEvents"] == n_initial, doc
    assert doc["lastFoldEvents"] == len(suffix), doc
    # the published generation equals a from-scratch retrain
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm, URAlgorithmParams, URDataSourceParams,
    )
    from predictionio_tpu.workflow import core_workflow

    ap = URAlgorithmParams(app_name="DaemonApp", mesh_dp=1,
                           max_correlators_per_item=5)
    ep = EngineParams(
        data_source_params=URDataSourceParams(app_name="DaemonApp",
                                              event_names=["purchase"]),
        algorithm_params_list=[("ur", ap)])
    engine = UniversalRecommenderEngine.apply()
    _instance, models = core_workflow.load_latest_models(
        "follow-ur", "1", "default", st)
    algo = URAlgorithm(ap)
    ref = _fresh_ref(engine, ep)
    for q in [URQuery(user="u1", num=5), URQuery(user="v0", num=5),
              URQuery(user="v3", num=5)]:
        assert _canon(algo.predict(models[0], q)) \
            == _canon(algo.predict(ref, q))
    set_storage(None)


# -- script wrapper ----------------------------------------------------------


def test_check_freshness_roundtrip_script():
    """Tier-1 wrapper for scripts/check_freshness_roundtrip.py: live
    deploy + embedded follower, append→fold→reflected rounds with exact
    parity vs a from-scratch retrain and zero 5xx."""
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "check_freshness_roundtrip.py")],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_freshness_roundtrip_sharded():
    """The same roundtrip over the sharded event store (shards=2):
    `pio deploy --follow` and delta staging work unchanged when events
    are hash-partitioned — the PR 9 acceptance gate."""
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "check_freshness_roundtrip.py"),
         "--storage", "sharded", "--shards", "2"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
