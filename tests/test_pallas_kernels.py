"""Pallas kernels vs their pure-XLA references (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("PIO_PALLAS", "interpret")


def test_masked_score_matmul_matches_xla():
    from predictionio_tpu.ops.pallas_kernels import masked_score_matmul

    rng = np.random.default_rng(0)
    b, k, n_items = 5, 12, 300   # deliberately unaligned shapes
    u = rng.normal(size=(b, k)).astype(np.float32)
    v = rng.normal(size=(n_items, k)).astype(np.float32)
    seen = (rng.random((b, n_items)) < 0.1).astype(np.float32)
    bias = rng.normal(size=n_items).astype(np.float32)

    got = np.asarray(masked_score_matmul(jnp.asarray(u), jnp.asarray(v), jnp.asarray(seen), jnp.asarray(bias)))
    want = u @ v.T + bias[None, :]
    want = np.where(seen > 0, -np.inf, want)
    assert got.shape == (b, n_items)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_recommend_batch_fused_matches_unfused(monkeypatch):
    from predictionio_tpu.ops.als import recommend_batch
    from predictionio_tpu.ops.pallas_kernels import recommend_batch_fused

    rng = np.random.default_rng(1)
    b, k, n_items = 4, 16, 257
    u = jnp.asarray(rng.normal(size=(b, k)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(n_items, k)), jnp.float32)
    seen = jnp.asarray((rng.random((b, n_items)) < 0.2), jnp.float32)

    monkeypatch.setenv("PIO_PALLAS", "0")       # pure-XLA reference path
    s1, i1 = recommend_batch(u, v, seen, 10)
    monkeypatch.setenv("PIO_PALLAS", "interpret")
    s2, i2 = recommend_batch_fused(u, v, seen, 10)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_llr_masked_scores_matches_reference():
    from predictionio_tpu.ops.cco import llr_score
    from predictionio_tpu.ops.pallas_kernels import llr_masked_scores

    rng = np.random.default_rng(2)
    r, c = 37, 190
    counts = rng.integers(0, 20, size=(r, c)).astype(np.float32)
    row = counts.sum(1) + rng.integers(0, 50, r)     # row marginal ≥ cooccurrence
    col = counts.sum(0) + rng.integers(0, 50, c)
    n_total = float(row.sum() + 1000)
    thr = 2.0

    got = np.asarray(
        llr_masked_scores(jnp.asarray(counts), jnp.asarray(row.astype(np.float32)),
                          jnp.asarray(col.astype(np.float32)), n_total, thr)
    )
    k11 = counts
    k12 = row[:, None] - counts
    k21 = col[None, :] - counts
    k22 = n_total - k11 - k12 - k21
    want = np.asarray(llr_score(jnp.asarray(k11), jnp.asarray(k12), jnp.asarray(k21), jnp.asarray(k22)))
    want = np.where((counts > 0) & (want >= thr), want, -np.inf)

    finite = np.isfinite(want)
    assert (np.isfinite(got) == finite).all()
    np.testing.assert_allclose(got[finite], want[finite], rtol=1e-4, atol=1e-4)


def test_cco_indicators_pallas_matches_xla(monkeypatch):
    from predictionio_tpu.ops.cco import block_interactions, cco_indicators, interaction_counts

    rng = np.random.default_rng(3)
    n_users, n_ip, n_it = 60, 25, 40
    pu = rng.integers(0, n_users, 400)
    pi = rng.integers(0, n_ip, 400)
    ou = rng.integers(0, n_users, 800)
    oi = rng.integers(0, n_it, 800)
    p = block_interactions(pu, pi, n_users, n_ip, user_block=16)
    o = block_interactions(ou, oi, n_users, n_it, user_block=16)
    rc, cc = interaction_counts(pi, n_ip), interaction_counts(oi, n_it)

    monkeypatch.setenv("PIO_PALLAS", "0")
    s1, i1 = cco_indicators(p, o, rc, cc, n_users, top_k=5, llr_threshold=1.0, item_tile=16)
    monkeypatch.setenv("PIO_PALLAS", "interpret")
    s2, i2 = cco_indicators(p, o, rc, cc, n_users, top_k=5, llr_threshold=1.0, item_tile=16)

    finite = np.isfinite(s1)
    assert (np.isfinite(s2) == finite).all()
    np.testing.assert_allclose(s1[finite], s2[finite], rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(i1, i2)


def test_pallas_mode_env(monkeypatch):
    from predictionio_tpu.ops import pallas_kernels as pk

    monkeypatch.setenv("PIO_PALLAS", "0")
    assert pk.pallas_mode() == "off" and not pk.pallas_enabled()
    monkeypatch.setenv("PIO_PALLAS", "interpret")
    assert pk.pallas_mode() == "interpret" and pk.pallas_enabled()
    monkeypatch.setenv("PIO_PALLAS", "compiled")
    assert pk.pallas_mode() == "compiled"
