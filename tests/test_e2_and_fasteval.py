"""e2 helper + FastEval memoization tests (reference analogues:
CategoricalNaiveBayesTest, MarkovChainTest, BinaryVectorizerTest,
FastEvalEngineTest — SURVEY.md §4)."""

import numpy as np
import pytest

from predictionio_tpu.e2 import (
    BinaryVectorizer,
    CategoricalNaiveBayes,
    MarkovChain,
    k_fold_split,
)


def test_binary_vectorizer():
    rows = [{"color": "red", "size": "L"}, {"color": "blue", "size": "L"}]
    v = BinaryVectorizer.fit(rows, ["color", "size"])
    assert v.width == 3
    a = v.transform({"color": "red", "size": "L"})
    assert a.sum() == 2 and a[v.index[("color", "red")]] == 1
    b = v.transform({"color": "green"})  # unseen value -> all zeros
    assert b.sum() == 0


def test_categorical_naive_bayes():
    points = [
        ("play", ["sunny", "weekend"]), ("play", ["sunny", "weekend"]),
        ("play", ["cloudy", "weekend"]), ("stay", ["rainy", "weekday"]),
        ("stay", ["rainy", "weekend"]), ("stay", ["cloudy", "weekday"]),
    ]
    model = CategoricalNaiveBayes.train(points)
    assert CategoricalNaiveBayes.predict(model, ["sunny", "weekend"]) == "play"
    assert CategoricalNaiveBayes.predict(model, ["rainy", "weekday"]) == "stay"
    # unseen value falls back to default likelihood without crashing
    assert CategoricalNaiveBayes.predict(model, ["snowy", "weekend"]) in ("play", "stay")


def test_markov_chain():
    transitions = [(0, 1), (0, 1), (0, 2), (1, 2), (2, 0)]
    mc = MarkovChain.train(transitions, n_states=3, top_k=2)
    nxt = mc.next_states(0)
    assert nxt[0][0] == 1 and abs(nxt[0][1] - 2 / 3) < 1e-6
    assert nxt[1][0] == 2 and abs(nxt[1][1] - 1 / 3) < 1e-6


def test_k_fold_split():
    data = list(range(100))
    folds = list(k_fold_split(data, 4, seed=1))
    assert len(folds) == 4
    for train, test in folds:
        assert sorted(train + test) == data
    all_test = sorted(sum((t for _, t in folds), []))
    assert all_test == data
    with pytest.raises(ValueError):
        list(k_fold_split(data, 1))


def test_fast_eval_memoizes_stages():
    import dataclasses

    from predictionio_tpu.controller import (
        Algorithm, AverageMetric, DataSource, Engine, EngineParams,
        FirstServing, MetricEvaluator, Params, Preparator,
    )
    from predictionio_tpu.workflow.fast_eval import FastEvalEngine

    calls = {"read_eval": 0, "prepare": 0, "train": 0}

    @dataclasses.dataclass
    class AP(Params):
        mult: float = 1.0

    class DS(DataSource):
        def read_training(self):
            return list(range(10))

        def read_eval(self):
            calls["read_eval"] += 1
            return [(list(range(10)), None, [(q, q * 2.0) for q in range(5)])]

    class Prep(Preparator):
        def prepare(self, td):
            calls["prepare"] += 1
            return td

    class Algo(Algorithm):
        params_class = AP

        def train(self, pd):
            calls["train"] += 1
            return self.params.mult

        def predict(self, model, q):
            return q * model

    class M(AverageMetric):
        higher_is_better = False

        def score_one(self, q, p, a):
            return abs(p - a)

    engine = Engine(DS, Prep, {"a": Algo}, FirstServing)
    candidates = [
        EngineParams(algorithm_params_list=[("a", AP(mult=m))]) for m in (1.0, 2.0, 3.0)
    ]
    fast = FastEvalEngine(engine)
    result = MetricEvaluator(M()).evaluate(engine, candidates, eval_runner=fast.eval)
    # D and P ran once despite 3 candidates; A ran once per candidate
    assert calls["read_eval"] == 1
    assert calls["prepare"] == 1
    assert calls["train"] == 3
    assert result.best_engine_params.algorithm_params_list[0][1].mult == 2.0
    # repeating a candidate hits the model cache
    MetricEvaluator(M()).evaluate(engine, candidates[:1], eval_runner=fast.eval)
    assert calls["train"] == 3
    assert fast.stats["models_hit"] >= 1


def test_params_grid_expands_cartesian():
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.controller.evaluation import params_grid
    from predictionio_tpu.models.recommendation.engine import ALSAlgorithmParams

    base = EngineParams(algorithm_params_list=[
        ("als", ALSAlgorithmParams(rank=4, num_iterations=2))])
    grid = params_grid(base, "als", {"rank": [4, 8], "lambda_": [0.01, 0.1]})
    assert len(grid) == 4
    combos = {(ep.algorithm_params_list[0][1].rank,
               ep.algorithm_params_list[0][1].lambda_) for ep in grid}
    assert combos == {(4, 0.01), (4, 0.1), (8, 0.01), (8, 0.1)}
    # base is untouched
    assert base.algorithm_params_list[0][1].rank == 4
    with pytest.raises(ValueError):
        params_grid(base, "nope", {"rank": [1]})


def test_eval_with_params_generator_cli(tmp_path, mem_storage, monkeypatch):
    """`pio eval <Evaluation> <EngineParamsGenerator>`: the generator's grid
    becomes the candidate list and the best params are recorded."""
    import sys
    import types

    from predictionio_tpu.cli.main import main as pio_main
    from predictionio_tpu.controller.engine import Engine, EngineParams
    from predictionio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation, Metric, params_grid)

    class FakeMetric(Metric):
        def score_one(self, q, p, a):
            return float(p == a)

    class FakeEval(Evaluation):
        def __init__(self):
            super().__init__(engine=object(), metric=FakeMetric())

        def run(self, eval_runner=None):
            # scores favor the candidate whose dict param x == 2
            from predictionio_tpu.controller.evaluation import (
                MetricEvaluator)
            ev = MetricEvaluator(self.metric)
            return ev.evaluate(
                self.engine, list(self.engine_params_list),
                eval_runner=lambda eng, ep: [
                    (None, [(0, ep.algorithm_params_list[0][1]["x"], 2)])])

    class Gen(EngineParamsGenerator):
        engine_params_list = params_grid(
            EngineParams(algorithm_params_list=[("a", {"x": 1})]),
            "a", {"x": [1, 2, 3]})

    mod = types.ModuleType("fake_eval_mod")
    mod.FakeEval = FakeEval
    mod.Gen = Gen
    monkeypatch.setitem(sys.modules, "fake_eval_mod", mod)
    rc = pio_main(["eval", "fake_eval_mod.FakeEval", "fake_eval_mod.Gen"])
    assert rc == 0
    done = mem_storage.evaluation_instances.get_completed()
    assert done and '"x": 2' in done[-1].evaluator_results_json
