"""Parallel cross-shard scan-and-stage pipeline (PR 12 tentpole).

The contracts under test:

- **parallel ≡ sequential**: the fan-out merge at ``PIO_SCAN_WORKERS>1``
  is bit-exact (row order, decoded values, property columns, id column,
  watermarks) vs the ``PIO_SCAN_WORKERS=1`` forced-serial oracle, on
  randomized multi-shard corpora with DISAGREEING per-shard property
  dictionaries and tombstones.
- **merged cross-shard snapshot**: ``build_snapshot`` persists the
  k-way merge; scans serve it at single-shard cost, stay correct across
  appends (tail splice), late tombstones (id-column mask), and fall
  back to the live fan-out when the manifest goes stale.
- **delta staging**: a parallel ``scan_tail_from`` with ``base`` merges
  INTO the base dictionaries (the shared-dict splice contract).
- **failover**: a shard partitioned mid-parallel-fan-out promotes and
  re-reads — every surviving event exactly once.
- **find**: the k-way heap-merge honors global time order and pushes
  ``limit`` down to each shard.
"""

import datetime as dt
import os
import shutil

import numpy as np
import pytest

from predictionio_tpu.storage import localfs
from predictionio_tpu.storage.sharded import (
    ShardedEvents,
    _scan_workers,
    _M_SCAN_WORKERS,
)
from predictionio_tpu.store.columnar import BatchMerger, EventBatch


def _wire(k, rng):
    """One wire event; property value domains differ per entity so the
    per-shard property dictionaries disagree."""
    d = {
        "event": ("buy", "view", "$set")[k % 3],
        "entityType": "user" if k % 3 != 2 else "item",
        "entityId": f"u{k % 13}" if k % 3 != 2 else f"i{k % 7}",
        "eventId": f"e{k}",
        "eventTime": (dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
                      + dt.timedelta(seconds=k)).isoformat(),
    }
    if k % 3 != 2:
        d["targetEntityType"] = "item"
        d["targetEntityId"] = f"i{k % 29}"
    if k % 4:
        d["properties"] = {
            "rating": int(rng.integers(0, 6)),
            "color": f"c{rng.integers(0, 9)}",
            "tags": [f"t{rng.integers(0, 5)}" for _ in range(k % 3)],
        }
    return d


def canon(batch, ids=None):
    """Decoded row tuples — the code-independent view both paths must
    agree on, row order included."""
    idl = ids.tolist() if ids is not None else [None] * len(batch)
    rows = []
    for j in range(len(batch)):
        props = {}
        if batch.prop_columns is not None:
            for key, col in batch.prop_columns.items():
                pos = int(np.searchsorted(col.rows, j))
                if pos < len(col) and col.rows[pos] == j:
                    props[key] = col.value_at(pos)
        t = int(batch.target_ids[j])
        r = float(batch.ratings[j])
        rows.append((
            idl[j],
            batch.event_dict.str(int(batch.event_codes[j])),
            batch.entity_type_dict.str(int(batch.entity_type_codes[j])),
            batch.entity_dict.str(int(batch.entity_ids[j])),
            batch.target_dict.str(t) if t >= 0 else None,
            int(batch.times_us[j]),
            None if np.isnan(r) else r,
            tuple(sorted(props.items())),
        ))
    return rows


@pytest.fixture()
def store3(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FSYNC", "rotate")
    ev = ShardedEvents(tmp_path / "s", shards=3, replicas=1)
    rng = np.random.default_rng(12)
    items = [_wire(k, rng) for k in range(240)]
    res = ev.insert_json_batch(items, 1)
    assert all(r["status"] == 201 for r in res)
    for k in (3, 17, 101, 200):       # tombstones, spread across shards
        assert ev.delete(f"e{k}", 1)
    yield ev
    ev.close()


def _drop_merged(ev):
    """Force the live fan-out path (hide the merged snapshot)."""
    shutil.rmtree(ev._chan_dir(1, None), ignore_errors=True)


def test_parallel_matches_serial_oracle(store3, monkeypatch):
    """Fan-out merge at workers=4 is bit-exact vs the workers=1 oracle:
    same rows in the same order, same decoded props (disagreeing
    per-shard dictionaries re-coded identically), same id column, same
    namespaced watermark — with some shards snapshot-backed and one on
    the full-parse fallback."""
    store3.build_snapshot(1)
    _drop_merged(store3)
    # one shard loses its snapshot → exercises the mixed
    # snapshot/full-parse fan-out
    shutil.rmtree(store3._shards[2].node_root("a") / "events" / "app_1"
                  / "_default" / "snapshot")
    monkeypatch.setenv("PIO_SCAN_WORKERS", "4")
    par = store3._fanout_snapshot_scan(1)
    assert int(_M_SCAN_WORKERS.value()) == 3       # capped at shards
    monkeypatch.setenv("PIO_SCAN_WORKERS", "1")
    ser = store3._fanout_snapshot_scan(1)
    assert par["events"] == ser["events"] == 236
    assert par["watermark"] == ser["watermark"]
    assert par["heads"] == ser["heads"]
    assert canon(par["batch"], par["ids"]) == canon(ser["batch"],
                                                    ser["ids"])
    # bit-exact down to the dictionary codes
    for col in ("event_codes", "entity_type_codes", "entity_ids",
                "target_ids", "times_us"):
        assert np.array_equal(getattr(par["batch"], col),
                              getattr(ser["batch"], col)), col
    assert np.array_equal(par["ids"].blob, ser["ids"].blob)
    assert np.array_equal(par["ids"].offs, ser["ids"].offs)


def test_merged_snapshot_serves_and_tracks_staleness(store3, monkeypatch):
    """The persisted merged snapshot returns the same event set as the
    live fan-out, splices appended tails, masks late tombstones via the
    id column, and never resurrects a deleted event."""
    monkeypatch.setenv("PIO_SCAN_WORKERS", "4")
    store3.build_snapshot(1)
    merged = store3.snapshot_scan(1)
    assert merged["snap_events"] == 236 and merged["tail_events"] == 0
    live = store3._fanout_snapshot_scan(1)
    assert sorted(canon(merged["batch"], merged["ids"])) == \
        sorted(canon(live["batch"], live["ids"]))
    assert merged["watermark"] == live["watermark"]
    # append → tail splice on the merged path
    store3.insert_json_batch(
        [{"event": "buy", "entityType": "user", "entityId": f"u{j}",
          "targetEntityType": "item", "targetEntityId": "iNEW",
          "eventId": f"n{j}", "properties": {"color": "brand-new"}}
         for j in range(9)], 1)
    res = store3.snapshot_scan(1)
    assert res["snap_events"] == 236 and res["tail_events"] == 9
    ids = {r[0] for r in canon(res["batch"], res["ids"])}
    assert "n8" in ids and "e3" not in ids
    # late tombstone → id-column mask, not a resurrect
    assert store3.delete("e30", 1)
    res = store3.snapshot_scan(1)
    assert res["events"] == 244
    ids = {r[0] for r in canon(res["batch"], res["ids"])}
    assert "e30" not in ids
    # a recreated segment (data-delete) invalidates the merged manifest:
    # the scan falls back and still answers correctly
    chan = (store3._shards[0].node_root("a") / "events" / "app_1"
            / "_default")
    seg = sorted(chan.glob("seg-*.jsonl"))[0]
    lines = seg.read_bytes()
    seg.write_bytes(b'{"event":"buy","entityType":"user","entityId":"uZ",'
                    b'"eventId":"zz0","eventTime":"2026-01-01T00:00:00Z"}\n')
    res2 = store3.snapshot_scan(1)
    assert res2 is not None
    ids2 = {r[0] for r in canon(res2["batch"],
                                res2.get("ids"))}
    assert "zz0" in ids2
    seg.write_bytes(lines)    # restore for fixture teardown sanity


def test_scan_tail_from_merges_into_base_dicts(store3, monkeypatch):
    """Parallel delta staging keeps the shared-dict splice contract:
    the merged tail carries the base's dictionary OBJECTS, so
    concat([base, tail]) takes the zero-re-code fast path; the result
    decodes identically to the workers=1 oracle."""
    monkeypatch.setenv("PIO_SCAN_WORKERS", "4")
    store3.build_snapshot(1)
    snap = store3.snapshot_scan(1)
    base = snap["batch"]
    store3.insert_json_batch(
        [{"event": "buy", "entityType": "user", "entityId": f"u{j % 13}",
          "targetEntityType": "item", "targetEntityId": f"iT{j}",
          "eventId": f"t{j}", "properties": {"color": f"cT{j % 4}"}}
         for j in range(20)], 1)
    tail = store3.scan_tail_from(1, None, snap["watermark"], base=base,
                                 heads=snap["heads"])
    assert tail["events"] == 20
    for d in ("event_dict", "entity_type_dict", "entity_dict",
              "target_dict"):
        assert getattr(tail["batch"], d) is getattr(base, d), d
    assert tail["batch"].prop_columns["color"].dict \
        is base.prop_columns["color"].dict
    spliced = EventBatch.concat([base, tail["batch"]])
    assert spliced.event_dict is base.event_dict      # fast path took
    monkeypatch.setenv("PIO_SCAN_WORKERS", "1")
    ser = store3.scan_tail_from(1, None, snap["watermark"], base=None,
                                heads=snap["heads"])
    assert canon(tail["batch"], tail["ids"]) == canon(ser["batch"],
                                                      ser["ids"])
    assert tail["watermark"] == ser["watermark"]
    # scan_events_up_to parity over the new watermark
    up_p = store3.scan_events_up_to(1, None, tail["watermark"],
                                    heads=tail["heads"])
    monkeypatch.setenv("PIO_SCAN_WORKERS", "4")
    up_s = store3.scan_events_up_to(1, None, tail["watermark"],
                                    heads=tail["heads"])
    assert up_p["events"] == up_s["events"] == len(spliced)
    assert canon(up_p["batch"]) == canon(up_s["batch"])


def test_partition_mid_fanout_promotes_and_dedups(tmp_path, monkeypatch):
    """A primary yanked while its shard's worker is mid-fan-out: the
    worker promotes the replica and re-reads — the merged result holds
    every acked event exactly once, identical to the serial oracle run
    on the promoted topology."""
    monkeypatch.setenv("PIO_FSYNC", "always")
    monkeypatch.setenv("PIO_SCAN_WORKERS", "2")
    ev = ShardedEvents(tmp_path / "s", shards=2, replicas=2)
    try:
        res = ev.insert_json_batch(
            [{"event": "buy", "entityType": "user", "entityId": f"u{k}",
              "eventId": f"e{k}"} for k in range(40)], 1)
        assert all(r["status"] == 201 for r in res)   # acked ⇒ replicated
        fired = {}
        orig = localfs.FSEvents.scan_tail_from

        def boom(self, *a, **kw):
            root = getattr(self, "_node_root", None)
            if (not fired and root is not None and root.name == "a"
                    and root.parent.name == "shard_00"):
                fired["yank"] = True
                lost = root.parent / "a.lost"
                shutil.move(str(root), str(lost))
                raise OSError("injected partition mid-fan-out")
            return orig(self, *a, **kw)

        monkeypatch.setattr(localfs.FSEvents, "scan_tail_from", boom)
        res = ev._fanout_snapshot_scan(1)
        assert fired, "injection never triggered"
        got = [r[0] for r in canon(res["batch"], res["ids"])]
        assert sorted(got) == sorted(f"e{k}" for k in range(40))
        assert len(got) == len(set(got)) == 40        # exactly once
        assert ev._shards[0].topology()["epoch"] >= 1  # promoted
        monkeypatch.setattr(localfs.FSEvents, "scan_tail_from", orig)
        monkeypatch.setenv("PIO_SCAN_WORKERS", "1")
        ser = ev._fanout_snapshot_scan(1)
        assert canon(res["batch"], res["ids"]) == canon(ser["batch"],
                                                        ser["ids"])
    finally:
        ev.close()


def test_find_heap_merge_order_and_limit_pushdown(tmp_path, monkeypatch):
    """Merged find: global (eventTime, creationTime) order across
    shards, limit honored, and the limit pushed down to each shard
    instead of materializing every event."""
    monkeypatch.setenv("PIO_FSYNC", "rotate")
    ev = ShardedEvents(tmp_path / "s", shards=3, replicas=1)
    try:
        items = [{"event": "buy", "entityType": "user",
                  "entityId": f"u{k}", "eventId": f"e{k}",
                  "eventTime": (dt.datetime(2026, 2, 1,
                                            tzinfo=dt.timezone.utc)
                                + dt.timedelta(seconds=k)).isoformat()}
                 for k in range(60)]
        ev.insert_json_batch(items, 1)
        seen_limits = []
        orig = localfs.FSEvents.find

        def spy(self, app_id, **kw):
            seen_limits.append(kw.get("limit"))
            return orig(self, app_id, **kw)

        monkeypatch.setattr(localfs.FSEvents, "find", spy)
        got = [e.event_id for e in ev.find(1, limit=7)]
        assert got == [f"e{k}" for k in range(7)]
        assert seen_limits == [7, 7, 7]               # pushed down
        rev = [e.event_id for e in ev.find(1, limit=5,
                                           reversed_order=True)]
        assert rev == [f"e{k}" for k in range(59, 54, -1)]
        everything = [e.event_id for e in ev.find(1)]
        assert everything == [f"e{k}" for k in range(60)]
    finally:
        ev.close()


def test_scan_workers_env_parsing(monkeypatch):
    monkeypatch.setenv("PIO_SCAN_WORKERS", "3")
    assert _scan_workers(8) == 3
    assert _scan_workers(2) == 2          # capped at shard count
    monkeypatch.setenv("PIO_SCAN_WORKERS", "not-a-number")
    assert _scan_workers(1) == 1
    monkeypatch.setenv("PIO_SCAN_WORKERS", "0")
    assert _scan_workers(64) == min(64, os.cpu_count() or 1)
    monkeypatch.delenv("PIO_SCAN_WORKERS")
    assert _scan_workers(64) >= 1


def test_batch_merger_matches_pairwise_concat():
    """Property-based spot check: one k-way BatchMerger pass equals the
    semantics of pairwise EventBatch.concat on batches with disjoint
    AND overlapping dictionaries."""
    from predictionio_tpu.events.event import Event

    rng = np.random.default_rng(5)

    def mk(lo, hi, n):
        evs = [Event(event=f"ev{int(rng.integers(0, 3))}",
                     entity_type="user",
                     entity_id=f"u{int(rng.integers(lo, hi))}",
                     target_entity_type="item",
                     target_entity_id=(f"i{int(rng.integers(lo, hi))}"
                                       if rng.random() > 0.3 else None),
                     properties={"rating": float(int(rng.integers(0, 5)))}
                     if rng.random() > 0.5 else {})
                for _ in range(n)]
        return EventBatch.from_events(evs)

    parts = [mk(0, 9, 17), mk(5, 14, 11), mk(100, 109, 23)]
    pairwise = parts[0]
    for p in parts[1:]:
        pairwise = EventBatch.concat([pairwise, p])
    merger = BatchMerger()
    for p in parts:
        merger.add(p)
    kway, _ids = merger.finish()
    assert canon(kway) == canon(pairwise)
