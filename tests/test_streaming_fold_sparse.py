"""Sparse fold state: sparse≡dense bit-exactness, the budget boundary,
and the fold-state checkpoint.

The PR-11 tentpole replaces the fold engine's dense [I_p, I_t] count
matrices with sorted-COO cells.  Its contract is that the representation
is INVISIBLE: for any delta sequence — item-space growth, mid-array code
inserts, duplicate-only deltas, marginal-changing new users — the sparse
state's emitted models are bit-identical to the dense state's, and both
to a from-scratch train.  These tests drive both representations over
the same storage tails (no mocks on the exactness path) and pin the
budget-demotion boundary the sparse state moves.
"""

import numpy as np
import pytest

from test_streaming_follow import (  # shared fixtures/helpers
    _buy,
    _seed_events,
    _set_item,
    _tail,
    _ur_setup,
    host_serving,  # noqa: F401  (fixture re-export)
)


def _two_states(ap, ds, batch, monkeypatch):
    """Bootstrap one sparse and one dense URFoldState from ONE batch
    object (shared dicts, so one storage tail feeds both)."""
    from predictionio_tpu.streaming.fold import URFoldState

    monkeypatch.setenv("PIO_FOLLOW_STATE", "sparse")
    sparse = URFoldState.bootstrap(ap, ds, batch)
    monkeypatch.setenv("PIO_FOLLOW_STATE", "dense")
    dense = URFoldState.bootstrap(ap, ds, batch)
    monkeypatch.delenv("PIO_FOLLOW_STATE")
    assert sparse.state_mode == "sparse" and dense.state_mode == "dense"
    return sparse, dense


def _assert_models_equal(ma, mb, ctx=""):
    assert ma.item_dict.strings() == mb.item_dict.strings(), ctx
    assert ma.user_dict.strings() == mb.user_dict.strings(), ctx
    assert set(ma.indicator_idx) == set(mb.indicator_idx), ctx
    for name in ma.indicator_idx:
        assert np.array_equal(ma.indicator_idx[name],
                              mb.indicator_idx[name]), (ctx, name)
        assert np.array_equal(ma.indicator_llr[name],
                              mb.indicator_llr[name]), (ctx, name)
        assert (ma.event_item_dicts[name].strings()
                == mb.event_item_dicts[name].strings()), (ctx, name)
    assert np.array_equal(ma.popularity, mb.popularity), ctx
    assert np.array_equal(ma.user_seen.indptr, mb.user_seen.indptr), ctx
    assert np.array_equal(ma.user_seen.values, mb.user_seen.values), ctx
    assert ma.item_properties == mb.item_properties, ctx


@pytest.mark.parametrize("dense_rellr", ["0", "default"])
def test_sparse_equals_dense_randomized(fs_storage, host_serving,
                                        monkeypatch, dense_rellr):
    """Randomized delta property test: across folds mixing item-space
    growth, duplicates, new users (marginal changes), property $sets and
    single-pair sliced re-LLRs, the sparse and dense states emit
    bit-identical models — and the final model equals a from-scratch
    train.  Runs twice: with the small-catalog dense-kernel routing off
    (PIO_FOLLOW_DENSE_RELLR_BYTES=0 — every re-LLR takes the SPARSE
    tail, the at-scale path) and at its default (the tiny-shape fast
    path)."""
    if dense_rellr != "default":
        monkeypatch.setenv("PIO_FOLLOW_DENSE_RELLR_BYTES", dense_rellr)
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    app_id, engine, ap, ep = _ur_setup(
        fs_storage, indicator_params={"view": {"maxCorrelatorsPerItem": 4}})
    rng = np.random.default_rng(23)
    fs_storage.l_events.insert_batch(_seed_events(seed=31), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item(f"i{k}", {"category": "red" if k < 4 else "blue"})
         for k in range(8)], app_id)
    tail = _tail(fs_storage, app_id, {}, None, None)
    sparse, dense = _two_states(ap, ep.data_source_params, tail["batch"],
                                monkeypatch)
    _assert_models_equal(sparse.model, dense.model, "bootstrap")
    wm, heads = tail["watermark"], tail["heads"]
    for rnd in range(6):
        evs = []
        # duplicates of existing events
        evs += [_buy(f"u{int(u)}", f"i{int(it)}")
                for u in rng.integers(0, 12, 3)
                for it in rng.integers(0, 8, 2)]
        if rnd % 2:
            # marginal change: brand-new users, sometimes new items
            base = 100 + rnd * 10
            evs += [_buy(f"u{base + int(u)}", f"i{int(it)}")
                    for u in range(2) for it in rng.integers(0, 10, 3)]
        if rnd == 2:
            # items seen ONLY as views earlier get purchased now: their
            # target codes predate every purchase code → mid-array
            # insert + full state remap
            evs += [_buy("u1", f"i{k}", event="view") for k in (20, 21)]
        if rnd == 3:
            evs += [_buy("u2", "i20"), _buy("u3", "i21")]
        if rnd == 4:
            evs += [_set_item("i2", {"category": "green"})]
        if rnd == 5:
            # single primary pair from an existing user → sliced re-LLR
            evs = [_buy("u0", "i6")]
        fs_storage.l_events.insert_batch(evs, app_id)
        tail = _tail(fs_storage, app_id, wm, sparse.batch, heads)
        assert tail is not None and tail["events"] > 0
        ms = sparse.fold(tail["batch"])
        md = dense.fold(tail["batch"])
        wm, heads = tail["watermark"], tail["heads"]
        _assert_models_equal(ms, md, f"round {rnd}")
        assert sparse.last_fold_stats == dense.last_fold_stats, rnd
    # the sliced path really ran somewhere in round 5
    assert any(s["mode"] == "sliced"
               for s in sparse.last_fold_stats.values())
    # both equal a from-scratch retrain at the end
    from predictionio_tpu.store.event_store import invalidate_staging_cache

    invalidate_staging_cache()
    ref = engine.train(ep)[0]
    _assert_models_equal(ms, ref, "vs train")
    algo = URAlgorithm(ap)
    for q in [URQuery(user="u1", num=6), URQuery(user="u101", num=5),
              URQuery(user="nobody", num=4)]:
        got = [(s.item, float(s.score))
               for s in algo.predict(ms, q).item_scores]
        want = [(s.item, float(s.score))
                for s in algo.predict(ref, q).item_scores]
        assert got == want, q


def _cert_counters():
    from predictionio_tpu.obs.metrics import get_registry

    c = get_registry().counter("pio_follow_rellr_rows_total", "x")
    return c.value(outcome="certified"), c.value(outcome="selected")


@pytest.mark.parametrize("dense_rellr", ["0", "default"])
def test_pruned_rellr_equals_full_property(fs_storage, host_serving,
                                           monkeypatch, dense_rellr):
    """ISSUE-13 pruning exactness: across randomized delta sequences —
    new-user N bumps, new items (pure end growth), $set props,
    duplicate-only deltas, and a tombstone restage — the PRUNED full
    re-LLR emits models bit-identical (idx, scores, tie order) to the
    kill-switch (PIO_FOLLOW_RELLR_PRUNE=off) oracle, to the dense-state
    oracle, and finally to a from-scratch train.  The catalog is sized
    past PIO_FOLLOW_DENSE_RELLR_BYTES so the sparse tail (the pruned
    path) runs at DEFAULT routing too, and the counter proves
    certification actually engaged in both parametrizations."""
    if dense_rellr != "default":
        monkeypatch.setenv("PIO_FOLLOW_DENSE_RELLR_BYTES", dense_rellr)
    from predictionio_tpu.streaming.fold import URFoldState

    app_id, engine, ap, ep = _ur_setup(fs_storage,
                                       event_names=("purchase",))
    rng = np.random.default_rng(29)
    # ~1300 items: dense f32 re-LLR matrix ≈ 6.8 MB > the 4 MiB default
    # routing budget → the sparse (prunable) tail runs either way
    evs = [_buy(f"u{k % 120}", f"i{k}") for k in range(1300)]
    evs += [_buy(f"u{u}", f"i{it}") for u in range(10) for it in range(8)
            if (u + it) % 3]
    fs_storage.l_events.insert_batch(evs, app_id)
    dead_id = fs_storage.l_events.insert(_buy("deadguy", "i3"), app_id)
    tail = _tail(fs_storage, app_id, {}, None, None)

    def bootstrap_pair(batch):
        monkeypatch.setenv("PIO_FOLLOW_RELLR_PRUNE", "off")
        full = URFoldState.bootstrap(ap, ep.data_source_params, batch)
        monkeypatch.delenv("PIO_FOLLOW_RELLR_PRUNE")
        pruned = URFoldState.bootstrap(ap, ep.data_source_params, batch)
        monkeypatch.setenv("PIO_FOLLOW_STATE", "dense")
        dense = URFoldState.bootstrap(ap, ep.data_source_params, batch)
        monkeypatch.delenv("PIO_FOLLOW_STATE")
        return pruned, full, dense

    pruned, full, dense = bootstrap_pair(tail["batch"])
    _assert_models_equal(pruned.model, full.model, "bootstrap")
    _assert_models_equal(pruned.model, dense.model, "bootstrap-dense")
    cert0, _sel0 = _cert_counters()
    wm, heads = tail["watermark"], tail["heads"]
    for rnd in range(6):
        evs = []
        if rnd == 0:
            evs = [_buy("fresh_user_a", "i7")]           # pure N bump
        elif rnd == 1:
            evs = [_buy("fresh_user_b", f"brand_new_{rnd}"),
                   _buy("fresh_user_b", "i7")]           # catalog growth
        elif rnd == 2:
            evs = [_buy(f"u{int(u)}", f"i{int(it)}")     # duplicates only
                   for u in rng.integers(0, 10, 4)
                   for it in rng.integers(0, 8, 2) if (u + it) % 3]
            evs = evs or [_buy("u1", "i1")]
        elif rnd == 3:
            evs = [_set_item("i2", {"tier": "gold"})]    # $set props
        elif rnd == 4:
            evs = [_buy(f"nb{j}", f"i{(j * 37) % 1300}")  # many N bumps
                   for j in range(6)]
        elif rnd == 5:
            # tombstone restage: the additive state cannot subtract, so
            # both representations rebootstrap from the live log
            assert fs_storage.l_events.delete(dead_id, app_id)
            fs_storage.l_events.build_snapshot(app_id)
            tail = _tail(fs_storage, app_id, {}, None, None)
            pruned, full, dense = bootstrap_pair(tail["batch"])
            wm, heads = tail["watermark"], tail["heads"]
            _assert_models_equal(pruned.model, full.model, "restage")
            continue
        fs_storage.l_events.insert_batch(evs, app_id)
        t = _tail(fs_storage, app_id, wm, pruned.batch, heads)
        assert t is not None and t["events"] > 0
        mp = pruned.fold(t["batch"])
        monkeypatch.setenv("PIO_FOLLOW_RELLR_PRUNE", "off")
        mf = full.fold(t["batch"])
        monkeypatch.delenv("PIO_FOLLOW_RELLR_PRUNE")
        md = dense.fold(t["batch"])
        wm, heads = t["watermark"], t["heads"]
        _assert_models_equal(mp, mf, f"round {rnd} pruned-vs-full")
        _assert_models_equal(mp, md, f"round {rnd} pruned-vs-dense")
        assert pruned.last_fold_stats == full.last_fold_stats, rnd
    cert1, _sel1 = _cert_counters()
    assert cert1 > cert0, "pruning certificate never engaged"
    # the certificate must be doing real work, not certifying nothing:
    # the pure-N-bump rounds certify (nearly) the whole catalog
    assert cert1 - cert0 > 1000
    from predictionio_tpu.store.event_store import invalidate_staging_cache

    invalidate_staging_cache()
    ref = engine.train(ep)[0]
    _assert_models_equal(pruned.model, ref, "vs train")


def test_select_topk_chunked_matches_inline(monkeypatch):
    """The worker-pool chunked re-selection is bit-identical to one
    global pass, across chunk boundaries and row skew."""
    import predictionio_tpu.streaming.fold as fold_mod
    from predictionio_tpu.ops.cco import _select_topk_cells

    rng = np.random.default_rng(5)
    n_rows, width = 257, 4
    rows = np.sort(rng.integers(0, n_rows, 20_000)).astype(np.int64)
    cols = rng.integers(0, 900, 20_000).astype(np.int64)
    scores = rng.choice(
        np.asarray([0.5, 1.25, 3.0, 7.5], np.float32), 20_000)
    monkeypatch.setattr(fold_mod, "_RELLR_CHUNK_MIN_CELLS", 1)
    monkeypatch.setenv("PIO_FOLLOW_RELLR_WORKERS", "3")
    s_c, i_c = fold_mod._select_topk_chunked(rows, cols, scores,
                                             n_rows, width)
    s_i, i_i = _select_topk_cells(rows, cols, scores, n_rows, width)
    assert np.array_equal(s_c, s_i)
    assert np.array_equal(i_c, i_i)


def test_from_sorted_pairs_matches_from_pairs():
    """CSRLookup.from_sorted_pairs on presorted deduped pairs is
    array-identical to from_pairs."""
    from predictionio_tpu.store.columnar import CSRLookup

    rng = np.random.default_rng(9)
    flat = np.unique(rng.integers(0, 40, 500) * 97
                     + rng.integers(0, 97, 500))
    rows, vals = flat // 97, flat % 97
    a = CSRLookup.from_pairs(rows, vals, 40)
    b = CSRLookup.from_sorted_pairs(rows, vals, 40)
    assert np.array_equal(a.indptr, b.indptr)
    assert np.array_equal(a.values, b.values)


def test_merge_pop_order_matches_full_sort():
    """_merge_pop_order ≡ host_topk_desc's full order: random updates
    with heavy ties, catalog growth, and superset changed sets."""
    from predictionio_tpu.models.common import host_topk_desc
    from predictionio_tpu.streaming.fold import _merge_pop_order

    rng = np.random.default_rng(3)
    pop = rng.choice(np.asarray([0, 1, 2, 5, 5, 9], np.float32), 300)
    order = host_topk_desc(pop, len(pop))[1]
    for step in range(8):
        grow = rng.integers(0, 12)
        new_pop = np.concatenate(
            [pop, rng.integers(0, 6, grow).astype(np.float32)])
        changed = np.unique(rng.integers(0, len(pop), 25)).astype(np.int64)
        new_pop[changed] += rng.integers(0, 3, len(changed))
        if step % 2:
            # superset: ids whose value did NOT move must still land
            # back at their exact slots
            changed = np.union1d(
                changed, np.unique(rng.integers(0, len(pop), 10)))
        changed = np.union1d(
            changed, np.arange(len(pop), len(new_pop), dtype=np.int64))
        merged = _merge_pop_order(order, new_pop, changed)
        want = host_topk_desc(new_pop, len(new_pop))[1]
        assert np.array_equal(merged, want), step
        pop, order = new_pop, merged


def test_incremental_emit_identity(fs_storage, host_serving):
    """The incremental emit's three carries are ARRAY-identical to the
    from-scratch rebuilds: (1) an N-bump fold (every LLR weight moves,
    no structure) regathers the host_inverted weights through the
    cached inversion permutation; (2) host_pop_order merges instead of
    re-sorting; (3) a props/user_seen-untouched fold carries the very
    same objects."""
    from test_streaming_follow import _follow_pair

    from predictionio_tpu.models.common import host_topk_desc

    app_id, engine, ap, ep = _ur_setup(fs_storage,
                                       event_names=("purchase",))
    fs_storage.l_events.insert_batch(
        [_buy(f"u{k % 40}", f"i{k}") for k in range(400)]
        + [_buy(f"u{u}", f"i{it}") for u in range(8) for it in range(6)
           if (u + it) % 3], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    m1 = follower._fold.model
    m1.host_inverted("purchase")
    m1.host_pop_order()
    # (1)+(2): brand-new user buying an existing item — N bump, same
    # catalog, popularity changes at exactly one id
    fs_storage.l_events.insert_batch([_buy("nb_user", "i5")], app_id)
    assert follower.tick() == "fold"
    m2 = follower._fold.model
    carried = m2.__dict__.get("_host_inv", {}).get("purchase")
    assert carried is not None, "inverted CSR was not carried/patched"
    fresh_model = follower._fold.model
    fresh_model.__dict__.pop("_host_inv")
    fresh = fresh_model.host_inverted("purchase")
    for a, b in zip(carried, fresh):
        assert np.array_equal(a, b)
    merged_order = m2.__dict__.get("_host_pop_order")
    assert merged_order is not None, "pop order was not merged"
    want_order = host_topk_desc(
        np.asarray(m2.popularity, np.float32), len(m2.item_dict))[1]
    assert np.array_equal(merged_order, want_order)
    # (3): duplicate-only fold — user_seen/props carry BY OBJECT
    fs_storage.l_events.insert_batch([_buy("u1", "i300")], app_id)
    assert follower.tick() == "fold"
    m3 = follower._fold.model
    assert m3.user_seen is not m2.user_seen  # (u1, i300) is a new pair
    fs_storage.l_events.insert_batch(
        [_buy("u1", "i300")], app_id)        # now a TRUE duplicate
    assert follower.tick() == "fold"
    m4 = follower._fold.model
    assert m4.user_seen is m3.user_seen
    assert m4.item_properties is m3.item_properties


def test_sparse_counts_unit():
    """_SparseCounts merge/gather/remap against a dense reference."""
    from predictionio_tpu.streaming.fold import _SparseCounts

    rng = np.random.default_rng(7)
    C = np.zeros((37, 23), np.int32)
    sc = _SparseCounts.empty()
    for _ in range(8):
        rows = rng.integers(0, 37, 50).astype(np.int64)
        cols = rng.integers(0, 23, 50).astype(np.int64)
        np.add.at(C, (rows, cols), 1)
        sc.add_pairs(rows, cols)
        assert np.array_equal(sc.to_dense(37, 23), C)
        assert np.all(np.diff(sc.keys) > 0)      # sorted, unique
    # row-subset gather
    rows = np.asarray(sorted(rng.choice(37, 9, replace=False)), np.int64)
    local, cols, counts = sc.row_cells(rows)
    got = np.zeros((9, 23), np.int32)
    got[local, cols] = counts
    assert np.array_equal(got, C[rows])
    # strictly-increasing col remap (23 → 30 cols, monotone injection)
    perm = np.sort(rng.choice(30, 23, replace=False)).astype(np.int64)
    sc.remap_cols(perm)
    C2 = np.zeros((37, 30), np.int32)
    C2[:, perm] = C
    assert np.array_equal(sc.to_dense(37, 30), C2)
    assert np.all(np.diff(sc.keys) > 0)
    # strictly-increasing row remap
    rperm = np.sort(rng.choice(45, 37, replace=False)).astype(np.int64)
    sc.remap_rows(rperm)
    C3 = np.zeros((45, 30), np.int32)
    C3[rperm, :] = C2
    assert np.array_equal(sc.to_dense(45, 30), C3)
    assert np.all(np.diff(sc.keys) > 0)
    # from_dense roundtrip
    assert np.array_equal(_SparseCounts.from_dense(C3).to_dense(45, 30), C3)


def test_budget_boundary_pins_demotion_threshold(fs_storage, host_serving,
                                                 monkeypatch):
    """The sparse state's demotion threshold is its O(nnz) footprint: a
    budget the DENSE state cannot fit (I²·4 alone exceeds it) holds the
    sparse state in fold mode, and a budget one byte under the sparse
    footprint demotes."""
    from predictionio_tpu.streaming.fold import (
        FoldUnsupported, URFoldState,
    )

    app_id, engine, ap, ep = _ur_setup(fs_storage,
                                       event_names=("purchase",))
    # ~600 distinct items: dense C = 600²·4 ≈ 1.44 MB; sparse nnz stays
    # tiny (each user owns a 6-item slice)
    fs_storage.l_events.insert_batch(
        [_buy(f"u{k % 100}", f"i{k}") for k in range(600)], app_id)
    tail = _tail(fs_storage, app_id, {}, None, None)

    monkeypatch.setenv("PIO_FOLLOW_STATE", "sparse")
    state = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    sparse_bytes = state.state_bytes()
    n_items = len(state.model.item_dict)
    dense_equiv = n_items * n_items * 4
    assert sparse_bytes < dense_equiv, (sparse_bytes, dense_equiv)

    # a budget between the two: sparse folds, dense demotes
    budget = max(sparse_bytes * 2, sparse_bytes + 4096)
    assert budget < dense_equiv
    monkeypatch.setenv("PIO_FOLLOW_STATE_BYTES", str(budget))
    fs_storage.l_events.insert_batch([_buy("u0", "i1")], app_id)
    tail2 = _tail(fs_storage, app_id, tail["watermark"], state.batch,
                  tail["heads"])
    state.fold(tail2["batch"])          # sparse: within budget

    monkeypatch.setenv("PIO_FOLLOW_STATE", "dense")
    with pytest.raises(FoldUnsupported):
        URFoldState.bootstrap(ap, ep.data_source_params,
                              _tail(fs_storage, app_id, {}, None,
                                    None)["batch"])

    # one byte under the sparse footprint demotes the sparse state too
    monkeypatch.setenv("PIO_FOLLOW_STATE", "sparse")
    monkeypatch.setenv("PIO_FOLLOW_STATE_BYTES",
                       str(state.state_bytes() - 1))
    fs_storage.l_events.insert_batch([_buy("u0", "i2")], app_id)
    tail3 = _tail(fs_storage, app_id, tail2["watermark"], state.batch,
                  tail2["heads"])
    with pytest.raises(FoldUnsupported):
        state.fold(tail3["batch"])


def test_checkpoint_roundtrip_bit_exact(fs_storage, host_serving):
    """checkpoint_arrays → restore_checkpoint reproduces the state: the
    restored model is bit-identical, and folding the same delta into
    the original and the restored state stays bit-identical."""
    from predictionio_tpu.streaming.fold import URFoldState

    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_seed_events(seed=41), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item("i1", {"category": "red"})], app_id)
    tail = _tail(fs_storage, app_id, {}, None, None)
    state = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    arrays, meta = state.checkpoint_arrays()
    restored = URFoldState.restore_checkpoint(
        ap, ep.data_source_params, state.batch, arrays, meta)
    _assert_models_equal(state.model, restored.model, "restore")
    # fold the same suffix into both
    fs_storage.l_events.insert_batch(
        [_buy("newguy", "i3"), _buy("u1", "i5")], app_id)
    t2 = _tail(fs_storage, app_id, tail["watermark"], state.batch,
               tail["heads"])
    m1 = state.fold(t2["batch"])
    m2 = restored.fold(t2["batch"])
    _assert_models_equal(m1, m2, "post-restore fold")


def test_checkpoint_fingerprint_rejects_corruption(fs_storage,
                                                   host_serving):
    """A flipped bit in the persisted pair set must fail the integrity
    fingerprint (ValueError → the follower restages)."""
    from predictionio_tpu.streaming.fold import URFoldState

    app_id, engine, ap, ep = _ur_setup(fs_storage,
                                       event_names=("purchase",))
    fs_storage.l_events.insert_batch(_seed_events(seed=43), app_id)
    tail = _tail(fs_storage, app_id, {}, None, None)
    state = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    arrays, meta = state.checkpoint_arrays()
    bad = dict(arrays)
    pairs = np.array(bad["t0_pairs"])
    pairs[0] ^= 1
    bad["t0_pairs"] = pairs
    with pytest.raises(ValueError, match="fingerprint"):
        URFoldState.restore_checkpoint(ap, ep.data_source_params,
                                       state.batch, bad, meta)
