"""E-commerce template tests: implicit-ALS recommendations, three predict
tiers, live seen/unavailable constraints, category/white/black-list rules."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.ecommerce import ECommerceEngine, ECommQuery
from predictionio_tpu.models.ecommerce.engine import (
    ECommAlgorithmParams,
    ECommDataSourceParams,
)
from predictionio_tpu.storage import App

APP = "ecommapp"


@pytest.fixture()
def ecomm_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, APP))
    rng = np.random.default_rng(11)
    events = []
    # two taste clusters: even users view/buy a-items, odd users z-items
    for u in range(40):
        items = [f"a{i}" for i in range(6)] if u % 2 == 0 else [f"z{i}" for i in range(6)]
        for it in items:
            if rng.random() < 0.8:
                events.append(Event(event="view", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
            if rng.random() < 0.3:
                events.append(Event(event="buy", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
    for i in range(6):
        events.append(Event(event="$set", entity_type="item", entity_id=f"a{i}",
                            properties=DataMap({"categories": ["alpha"]})))
        events.append(Event(event="$set", entity_type="item", entity_id=f"z{i}",
                            properties=DataMap({"categories": ["zeta"]})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage, app_id


def make_ep(**algo_overrides):
    params = dict(app_name=APP, rank=8, num_iterations=10, alpha=2.0, mesh_dp=1)
    params.update(algo_overrides)
    return EngineParams(
        data_source_params=ECommDataSourceParams(app_name=APP),
        algorithm_params_list=[("ecomm", ECommAlgorithmParams(**params))],
    )


def trained(ep):
    engine = ECommerceEngine.apply()
    models = engine.train(ep)
    return engine.predictor(ep, models)


def items_of(res):
    return [s.item for s in res.item_scores]


def test_known_user_stays_in_cluster(ecomm_app):
    predict = trained(make_ep())
    res = predict(ECommQuery(user="u0", num=4))
    assert res.item_scores
    assert all(i.startswith("a") for i in items_of(res)), items_of(res)
    res = predict(ECommQuery(user="u1", num=4))
    assert all(i.startswith("z") for i in items_of(res)), items_of(res)


def test_category_white_black_rules(ecomm_app):
    predict = trained(make_ep())
    res = predict(ECommQuery(user="u0", num=6, categories=["zeta"]))
    assert res.item_scores and all(i.startswith("z") for i in items_of(res))
    res = predict(ECommQuery(user="u0", num=6, white_list=["a1", "a2"]))
    assert set(items_of(res)) <= {"a1", "a2"}
    res = predict(ECommQuery(user="u0", num=6, black_list=["a0", "a1"]))
    assert not {"a0", "a1"} & set(items_of(res))
    # unknown category name: nothing qualifies
    res = predict(ECommQuery(user="u0", num=6, categories=["nope"]))
    assert res.item_scores == []


def test_unavailable_items_update_live(ecomm_app):
    storage, app_id = ecomm_app
    predict = trained(make_ep())
    base = items_of(predict(ECommQuery(user="u0", num=3)))
    assert base
    # mark the top item unavailable — takes effect with NO retrain
    storage.l_events.insert(
        Event(event="$set", entity_type="constraint",
              entity_id="unavailableItems",
              properties=DataMap({"items": [base[0]]})), app_id)
    after = items_of(predict(ECommQuery(user="u0", num=3)))
    assert base[0] not in after and after
    # a newer constraint replaces (not extends) the previous list
    storage.l_events.insert(
        Event(event="$set", entity_type="constraint",
              entity_id="unavailableItems",
              properties=DataMap({"items": []})), app_id)
    assert base[0] in items_of(predict(ECommQuery(user="u0", num=3)))


def test_unseen_only_excludes_live_seen(ecomm_app):
    storage, app_id = ecomm_app
    predict = trained(make_ep(unseen_only=True))
    res = items_of(predict(ECommQuery(user="u0", num=6)))
    seen = {e.target_entity_id for e in storage.l_events.find(
        app_id, entity_type="user", entity_id="u0")}
    assert res and not (set(res) & seen)
    # a view recorded AFTER training is excluded too (live read)
    if res:
        storage.l_events.insert(
            Event(event="view", entity_type="user", entity_id="u0",
                  target_entity_type="item", target_entity_id=res[0]), app_id)
        assert res[0] not in items_of(predict(ECommQuery(user="u0", num=6)))


def test_unknown_user_with_recent_views_gets_similar(ecomm_app):
    storage, app_id = ecomm_app
    predict = trained(make_ep())
    # brand-new user (absent from training) views two z-items post-train
    for it in ["z0", "z1"]:
        storage.l_events.insert(
            Event(event="view", entity_type="user", entity_id="unew",
                  target_entity_type="item", target_entity_id=it), app_id)
    res = items_of(predict(ECommQuery(user="unew", num=3)))
    assert res, "similar-items fallback should fire"
    assert all(i.startswith("z") for i in res), res
    assert not {"z0", "z1"} & set(res), "recently viewed items are excluded"


def test_cold_user_popular_fallback_respects_rules(ecomm_app):
    predict = trained(make_ep())
    res = items_of(predict(ECommQuery(user="nobody", num=4)))
    assert res, "popular fallback should return items"
    res = items_of(predict(ECommQuery(user="nobody", num=4, categories=["alpha"])))
    assert res and all(i.startswith("a") for i in res)


def test_model_roundtrip_serves_identically(ecomm_app):
    import pickle

    engine = ECommerceEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    restored = [pickle.loads(pickle.dumps(m)) for m in models]
    q = ECommQuery(user="u0", num=4)
    a = engine.predictor(ep, models)(q).to_json()
    b = engine.predictor(ep, restored)(q).to_json()
    assert a == b


def test_explicitly_empty_whitelist_returns_nothing(ecomm_app):
    predict = trained(make_ep())
    assert items_of(predict(ECommQuery(user="u0", num=4, white_list=[]))) == []
    # and via the wire format: present-but-empty != absent
    q = ECommQuery.from_json({"user": "u0", "num": 4, "whiteList": []})
    assert q.white_list == []
    assert ECommQuery.from_json({"user": "u0"}).white_list is None


def test_first_revision_pickle_format_migrates(ecomm_app):
    """Models persisted by the first ECommModel revision (dense cat_masks +
    cat-name dict in state) still load and serve identically."""
    import pickle

    engine = ECommerceEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    m = models[0]
    old_state = {
        "X": m.user_factors, "Y": m.item_factors,
        "users": m.user_dict.to_state(), "items": m.item_dict.to_state(),
        "cats": m.cat_dict.to_state(), "cat_masks": m.cat_masks,
        "popular": m.popular,
    }
    restored = type(m).__new__(type(m))
    restored.__setstate__(old_state)
    assert sorted(restored.item_categories) == sorted(m.item_categories)
    assert (restored.cat_masks == m.cat_masks).all()
    q = ECommQuery(user="u0", num=4, categories=["alpha"])
    a = engine.predictor(ep, models)(q).to_json()
    b = engine.predictor(ep, [restored])(q).to_json()
    assert a == b


def test_ecomm_serve_batch_matches_serial(ecomm_app):
    """serve_batch_predict ≡ predict across tier-1 (known user), tier-2
    (recent-similar), tier-3 (popularity), rules, and infeasible queries
    in one batch."""
    from predictionio_tpu.models.ecommerce import ECommerceEngine

    engine = ECommerceEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    model = models[0]
    name, params = ep.algorithm_params_list[0]
    algo = engine.algorithm_classes[name](params)
    queries = [
        ECommQuery(user="u0", num=4),
        ECommQuery(user="u1", num=4),
        ECommQuery(user="totally-new", num=4),           # popularity tier
        ECommQuery(user="u0", num=6, categories=["zeta"]),
        ECommQuery(user="u0", num=6, white_list=["a1", "a2"]),
        ECommQuery(user="u0", num=6, black_list=["a0", "a1"]),
        ECommQuery(user="u0", num=6, categories=["nope"]),  # infeasible
    ]
    serial = [algo.predict(model, q) for q in queries]
    batched = algo.serve_batch_predict(model, queries)
    for q, s, b in zip(queries, serial, batched):
        s_i = [(r.item, round(r.score, 4)) for r in s.item_scores]
        b_i = [(r.item, round(r.score, 4)) for r in b.item_scores]
        assert s_i == b_i, (q, s_i, b_i)
