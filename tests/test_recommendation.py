"""End-to-end recommendation template test: ingest → train → persist →
reload → predict (reference analogue: the integration harness's
Recommendation template loop — SURVEY.md §4)."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.recommendation import (
    ALSAlgorithm,
    RecommendationEngine,
    RecoQuery,
)
from predictionio_tpu.models.recommendation.engine import (
    ALSAlgorithmParams,
    DataSourceParams,
)
from predictionio_tpu.storage import App
from predictionio_tpu.workflow import core_workflow


@pytest.fixture()
def rating_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "recapp"))
    rng = np.random.default_rng(5)
    # two latent taste groups: users 0-9 like items 0-4, users 10-19 like 5-9
    events = []
    for u in range(20):
        group = 0 if u < 10 else 1
        for i in range(10):
            in_group = (i < 5) == (group == 0)
            r = 5.0 if in_group else 1.0
            if rng.random() < 0.8:
                events.append(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties=DataMap({"rating": r}))
                )
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_params(**algo_over):
    algo = dict(rank=6, num_iterations=8, lambda_=0.05, mesh_dp=1)
    algo.update(algo_over)
    return EngineParams(
        data_source_params=DataSourceParams(app_name="recapp"),
        algorithm_params_list=[("als", ALSAlgorithmParams(**algo))],
    )


def test_train_and_predict_groups(rating_app):
    engine = RecommendationEngine.apply()
    ep = make_params()
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(RecoQuery(user="u1", num=3))
    top = [s.item for s in res.item_scores]
    # group-0 user should be recommended group-0 items
    assert all(int(t[1:]) < 5 for t in top), top
    res2 = predict(RecoQuery(user="u15", num=3))
    assert all(int(t[1:]) >= 5 for t in res2.item_scores and [s.item for s in res2.item_scores] or ["i9"])


def test_unknown_user_returns_empty(rating_app):
    engine = RecommendationEngine.apply()
    ep = make_params()
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    assert predict(RecoQuery(user="ghost", num=3)).item_scores == []


def test_workflow_persist_and_reload(rating_app):
    engine = RecommendationEngine.apply()
    ep = make_params()
    instance = core_workflow.run_train(
        engine, ep, engine_id="reco-test", storage=rating_app
    )
    assert instance.status == "COMPLETED"
    inst2, models = core_workflow.load_latest_models("reco-test", storage=rating_app)
    assert inst2.id == instance.id
    predict = engine.predictor(ep, models)
    res = predict(RecoQuery(user="u1", num=2))
    assert len(res.item_scores) == 2
    assert res.item_scores[0].score >= res.item_scores[1].score


def test_workflow_failed_training_recorded(mem_storage):
    engine = RecommendationEngine.apply()
    ep = EngineParams(
        data_source_params=DataSourceParams(app_name="no-such-app"),
        algorithm_params_list=[("als", ALSAlgorithmParams())],
    )
    with pytest.raises(ValueError):
        core_workflow.run_train(engine, ep, engine_id="reco-fail", storage=mem_storage)
    instances = mem_storage.engine_instances.get_all()
    assert len(instances) == 1 and instances[0].status == "FAILED"


def test_batch_predict_matches_single(rating_app):
    engine = RecommendationEngine.apply()
    ep = make_params()
    models = engine.train(ep)
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=6, num_iterations=8, lambda_=0.05, mesh_dp=1))
    queries = [RecoQuery(user=f"u{u}", num=3) for u in (0, 5, 15)]
    batch = algo.batch_predict(models[0], queries)
    singles = [algo.predict(models[0], q) for q in queries]
    for b, s in zip(batch, singles):
        assert [x.item for x in b.item_scores] == [x.item for x in s.item_scores]


def test_eval_folds(rating_app):
    from predictionio_tpu.controller.evaluation import OptionAverageMetric, MetricEvaluator

    class PrecisionAtK(OptionAverageMetric):
        def score_one(self, q, p, a):
            actual_item, rating = a
            if rating < 4.0:
                return None
            items = [s.item for s in p.item_scores]
            return 1.0 if actual_item in items else 0.0

    engine = RecommendationEngine.apply()
    ep = EngineParams(
        data_source_params=DataSourceParams(app_name="recapp", eval_k=3),
        algorithm_params_list=[("als", ALSAlgorithmParams(rank=6, num_iterations=6, mesh_dp=1))],
    )
    result = MetricEvaluator(PrecisionAtK()).evaluate(engine, [ep])
    # liked items dominate each user's group; ALS should rank them in top-10
    assert result.best_score > 0.5


def test_unseen_only_excludes_rated_items(rating_app):
    """unseenOnly=true must exclude every item the user has rated
    (reference e-commerce template's unseenOnly), via the model's CSR
    seen lookup."""
    engine = RecommendationEngine.apply()
    ep = make_params()
    models = engine.train(ep)
    model = models[0]
    predict = engine.predictor(ep, models)
    uid = model.user_dict.id("u1")
    rated = {model.item_dict.str(int(j)) for j in model.seen.row(uid)}
    assert rated, "fixture gives u1 rated items"
    res = predict(RecoQuery(user="u1", num=10, unseen_only=True))
    recs = {s.item for s in res.item_scores}
    assert recs.isdisjoint(rated), f"rated items leaked: {recs & rated}"
    # without the flag, the top items ARE the user's high-rated ones
    res_all = predict(RecoQuery(user="u1", num=10))
    assert {s.item for s in res_all.item_scores} & rated


def test_blacklist_query_field(rating_app):
    engine = RecommendationEngine.apply()
    ep = make_params()
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    base = predict(RecoQuery(user="u1", num=3))
    banned = base.item_scores[0].item
    res = predict(RecoQuery.from_json(
        {"user": "u1", "num": 3, "blackList": [banned]}))
    assert banned not in [s.item for s in res.item_scores]


def test_batch_predict_respects_flags(rating_app):
    engine = RecommendationEngine.apply()
    ep = make_params()
    models = engine.train(ep)
    model = models[0]
    algo = ALSAlgorithm(ep.algorithm_params_list[0][1])
    uid = model.user_dict.id("u1")
    rated = {model.item_dict.str(int(j)) for j in model.seen.row(uid)}
    out = algo.batch_predict(model, [
        RecoQuery(user="u1", num=10, unseen_only=True),
        RecoQuery(user="u1", num=10),
        RecoQuery(user="nobody", num=3),
    ])
    assert {s.item for s in out[0].item_scores}.isdisjoint(rated)
    assert {s.item for s in out[1].item_scores} & rated
    assert out[2].item_scores == []


def test_seen_csr_is_flat_arrays(rating_app):
    """Model blob stores seen items as two flat arrays (CSR), not a python
    dict of per-user arrays — size must be O(nnz), not O(users) objects."""
    import pickle

    engine = RecommendationEngine.apply()
    models = engine.train(make_params())
    state = models[0].__getstate__()
    assert set(state["seen"]) == {"indptr", "values"}
    m2 = pickle.loads(pickle.dumps(models[0]))
    uid = m2.user_dict.id("u1")
    assert (m2.seen.row(uid) == models[0].seen.row(uid)).all()
