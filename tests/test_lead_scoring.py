"""Lead Scoring template tests: sessionization, conversion scoring,
fallback for unseen attribute combos."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.lead_scoring import LeadScoringEngine, LSQuery
from predictionio_tpu.models.lead_scoring.engine import (
    LSAlgorithmParams,
    LSDataSourceParams,
)
from predictionio_tpu.storage import App

APP = "lsapp"


@pytest.fixture()
def ls_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, APP))
    rng = np.random.default_rng(9)
    events = []
    sid = 0
    # /sale + google sessions convert 90%; /home + direct convert 10%
    for k in range(300):
        sid += 1
        hot = k % 2 == 0
        attrs = ({"sessionId": f"s{sid}", "landingPageId": "/sale",
                  "referrerId": "google", "browser": "Chrome"} if hot else
                 {"sessionId": f"s{sid}", "landingPageId": "/home",
                  "referrerId": "direct", "browser": "Firefox"})
        events.append(Event(event="view", entity_type="user",
                            entity_id=f"u{k}", properties=DataMap(attrs)))
        if rng.random() < (0.9 if hot else 0.1):
            events.append(Event(event="buy", entity_type="user",
                                entity_id=f"u{k}", target_entity_type="item",
                                target_entity_id="i1",
                                properties=DataMap({"sessionId": f"s{sid}"})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_ep():
    return EngineParams(
        data_source_params=LSDataSourceParams(app_name=APP),
        algorithm_params_list=[("logreg", LSAlgorithmParams(
            iterations=150))],
    )


def trained():
    engine = LeadScoringEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    return engine, ep, models, engine.predictor(ep, models)


def test_hot_sessions_score_higher(ls_app):
    _, _, _, predict = trained()
    hot = predict(LSQuery.from_json({
        "landingPageId": "/sale", "referrerId": "google",
        "browser": "Chrome"})).score
    cold = predict(LSQuery.from_json({
        "landingPageId": "/home", "referrerId": "direct",
        "browser": "Firefox"})).score
    assert 0.0 < cold < 0.35 < 0.65 < hot < 1.0, (hot, cold)


def test_unseen_combo_falls_back_to_base_rate(ls_app):
    engine, ep, models, predict = trained()
    res = predict(LSQuery(landing_page_id="/unknown", referrer_id="nobody",
                          browser="Lynx"))
    assert abs(res.score - models[0].base_rate) < 1e-9
    assert 0.2 < res.score < 0.8  # overall ~50% conversion in fixture


def test_sessionization_first_view_wins(ls_app):
    import datetime as dt

    from predictionio_tpu.events.event import DataMap, Event

    storage = ls_app
    app_id = storage.apps.get_by_name(APP).id
    # a LATER second view of session s1 with different attributes must NOT
    # replace the first view's attributes
    storage.l_events.insert(Event(
        event="view", entity_type="user", entity_id="u-late",
        event_time=dt.datetime(2030, 1, 1, tzinfo=dt.timezone.utc),
        properties=DataMap({"sessionId": "s1", "landingPageId": "/changed",
                            "referrerId": "elsewhere", "browser": "Edge"})), app_id)
    engine, ep, models, _ = trained()
    ds = engine.make_components(ep)[0]
    td = ds.read_training()
    assert td.attr_idx.shape[1] == 300
    # the late duplicate's values never enroll, in ANY attribute dict
    assert all(len(d) == 2 for d in td.attr_dicts)
    for d, late_value in zip(td.attr_dicts, ("/changed", "elsewhere", "Edge")):
        assert late_value not in list(d.strings())


def test_wire_format_and_roundtrip(ls_app):
    import pickle

    engine, ep, models, predict = trained()
    out = predict(LSQuery.from_json({"landingPageId": "/sale",
                                     "referrerId": "google",
                                     "browser": "Chrome"})).to_json()
    assert set(out) == {"score"}
    restored = [pickle.loads(pickle.dumps(m)) for m in models]
    q = LSQuery(landing_page_id="/sale", referrer_id="google", browser="Chrome")
    assert (engine.predictor(ep, models)(q).to_json()
            == engine.predictor(ep, restored)(q).to_json())
