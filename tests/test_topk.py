"""Bitonic tournament top-k (ops/topk.py) and its Pallas tile kernel
(pallas_kernels.tile_topk_desc) vs lax.top_k, plus the tiled-CCO merge
parity under PIO_CCO_TOPK=pallas."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _check_topk(x, s, i, k):
    """Values must match lax.top_k exactly; indices must be a valid
    (possibly tie-reordered) selection."""
    ref_s, _ = jax.lax.top_k(jnp.asarray(x), k)
    sv, iv = np.asarray(s), np.asarray(i)
    np.testing.assert_allclose(sv[:, :k], np.asarray(ref_s))
    for r in range(x.shape[0]):
        fin = np.isfinite(sv[r, :k])
        assert (x[r][iv[r, :k][fin]] == sv[r, :k][fin]).all()
        assert len(set(iv[r, :k][fin].tolist())) == fin.sum()


def test_bitonic_topk_matches_lax():
    from predictionio_tpu.ops.topk import bitonic_topk

    rng = np.random.default_rng(0)
    for (r, w, k) in [(7, 100, 10), (9, 161, 20), (5, 8, 3), (4, 64, 64),
                      (3, 5, 9), (2, 1, 1)]:
        x = rng.standard_normal((r, w)).astype(np.float32)
        x[x < -1.0] = -np.inf           # padding-like rows
        x[0, : min(w, 5)] = 1.5         # ties
        k_eff = min(k, w)
        s, i = bitonic_topk(jnp.asarray(x), k_eff)
        _check_topk(x, s, i, k_eff)


def test_running_merge_across_tiles_matches_global_topk():
    from predictionio_tpu.ops.topk import block_width, merge_desc, sort_topb_desc

    rng = np.random.default_rng(1)
    r, t, n_tiles, k = 9, 64, 4, 12
    b = block_width(k)
    x = rng.standard_normal((r, t * n_tiles)).astype(np.float32)
    x[x < 0.5] = -np.inf
    bs = jnp.full((r, b), -np.inf)
    bi = jnp.zeros((r, b), jnp.int32)
    for tt in range(n_tiles):
        tile = jnp.asarray(x[:, tt * t:(tt + 1) * t])
        idx = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None, :] + tt * t, tile.shape)
        ts, ti = sort_topb_desc(tile, idx, b)
        bs, bi = merge_desc(bs, bi, ts, ti)
    _check_topk(x, bs, bi, b)


def test_pallas_tile_topk_desc_matches_lax():
    from predictionio_tpu.ops.pallas_kernels import tile_topk_desc

    rng = np.random.default_rng(2)
    for (r, w, b) in [(9, 300, 64), (3, 64, 128), (5, 520, 16)]:
        x = rng.standard_normal((r, w)).astype(np.float32)
        x[x < 0] = -np.inf
        x[0, : min(5, w)] = 2.0
        s, i = tile_topk_desc(jnp.asarray(x), b, block_r=8)
        _check_topk(x, s, i, min(b, w))


@pytest.mark.parametrize("strategy", ["resident", "chunked", "dense"])
def test_cco_topk_pallas_matches_lax(monkeypatch, strategy):
    """dense ≡ tiled parity contract extended to the merge impl: the CCO
    indicator tables are identical under PIO_CCO_TOPK=lax and =pallas on
    every device strategy (the kernel runs in interpret mode on CPU)."""
    from predictionio_tpu.ops import cco as cco_ops

    rng = np.random.default_rng(3)
    n_users, n_ip, n_it = 80, 30, 47
    pu = rng.integers(0, n_users, 500)
    pi = rng.integers(0, n_ip, 500)
    ou = rng.integers(0, n_users, 900)
    oi = rng.integers(0, n_it, 900)

    if strategy == "dense":
        monkeypatch.setenv("PIO_CCO_DENSE", "1")
    else:
        monkeypatch.setenv("PIO_CCO_DENSE", "0")
        if strategy == "chunked":
            monkeypatch.setattr(cco_ops, "_TILED_P_BYTES", 0)

    def run():
        return cco_ops.cco_indicators_coo(
            pu, pi, ou, oi, n_users, n_ip, n_it,
            top_k=7, llr_threshold=0.5, user_block=32, item_tile=16)

    monkeypatch.setenv("PIO_CCO_TOPK", "lax")
    s1, i1 = run()
    monkeypatch.setenv("PIO_CCO_TOPK", "pallas")
    s2, i2 = run()

    finite = np.isfinite(s1)
    assert (np.isfinite(s2) == finite).all()
    np.testing.assert_allclose(s1[finite], s2[finite], rtol=1e-5, atol=1e-5)
    # ids equal wherever scores have no exact ties at the cut
    np.testing.assert_allclose(
        np.sort(s1, axis=1), np.sort(s2, axis=1), rtol=1e-5, atol=1e-5)


def test_topk_impl_env(monkeypatch):
    from predictionio_tpu.ops.cco import _carry_width, topk_impl

    monkeypatch.setenv("PIO_CCO_TOPK", "pallas")
    assert topk_impl() == "pallas"
    monkeypatch.setenv("PIO_CCO_TOPK", "lax")
    assert topk_impl() == "lax"
    monkeypatch.delenv("PIO_CCO_TOPK", raising=False)
    assert topk_impl() == "lax"    # auto stays lax until hardware-verified
    assert _carry_width(50, "pallas") == 64
    assert _carry_width(50, "lax") == 50
    assert _carry_width(3, "pallas") == 8
