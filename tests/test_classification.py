"""Classification template tests: logreg + naive bayes over $set-aggregated
entity properties, eval folds, and the dp-sharded logreg path."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.controller.evaluation import AverageMetric, MetricEvaluator
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.classification import (
    ClassificationEngine,
    ClassificationQuery,
)
from predictionio_tpu.models.classification.engine import (
    ClassificationDSParams,
    LogRegParams,
    NaiveBayesParams,
)
from predictionio_tpu.storage import App


def seed_labeled_app(storage, n=120, seed=0):
    """Two gaussian blobs in 3-D => linearly separable labels."""
    app_id = storage.apps.insert(App(0, "clfapp"))
    rng = np.random.default_rng(seed)
    events = []
    for j in range(n):
        label = j % 2
        center = np.array([2.0, 2.0, 2.0]) if label else np.array([-2.0, -2.0, -2.0])
        v = center + rng.normal(size=3)
        events.append(Event(
            event="$set", entity_type="user", entity_id=f"u{j}",
            properties=DataMap({
                "attr0": float(v[0]), "attr1": float(v[1]), "attr2": float(v[2]),
                "label": "pos" if label else "neg",
            })))
    storage.l_events.insert_batch(events, app_id)
    return storage


@pytest.fixture()
def clf_app(mem_storage):
    return seed_labeled_app(mem_storage)


@pytest.mark.parametrize("algo,params", [
    ("logreg", LogRegParams(iterations=50, mesh_dp=1)),
    ("logreg", LogRegParams(iterations=60, optimizer="adam", learning_rate=0.3, mesh_dp=1)),
    ("naivebayes", NaiveBayesParams(model_type="gaussian")),
])
def test_classification_train_predict(clf_app, algo, params):
    engine = ClassificationEngine.apply()
    ep = EngineParams(
        data_source_params=ClassificationDSParams(app_name="clfapp"),
        algorithm_params_list=[(algo, params)],
    )
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    assert predict(ClassificationQuery({"attr0": 3, "attr1": 2, "attr2": 2})).label == "pos"
    assert predict(ClassificationQuery({"attr0": -3, "attr1": -2, "attr2": -2})).label == "neg"


def test_logreg_mesh_sharded(clf_app):
    engine = ClassificationEngine.apply()
    ep = EngineParams(
        data_source_params=ClassificationDSParams(app_name="clfapp"),
        algorithm_params_list=[("logreg", LogRegParams(iterations=40, mesh_dp=8))],
    )
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    assert predict(ClassificationQuery({"attr0": 3, "attr1": 3, "attr2": 3})).label == "pos"


def test_multinomial_nb():
    from predictionio_tpu.ops.naive_bayes import multinomial_nb_predict, multinomial_nb_train

    x = np.array([[5, 0, 1], [4, 1, 0], [0, 5, 2], [1, 4, 3]], np.float32)
    y = np.array([0, 0, 1, 1], np.int32)
    model = multinomial_nb_train(x, y, 2)
    assert multinomial_nb_predict(model, np.array([[6, 0, 1]], np.float32))[0] == 0
    assert multinomial_nb_predict(model, np.array([[0, 6, 2]], np.float32))[0] == 1


class Accuracy(AverageMetric):
    def score_one(self, q, p, a):
        return 1.0 if p.label == a else 0.0


def test_eval_picks_better_hyperparams(clf_app):
    engine = ClassificationEngine.apply()
    candidates = [
        EngineParams(
            data_source_params=ClassificationDSParams(app_name="clfapp", eval_k=3),
            algorithm_params_list=[("logreg", LogRegParams(iterations=it, mesh_dp=1))],
        )
        for it in (1, 50)
    ]
    result = MetricEvaluator(Accuracy()).evaluate(engine, candidates)
    assert result.best_score > 0.9


def test_missing_label_raises(mem_storage):
    mem_storage.apps.insert(App(0, "emptyclf"))
    engine = ClassificationEngine.apply()
    ep = EngineParams(
        data_source_params=ClassificationDSParams(app_name="emptyclf"),
        algorithm_params_list=[("logreg", LogRegParams(mesh_dp=1))],
    )
    with pytest.raises(ValueError, match="no labeled"):
        engine.train(ep)
