"""Native GIL-releasing data-plane cores (PR 18 tentpole).

Contracts under test, all against the ``PIO_NATIVE=off`` Python oracle:

- **scan core**: columnar ``read_batch`` (header parse, dict decode,
  props, meta, ids) and ``BatchMerger`` k-way merges are bit-exact on
  randomized corpora with disagreeing per-part dictionaries and unicode
  torture strings; the sharded live fan-out (multi-shard, tombstones)
  produces identical rows/codes/ids/watermarks native vs oracle.
- **serve core**: ``gather_csr_rows`` / ``host_topk_desc`` native
  dispatch is bit-exact (element order, dtypes, -0.0 and boundary-tie
  total order), the full host scorer (unique + weighted compacted
  bincount + f32 weight multiply) matches the numpy oracle to the bit,
  and the engine-level predict path answers identically on vs off.
- **http core**: ``parse_request_head`` refusal ORDER and parsed
  results match the Python walk over a randomized head corpus;
  ``assemble_response`` is value-equal.
- **degradation**: with the build simulated away, ``PIO_NATIVE=on``
  answers every call from the oracle with zero behavior change and
  bumps ``pio_native_fallback_total{reason="no_build"}``.
- **history cache** (satellite): ``PIO_HISTORY_CACHE=off`` is the
  always-fresh staleness oracle; the cache matches it across appends,
  per-entity invalidation, deletes, and storage swaps.
- **build keying** (satellite): artifacts are keyed on source CONTENT —
  an edited source can never serve a stale ``.so``.
"""

import datetime as dt
import itertools
import random
import string

import numpy as np
import pytest

from predictionio_tpu.native import build as native_build
from predictionio_tpu.native import core as ncore
from predictionio_tpu.store import columnar as col

_HAVE_NATIVE = ncore.lib() is not None

needs_native = pytest.mark.skipif(
    not _HAVE_NATIVE, reason="no C++ toolchain; native cores not built")


@pytest.fixture()
def native_on(monkeypatch):
    monkeypatch.setenv("PIO_NATIVE", "on")


def _rand_str(rng):
    if rng.random() < 0.2:
        return "".join(rng.choice("héllo😀日本 ñ" + string.ascii_letters)
                       for _ in range(rng.randint(1, 8)))
    return "".join(rng.choice(string.ascii_lowercase)
                   for _ in range(rng.randint(1, 10)))


def _make_batch(n, seed):
    from predictionio_tpu.events.event import Event

    rng = random.Random(seed)
    evs = []
    for _ in range(n):
        name = rng.choice(["buy", "view", "$set"])
        tgt = (None if name == "$set" or rng.random() < 0.3
               else f"i{rng.randint(0, 50)}")
        props = {}
        if rng.random() < 0.5:
            props = {"rating": rng.random() * 5, "tag": _rand_str(rng)}
        evs.append(Event(
            event=name, entity_type=rng.choice(["user", "item"]),
            entity_id=f"u{rng.randint(0, max(n // 2, 1))}",
            target_entity_id=tgt,
            event_time=dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc),
            properties=props))
    return col.EventBatch.from_events(evs)


def _assert_batches_equal(x, y):
    for f in ("event_codes", "entity_type_codes", "entity_ids",
              "target_ids", "times_us"):
        assert np.array_equal(getattr(x, f), getattr(y, f)), f
    assert np.array_equal(np.isnan(x.ratings), np.isnan(y.ratings))
    assert np.array_equal(x.ratings[~np.isnan(x.ratings)],
                          y.ratings[~np.isnan(y.ratings)])
    for d in ("event_dict", "entity_type_dict", "entity_dict",
              "target_dict"):
        assert getattr(x, d).strings() == getattr(y, d).strings(), d
    px, py = x.prop_columns or {}, y.prop_columns or {}
    assert set(px) == set(py)
    for k in px:
        for f in ("rows", "kind", "num", "str_offs", "codes"):
            assert np.array_equal(getattr(px[k], f), getattr(py[k], f)), (k, f)
        assert px[k].dict.strings() == py[k].dict.strings(), k


# -- scan core ---------------------------------------------------------------


@needs_native
def test_read_batch_parity(tmp_path, monkeypatch):
    rng = random.Random(5)
    b = _make_batch(400, 1)
    ids = col.EventIdColumn.from_ids(
        [f"ev-{i}-{_rand_str(rng)}" for i in range(len(b))])
    p = tmp_path / "batch.col"
    col.write_batch(p, b, event_ids=ids, meta={"watermark": {"s": 12}})
    monkeypatch.setenv("PIO_NATIVE", "off")
    b0, i0, m0 = col.read_batch(p)
    monkeypatch.setenv("PIO_NATIVE", "on")
    before = ncore._M_CALLS.value(core="scan")
    b1, i1, m1 = col.read_batch(p)
    assert ncore._M_CALLS.value(core="scan") == before + 1
    _assert_batches_equal(b0, b1)
    assert i0.tolist() == i1.tolist()
    assert m0 == m1 == {"watermark": {"s": 12}}


@needs_native
def test_read_batch_lone_surrogate_strings(tmp_path, monkeypatch):
    """JSON legally carries lone surrogates (Python's own json emits
    them); the native header parser must decode them identically."""
    d = col.IdDict(["ok", "bad\ud800end", "café"])
    b = _make_batch(8, 2)
    b = col.EventBatch(
        event_codes=b.event_codes, entity_type_codes=b.entity_type_codes,
        entity_ids=b.entity_ids, target_ids=b.target_ids,
        times_us=b.times_us, ratings=b.ratings,
        event_dict=b.event_dict, entity_type_dict=b.entity_type_dict,
        entity_dict=d, target_dict=b.target_dict,
        prop_columns=b.prop_columns)
    p = tmp_path / "surr.col"
    col.write_batch(p, b)
    monkeypatch.setenv("PIO_NATIVE", "off")
    b0, _, _ = col.read_batch(p)
    monkeypatch.setenv("PIO_NATIVE", "on")
    b1, _, _ = col.read_batch(p)
    assert (b0.entity_dict.strings() == b1.entity_dict.strings()
            == ["ok", "bad\ud800end", "café"])


@needs_native
@pytest.mark.parametrize("seed", [3, 4])
def test_batch_merger_parity(monkeypatch, seed):
    """K-way merges of parts with DISAGREEING dictionaries re-code
    identically under the native bulk-union."""
    parts = [_make_batch(120, seed * 10 + i) for i in range(4)]
    ids = [col.EventIdColumn.from_ids([f"p{i}e{j}" for j in range(len(p))])
           for i, p in enumerate(parts)]

    def merge():
        m = col.BatchMerger()
        for p, i in zip(parts, ids):
            m.add(p, i)
        return m.finish()

    monkeypatch.setenv("PIO_NATIVE", "off")
    b0, i0 = merge()
    monkeypatch.setenv("PIO_NATIVE", "on")
    b1, i1 = merge()
    _assert_batches_equal(b0, b1)
    assert i0.tolist() == i1.tolist()


@needs_native
def test_sharded_fanout_parity(tmp_path, monkeypatch):
    """The live multi-shard fan-out (tombstones, disagreeing per-shard
    dicts) is bit-exact native vs oracle, snapshot crutch hidden."""
    import shutil

    from predictionio_tpu.storage.sharded import ShardedEvents

    monkeypatch.setenv("PIO_FSYNC", "rotate")
    rng = np.random.default_rng(12)
    ev = ShardedEvents(tmp_path / "s", shards=3, replicas=1)
    try:
        items = []
        for k in range(240):
            d = {"event": ("buy", "view", "$set")[k % 3],
                 "entityType": "user" if k % 3 != 2 else "item",
                 "entityId": f"u{k % 13}" if k % 3 != 2 else f"i{k % 7}",
                 "eventId": f"e{k}",
                 "eventTime": (dt.datetime(2026, 1, 1,
                                           tzinfo=dt.timezone.utc)
                               + dt.timedelta(seconds=k)).isoformat()}
            if k % 3 != 2:
                d["targetEntityType"] = "item"
                d["targetEntityId"] = f"i{k % 29}"
            if k % 4:
                d["properties"] = {"rating": int(rng.integers(0, 6)),
                                   "color": f"c{rng.integers(0, 9)}"}
            items.append(d)
        assert all(r["status"] == 201
                   for r in ev.insert_json_batch(items, 1))
        for k in (3, 17, 101, 200):
            assert ev.delete(f"e{k}", 1)
        ev.build_snapshot(1)
        shutil.rmtree(ev._chan_dir(1, None), ignore_errors=True)

        monkeypatch.setenv("PIO_SCAN_WORKERS", "3")
        monkeypatch.setenv("PIO_NATIVE", "on")
        nat = ev._fanout_snapshot_scan(1)
        monkeypatch.setenv("PIO_NATIVE", "off")
        ora = ev._fanout_snapshot_scan(1)
        assert nat["events"] == ora["events"] == 236
        assert nat["watermark"] == ora["watermark"]
        _assert_batches_equal(nat["batch"], ora["batch"])
        assert np.array_equal(nat["ids"].blob, ora["ids"].blob)
        assert np.array_equal(nat["ids"].offs, ora["ids"].offs)
    finally:
        ev.close()


# -- serve core --------------------------------------------------------------


@needs_native
def test_gather_csr_rows_parity(monkeypatch):
    from predictionio_tpu.models import common as mc

    rng = np.random.default_rng(0)
    for _ in range(60):
        n = int(rng.integers(1, 40))
        lens = rng.integers(0, 6, n)
        indptr = np.concatenate(([0], np.cumsum(lens))).astype(np.int64)
        rows = rng.integers(0, 1000, int(indptr[-1])).astype(np.int32)
        w = rng.random(int(indptr[-1])).astype(np.float32)
        ids = rng.integers(-3, n + 3, int(rng.integers(0, 20)))
        monkeypatch.setenv("PIO_NATIVE", "off")
        a2 = mc.gather_csr_rows(indptr, ids, rows, w)
        a1 = mc.gather_csr_rows(indptr, ids, rows)
        monkeypatch.setenv("PIO_NATIVE", "on")
        b2 = mc.gather_csr_rows(indptr, ids, rows, w)
        b1 = mc.gather_csr_rows(indptr, ids, rows)
        assert all(np.array_equal(x, y) and x.dtype == y.dtype
                   for x, y in zip(a2, b2))
        assert np.array_equal(a1[0], b1[0]) and len(b1) == 1


@needs_native
def test_host_topk_parity_total_order(monkeypatch):
    """Native top-k reproduces the composite-key total order exactly:
    -0.0 < +0.0, boundary ties broken lower-index-first."""
    from predictionio_tpu.models import common as mc

    rng = np.random.default_rng(1)
    for trial in range(80):
        n = int(rng.integers(1, 200))
        if trial % 3:
            s = rng.choice(np.asarray(
                [0.0, -0.0, 1.5, -2.25, np.inf, -np.inf], np.float32), n)
        else:
            s = rng.standard_normal(n).astype(np.float32)
        k = int(rng.integers(0, n + 5))
        monkeypatch.setenv("PIO_NATIVE", "off")
        v0, i0 = mc.host_topk_desc(s, k)
        monkeypatch.setenv("PIO_NATIVE", "on")
        v1, i1 = mc.host_topk_desc(s, k)
        # bit-compare (view) so -0.0 vs +0.0 can't silently pass
        assert np.array_equal(v0.view(np.int32), v1.view(np.int32))
        assert np.array_equal(i0, i1)


@needs_native
def test_score_accum_parity_weight_semantics():
    """unique + compacted weighted bincount + f32 cast + f32 weight
    multiply + f32 type-order adds — bit-exact vs the numpy oracle,
    including weight != 1.0 (f32 multiply, not f64)."""
    rng = np.random.default_rng(2)
    for _ in range(40):
        types = []
        for _t in range(int(rng.integers(1, 4))):
            m = int(rng.integers(0, 300))
            rows = rng.integers(0, 500, m).astype(np.int32)
            w = (rng.random(m).astype(np.float32)
                 if rng.random() < 0.5 else None)
            weight = float(rng.choice([1.0, 2.0, 0.25, 3.7]))
            types.append((rows, w, weight))
        allr = np.concatenate([r for r, _, _ in types]) if types else \
            np.zeros(0, np.int32)
        cand_o = np.unique(allr).astype(np.int32)
        total_o = None
        for rows, w, weight in types:
            rel = np.searchsorted(cand_o, rows)
            if w is not None:
                sc = np.bincount(rel, weights=w,
                                 minlength=len(cand_o)).astype(np.float32)
            else:
                sc = np.bincount(rel, minlength=len(cand_o)).astype(
                    np.float32)
            if weight != 1.0:
                sc *= np.float32(weight)
            total_o = sc if total_o is None else total_o + sc
        cand_n = ncore.unique_i32(allr)
        assert np.array_equal(cand_o, cand_n)
        scratch = np.empty(len(cand_n), np.float64)
        total_n = np.empty(len(cand_n), np.float32)
        first = True
        for rows, w, weight in types:
            ncore.score_accum(cand_n, rows, w, weight, scratch, total_n,
                              first)
            first = False
        assert np.array_equal(total_o.view(np.int32),
                              total_n.view(np.int32))


# -- http core ---------------------------------------------------------------


def _head_corpus():
    rng = random.Random(42)
    names = [b"Content-Length", b"content-length", b"CONTENT-length",
             b"Host", b"X-Foo", b"Transfer-Encoding", b"Connection",
             b"Expect", b"", b"  weird  ", b"a:b"]
    vals = [b"7", b"07", b"7 ", b" 7", b"\xbc\xbd", b"abc", b"1_0", b"",
            b"close", b"keep-alive", b"100-continue", b"chunked",
            b"\x85x", b"\xa0 9", b"9\xa0", b"12\x1c", b"10", b"007"]
    lines0 = [b"GET /q HTTP/1.1", b"POST /e?k=1 HTTP/1.0", b"GET /",
              b"G E T /x HTTP/1.1", b"GET  /x HTTP/1.1", b"",
              b"GET /x HTTP/1.1 extra", b"\xff\xfe /p HTTP/1.1"]
    heads = []
    for _ in range(1500):
        parts = [rng.choice(lines0)]
        for _h in range(rng.randint(0, 6)):
            style = rng.random()
            if style < 0.1:
                parts.append(rng.choice([b" folded", b"\tfold", b"  "]))
            elif style < 0.2:
                parts.append(rng.choice([b"noColonHere", b":", b"::",
                                         b"a:"]))
            else:
                parts.append(rng.choice(names) + b":" + rng.choice(vals))
        heads.append(b"\r\n".join(parts))
    heads.append(b"GET /x HTTP/1.1" + b"\r\nH: 1" * 101)   # count cap
    heads.append(b"GET /x HTTP/1.1" + b"\r\nH: 1" * 100)   # at the cap
    return heads


@needs_native
def test_http_parse_head_parity(monkeypatch):
    """Refusal order and parsed results are identical native vs oracle
    over a randomized head corpus.  The one permitted divergence: a
    Content-Length beyond ~1e18 saturates natively — both sides still
    refuse 413 at any real max_body."""
    from predictionio_tpu.api import http_util as hu

    for head in _head_corpus():
        ora = hu._py_parse_request_head(head)
        monkeypatch.setenv("PIO_NATIVE", "on")
        nat = hu.parse_request_head(head)
        monkeypatch.setenv("PIO_NATIVE", "off")
        off = hu.parse_request_head(head)
        assert off == ora       # off-mode IS the oracle
        if nat != ora:
            assert (nat[0] == ora[0] == "ok" and nat[:5] == ora[:5]
                    and min(nat[5], ora[5]) > (1 << 56))


@needs_native
def test_http_assemble_parity(monkeypatch):
    from predictionio_tpu.api import http_util as hu

    # bodies under _NATIVE_ASSEMBLE_MIN take the join path even with
    # PIO_NATIVE=on (the ctypes marshalling costs more than the join at
    # those sizes); the oversized body forces the native branch so its
    # parity is actually exercised, and the gated sizes prove the gate
    # itself is response-invisible
    big = b"z" * (hu._NATIVE_ASSEMBLE_MIN + 17)
    for status, body, rid, close in itertools.product(
            (200, 400, 503), (b"", b'{"x":1}', b"z" * 5000, big),
            ("", "req-123"), (False, True)):
        monkeypatch.setenv("PIO_NATIVE", "off")
        ora = hu.assemble_response(status, body, rid=rid, close=close)
        monkeypatch.setenv("PIO_NATIVE", "on")
        nat = hu.assemble_response(status, body, rid=rid, close=close)
        assert bytes(nat) == ora


# -- engine-level serve parity ----------------------------------------------


@needs_native
def test_predict_parity_native_vs_oracle(mem_storage, monkeypatch):
    """End-to-end predict answers are identical on vs off — rules,
    blacklist, cold user — through the full native serve lane."""
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery)
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm, URAlgorithmParams, URDataSourceParams)
    from predictionio_tpu.storage import App

    app_id = mem_storage.apps.insert(App(0, "natserve"))
    rng = np.random.default_rng(7)
    events = []
    for u in range(20):
        for it in range(8):
            if rng.random() < 0.6:
                events.append(Event(
                    event="purchase", entity_type="user",
                    entity_id=f"u{u}", target_entity_type="item",
                    target_entity_id=f"i{it}"))
            if rng.random() < 0.8:
                events.append(Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{it}"))
    for it in range(8):
        events.append(Event(
            event="$set", entity_type="item", entity_id=f"i{it}",
            properties=DataMap(
                {"category": "odd" if it % 2 else "even"})))
    mem_storage.l_events.insert_batch(events, app_id)

    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="natserve", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="natserve", mesh_dp=1,
            max_correlators_per_item=8, min_llr=0.0))])
    engine = UniversalRecommenderEngine.apply()
    models = engine.train(ep)
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_SERVE_CACHE", "off")
    queries = [
        URQuery.from_json({"user": "u2", "num": 6}),
        URQuery.from_json({"user": "stranger", "num": 5}),
        URQuery.from_json({"user": "u3", "num": 6,
                           "fields": [{"name": "category",
                                       "values": ["odd"], "bias": -1}]}),
        URQuery.from_json({"user": "u4", "num": 6,
                           "blacklistItems": ["i0", "i3"]}),
        URQuery.from_json({"user": "u5", "num": 8,
                           "fields": [{"name": "category",
                                       "values": ["even"],
                                       "bias": 2.5}]}),
    ]

    def canon(r):
        return [(s.item, float(s.score)) for s in r.item_scores]

    monkeypatch.setenv("PIO_NATIVE", "off")
    off = [canon(algo.predict(model, q)) for q in queries]
    monkeypatch.setenv("PIO_NATIVE", "on")
    on = [canon(algo.predict(model, q)) for q in queries]
    assert any(off), "fixture produced only empty results"
    assert off == on


# -- graceful degradation ----------------------------------------------------


def test_no_toolchain_simulation(monkeypatch, tmp_path):
    """With the build gone, PIO_NATIVE=on answers everything from the
    oracle — zero behavior change — and counts the denial once per core
    as fallback_total{reason="no_build"}."""
    from predictionio_tpu.api import http_util as hu
    from predictionio_tpu.models import common as mc

    b = _make_batch(60, 9)
    p = tmp_path / "x.col"
    col.write_batch(p, b, meta={"m": 1})
    monkeypatch.setenv("PIO_NATIVE", "off")
    b0, _, m0 = col.read_batch(p)
    g0 = mc.gather_csr_rows(
        np.array([0, 2, 5], np.int64), [0, 1],
        np.arange(5, dtype=np.int32))
    h0 = hu.parse_request_head(b"GET /x HTTP/1.1\r\nContent-Length: 3")

    monkeypatch.setattr(native_build, "load", lambda *a, **k: None)
    ncore.reset_for_tests()
    try:
        monkeypatch.setenv("PIO_NATIVE", "on")
        before = ncore._M_FALLBACK.value(reason="no_build")
        b1, _, m1 = col.read_batch(p)
        g1 = mc.gather_csr_rows(
            np.array([0, 2, 5], np.int64), [0, 1],
            np.arange(5, dtype=np.int32))
        h1 = hu.parse_request_head(
            b"GET /x HTTP/1.1\r\nContent-Length: 3")
        _assert_batches_equal(b0, b1)
        assert m0 == m1
        assert np.array_equal(g0[0], g1[0])
        assert h0 == h1
        # one denial per core, not per call
        col.read_batch(p)
        gained = ncore._M_FALLBACK.value(reason="no_build") - before
        assert gained == len({"scan", "serve", "http"})
        assert ncore._M_ACTIVE.value() == 0
    finally:
        ncore.reset_for_tests()


# -- build caching (satellite 2) ---------------------------------------------


def test_build_artifacts_content_keyed(tmp_path):
    """source_key/artifact_path change with CONTENT, not mtime — the
    regression that let an edited .cpp serve a stale .so."""
    src = tmp_path / "thing.cpp"
    src.write_text("int a() { return 1; }\n")
    k1 = native_build.source_key(src)
    p1 = native_build.artifact_path(src, "libthing")
    import os
    st = src.stat()
    src.write_text("int a() { return 2; }\n")
    os.utime(src, (st.st_atime, st.st_mtime))   # same mtime, new content
    k2 = native_build.source_key(src)
    assert k1 != k2
    assert p1 != native_build.artifact_path(src, "libthing")
    assert p1.name.startswith("libthing-") and p1.suffix == ".so"


@needs_native
def test_build_replaces_stale_artifact(tmp_path, monkeypatch):
    monkeypatch.setattr(native_build, "BUILD_DIR", tmp_path / "_build")
    src = tmp_path / "mini.cpp"
    src.write_text('extern "C" int mini() { return 7; }\n')
    so1 = native_build.build(src, "libmini")
    assert so1.exists()
    src.write_text('extern "C" int mini() { return 8; }\n')
    so2 = native_build.build(src, "libmini")
    assert so2 != so1 and so2.exists()
    assert not so1.exists()       # old content-keyed artifact cleaned
    import ctypes
    assert ctypes.CDLL(str(so2)).mini() == 8


# -- history cache (satellite 1) ---------------------------------------------


def _hev(u, i, name="buy"):
    from predictionio_tpu.events.event import Event

    return Event(event=name, entity_type="user", entity_id=u,
                 target_entity_id=i,
                 event_time=dt.datetime(2024, 1, 1,
                                        tzinfo=dt.timezone.utc))


def test_history_cache_staleness_oracle(mem_storage, monkeypatch):
    """The cache NEVER serves a read the PIO_HISTORY_CACHE=off oracle
    answers differently: appends invalidate per entity, deletes flush,
    and unrelated entities keep their entries."""
    from predictionio_tpu.serve import history_cache as hc
    from predictionio_tpu.storage import App

    app_id = mem_storage.apps.insert(App(0, "histapp"))
    cache = hc.get_cache()
    cache.reset_for_tests()

    def oracle(u):
        monkeypatch.setenv("PIO_HISTORY_CACHE", "off")
        try:
            return hc.user_history_targets("histapp", "user", u, "buy", 50)
        finally:
            monkeypatch.delenv("PIO_HISTORY_CACHE")

    def cached(u):
        return hc.user_history_targets("histapp", "user", u, "buy", 50)

    def hits():
        return hc._M_LOOKUP.value(outcome="hit")

    mem_storage.l_events.insert_batch(
        [_hev("u1", "i1"), _hev("u1", "i2"), _hev("u2", "i9")], app_id)
    assert sorted(cached("u1")) == sorted(oracle("u1")) == ["i1", "i2"]
    h0 = hits()
    cached("u1")
    assert hits() == h0 + 1                   # second read was a hit

    # append for u1: only u1 re-reads
    mem_storage.l_events.insert_batch([_hev("u1", "i3")], app_id)
    assert sorted(cached("u1")) == sorted(oracle("u1"))
    cached("u2")
    h1 = hits()
    mem_storage.l_events.insert_batch([_hev("u1", "i4")], app_id)
    cached("u2")                              # u2 untouched -> still a hit
    assert hits() == h1 + 1

    # delete flushes (entity unknown); result matches the oracle
    eid = mem_storage.l_events.insert(_hev("u1", "i5"), app_id)
    assert "i5" in cached("u1")
    mem_storage.l_events.delete(eid, app_id)
    assert sorted(cached("u1")) == sorted(oracle("u1"))
    assert "i5" not in cached("u1")

    # unknown app: empty and uncacheable, both modes
    assert hc.user_history_targets("ghost", "user", "u", "buy", 5) == ()


def test_history_cache_user_history_engine_parity(mem_storage, monkeypatch):
    """Engine-level ``_user_history`` is identical with the cache on vs
    the off oracle, before and after mid-stream appends."""
    from predictionio_tpu.serve import history_cache as hc
    from predictionio_tpu.storage import App

    class _Dict:
        def __init__(self, ids):
            self._m = {s: k for k, s in enumerate(ids)}

        def id(self, s):
            return self._m.get(s)

    class _Model:
        event_item_dicts = {"buy": _Dict([f"i{k}" for k in range(10)])}

    class _Params:
        app_name = "uheng"
        max_query_events = 50

    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm)

    app_id = mem_storage.apps.insert(App(0, "uheng"))
    hc.get_cache().reset_for_tests()
    algo = URAlgorithm.__new__(URAlgorithm)
    algo.params = _Params()

    mem_storage.l_events.insert_batch(
        [_hev("u1", "i1"), _hev("u1", "i7"), _hev("u1", "zzz")], app_id)

    def both(u):
        on = URAlgorithm._user_history(algo, _Model(), u)
        monkeypatch.setenv("PIO_HISTORY_CACHE", "off")
        try:
            off = URAlgorithm._user_history(algo, _Model(), u)
        finally:
            monkeypatch.delenv("PIO_HISTORY_CACHE")
        assert set(on) == set(off)
        for k in on:
            assert np.array_equal(on[k], off[k]), k
        return on

    h = both("u1")
    assert h["buy"].tolist() == [1, 7]        # "zzz" filtered by the dict
    mem_storage.l_events.insert_batch([_hev("u1", "i2")], app_id)
    h = both("u1")
    assert h["buy"].tolist() == [1, 2, 7]
    assert both("nobody")["buy"].tolist() == []
