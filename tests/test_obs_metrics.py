"""Observability subsystem: registry thread-safety, Prometheus golden
text, cross-worker aggregation through real prefork workers, stats.json
window semantics, span-journal round trip through the train workflow,
and the metric-name lint."""

import json
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.obs.exposition import (
    StatsCollector,
    family_total,
    parse_prometheus_text,
    render_prometheus,
    summarize_prometheus,
)
from predictionio_tpu.obs.metrics import (
    MetricsRegistry,
    merge_snapshots,
)
from predictionio_tpu.storage import AccessKey, App

REPO = Path(__file__).resolve().parent.parent


def http(method, url, body=None):
    import urllib.error

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


# -- registry -----------------------------------------------------------------

def test_registry_thread_safety_concurrent_increments():
    reg = MetricsRegistry()
    c = reg.counter("pio_tst_total", "t")
    g = reg.gauge("pio_tst_gauge", "t")
    h = reg.histogram("pio_tst_seconds", "t")
    n_threads, per_thread = 8, 5_000

    def work():
        for k in range(per_thread):
            c.inc(1, route="/x")
            g.inc(1)
            h.observe(0.001 * (k % 7))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert c.value(route="/x") == total
    assert g.value() == total
    snap = reg.snapshot()
    hs = snap["pio_tst_seconds"]["series"][""]
    assert hs["count"] == total
    assert sum(hs["counts"]) == total


def test_registry_name_and_help_validation():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("http_requests_total", "missing pio_ prefix")
    with pytest.raises(ValueError):
        reg.counter("pio_Bad_Case", "uppercase")
    with pytest.raises(ValueError):
        reg.counter("pio_ok_total", "")
    c = reg.counter("pio_ok_total", "help")
    assert reg.counter("pio_ok_total", "help") is c   # idempotent
    with pytest.raises(ValueError):
        reg.gauge("pio_ok_total", "kind mismatch")


def test_registry_disabled_is_noop():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("pio_off_total", "t")
    c.inc(5)
    assert c.value() == 0.0


def test_prometheus_text_golden():
    reg = MetricsRegistry()
    c = reg.counter("pio_g_requests_total", "Requests served")
    c.inc(3, route="/a", status="200")
    c.inc(1, route="/b", status="404")
    g = reg.gauge("pio_g_in_flight", "In-flight requests")
    g.set(2)
    h = reg.histogram("pio_g_latency_seconds", "Latency", buckets=(0.01, 0.1))
    h.observe(0.005)
    h.observe(0.05)
    h.observe(5.0)
    assert render_prometheus(reg.snapshot()) == (
        "# HELP pio_g_in_flight In-flight requests\n"
        "# TYPE pio_g_in_flight gauge\n"
        "pio_g_in_flight 2\n"
        "# HELP pio_g_latency_seconds Latency\n"
        "# TYPE pio_g_latency_seconds histogram\n"
        'pio_g_latency_seconds_bucket{le="0.01"} 1\n'
        'pio_g_latency_seconds_bucket{le="0.1"} 2\n'
        'pio_g_latency_seconds_bucket{le="+Inf"} 3\n'
        "pio_g_latency_seconds_sum 5.055\n"
        "pio_g_latency_seconds_count 3\n"
        "# HELP pio_g_requests_total Requests served\n"
        "# TYPE pio_g_requests_total counter\n"
        'pio_g_requests_total{route="/a",status="200"} 3\n'
        'pio_g_requests_total{route="/b",status="404"} 1\n'
    )


def test_prometheus_parse_and_summary_roundtrip():
    reg = MetricsRegistry()
    reg.counter("pio_r_total", "t").inc(7, route="/x,y", status="201")
    reg.histogram("pio_r_seconds", "t").observe(0.3)
    text = render_prometheus(reg.snapshot())
    fams, types = parse_prometheus_text(text)
    assert types == {"pio_r_total": "counter", "pio_r_seconds": "histogram"}
    # label values containing a comma survive the round trip
    assert fams["pio_r_total"] == [({"route": "/x,y", "status": "201"}, 7.0)]
    assert family_total(fams, "pio_r_seconds_count") == 1.0
    digest = summarize_prometheus(text)
    assert "pio_r_total" in digest and "count=1" in digest


def test_label_escape_roundtrip_hostile_values():
    reg = MetricsRegistry()
    c = reg.counter("pio_esc_total", "t")
    nasty = ['a\\nb', 'a\nb', 'say "hi"', "back\\slash", "plain"]
    for v in nasty:
        c.inc(1, event=v)
    fams, _ = parse_prometheus_text(render_prometheus(reg.snapshot()))
    parsed = {lb["event"] for lb, _v in fams["pio_esc_total"]}
    assert parsed == set(nasty)


def test_stale_worker_snapshot_zeroes_gauges_keeps_counters(tmp_path):
    import os

    from predictionio_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.get_registry()
    try:
        obs_metrics.start_worker_flusher(str(tmp_path), tag="live-w")
        # fake a dead sibling: stale mtime, nonzero gauge + counter
        dead = MetricsRegistry()
        dead.gauge("pio_http_requests_in_flight", "x").set(3)
        dead.counter("pio_storage_events_appended_total", "x").inc(7)
        import json as _json

        p = tmp_path / "dead-w.json"
        p.write_text(_json.dumps(dead.snapshot()))
        os.utime(p, (0, 0))   # ancient mtime → stale
        snap = obs_metrics.aggregate_snapshot(reg)
        # dead worker's counters still aggregate; its gauges read 0
        assert sum(
            snap["pio_storage_events_appended_total"]["series"].values()) >= 7
        inflight = snap["pio_http_requests_in_flight"]["series"]
        assert sum(inflight.values()) == reg.gauge(
            "pio_http_requests_in_flight", "x").value()
    finally:
        obs_metrics.stop_worker_flusher()


def test_merge_snapshots_across_workers():
    def make(n):
        reg = MetricsRegistry()
        reg.counter("pio_m_total", "t").inc(n)
        reg.histogram("pio_m_seconds", "t", buckets=(0.1, 1.0)).observe(n)
        return reg.snapshot()

    merged = merge_snapshots([make(0.05), make(0.5)])
    assert merged["pio_m_total"]["series"][""] == 0.55
    hs = merged["pio_m_seconds"]["series"][""]
    assert hs["count"] == 2 and hs["counts"] == [1, 1, 0]
    text = render_prometheus(merged)
    fams, _ = parse_prometheus_text(text)
    assert family_total(fams, "pio_m_seconds_count") == 2.0


# -- stats.json windows -------------------------------------------------------

def test_stats_collector_window_semantics():
    s = StatsCollector(window_s=10.0)
    s.record(1, 201, "buy", "user", now=0.0)
    s.record(1, 201, "buy", "user", now=3.0)
    s.record(2, 400, None, None, now=4.0)
    doc = s.to_json(now=5.0)
    assert doc["statsSinceStart"] == doc["statsCurrent"]
    assert doc["statsLastWindow"] == []
    buy = next(e for e in doc["statsCurrent"] if e.get("event") == "buy")
    assert buy == {"status": 201, "count": 2, "appId": 1, "event": "buy",
                   "entityType": "user"}
    # crossing the window boundary publishes current as last-window
    s.record(1, 201, "view", "user", now=12.0)
    doc = s.to_json(now=12.5)
    assert [e["count"] for e in doc["statsLastWindow"]] == [2, 1]
    assert len(doc["statsCurrent"]) == 1
    assert doc["statsCurrent"][0]["event"] == "view"
    assert len(doc["statsSinceStart"]) == 3   # since-start never resets
    # app filter keeps only that app's entries
    doc1 = s.to_json(app_id=2, now=13.0)
    assert all(e["appId"] == 2 for e in doc1["statsSinceStart"])
    # an idle gap spanning multiple windows: the just-completed window
    # was empty — old counts must not resurface as "last window"
    doc2 = s.to_json(now=300.0)
    assert doc2["statsLastWindow"] == []
    assert doc2["statsCurrent"] == []
    assert len(doc2["statsSinceStart"]) == 3


def test_event_server_state_bounds_event_label_cardinality(mem_storage):
    from predictionio_tpu.api.event_server import EventServerState

    state = EventServerState(mem_storage)
    state.MAX_EVENT_LABELS = 10
    for k in range(50):
        state.record(1, f"evt-{k}", 201, entity_type="user")
    recorded = set(state.counts[1])
    # names and entity types share the budget: at most MAX distinct
    # labels total, overflow folded into "(other)"
    assert "(other)" in recorded
    assert len(recorded) <= state.MAX_EVENT_LABELS + 1
    assert sum(state.counts[1].values()) == 50  # nothing dropped, only folded
    assert len(state._event_labels) == state.MAX_EVENT_LABELS


# -- event server endpoints ---------------------------------------------------

@pytest.fixture()
def event_server(mem_storage):
    from predictionio_tpu.api.event_server import run_event_server

    app_id = mem_storage.apps.insert(App(0, "obsapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    yield {"base": f"http://127.0.0.1:{httpd.server_address[1]}",
           "key": key, "app_id": app_id}
    httpd.shutdown()
    httpd.server_close()


def test_readiness_probe_reports_version_and_tag(event_server):
    from predictionio_tpu import __version__

    status, body = http("GET", event_server["base"] + "/")
    assert status == 200
    assert body["version"] == __version__
    assert body["workerTag"]   # pid-based when not prefork-spawned


def test_event_server_stats_json_windows_and_compat(event_server):
    base, key = event_server["base"], event_server["key"]
    for _ in range(2):
        s, _b = http("POST", f"{base}/events.json?accessKey={key}", {
            "event": "rate", "entityType": "user", "entityId": "u1"})
        assert s == 201
    status, doc = http("GET", f"{base}/stats.json?accessKey={key}")
    assert status == 200
    # back-compat keys survive
    assert doc["appId"] == event_server["app_id"]
    assert doc["counts"]["rate"] == 2
    # reference-parity windows
    entry = next(e for e in doc["statsSinceStart"] if e.get("event") == "rate")
    assert entry["status"] == 201 and entry["count"] == 2
    assert entry["entityType"] == "user"
    assert doc["statsCurrent"] and "startTime" in doc and "window" in doc


def test_event_server_metrics_endpoint(event_server):
    base, key = event_server["base"], event_server["key"]
    s, _ = http("POST", f"{base}/events.json?accessKey={key}", {
        "event": "buy", "entityType": "user", "entityId": "u9"})
    assert s == 201
    with urllib.request.urlopen(base + "/metrics") as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    fams, types = parse_prometheus_text(text)
    assert types["pio_http_requests_total"] == "counter"
    assert types["pio_http_request_duration_seconds"] == "histogram"
    assert family_total(fams, "pio_events_ingested_total",
                        app=str(event_server["app_id"]), event="buy") >= 1
    # route label is normalized, not per-path cardinality
    assert any(lb.get("route") == "/events.json"
               for lb, _v in fams["pio_http_requests_total"])


def test_request_id_echoed_and_propagated(event_server):
    req = urllib.request.Request(event_server["base"] + "/",
                                 headers={"X-Request-ID": "abc-123"})
    with urllib.request.urlopen(req) as r:
        assert r.headers["X-Request-ID"] == "abc-123"
    with urllib.request.urlopen(event_server["base"] + "/") as r:
        assert r.headers["X-Request-ID"]   # server-minted when absent


def test_route_label_bounds_cardinality():
    from predictionio_tpu.api.http_util import route_label

    assert route_label("/events.json?accessKey=k") == "/events.json"
    assert route_label("/events/abc123.json") == "/events/{id}.json"
    assert route_label("/webhooks/segmentio.json") == "/webhooks/{name}.json"
    assert route_label("/cmd/app/My App/accesskeys") == "/cmd/app/{name}/accesskeys"
    assert route_label("/totally/unknown/path") == "(other)"


# -- dashboard + query server endpoints ---------------------------------------

def test_dashboard_serves_metrics_stats_and_durations(fs_storage):
    import datetime as dt

    from predictionio_tpu.api.dashboard import run_dashboard
    from predictionio_tpu.storage.base import EngineInstance

    t0 = dt.datetime(2026, 8, 1, 12, 0, 0, tzinfo=dt.timezone.utc)
    fs_storage.engine_instances.insert(EngineInstance(
        id="dashinst1", status="COMPLETED", start_time=t0,
        end_time=t0 + dt.timedelta(seconds=12.5),
        engine_id="e", engine_version="1", engine_variant="default",
        engine_factory="f"))
    httpd = run_dashboard(host="127.0.0.1", port=0, storage=fs_storage,
                          background=True)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        with urllib.request.urlopen(base + "/") as r:
            page = r.read().decode()
        assert "12.50 s" in page          # rendered end−start duration
        with urllib.request.urlopen(base + "/metrics") as r:
            assert b"pio_http_requests_total" in r.read()
        status, doc = http("GET", base + "/stats.json")
        assert status == 200 and "statsSinceStart" in doc
        status, _ = http("GET", base + "/spans/nonexistent.json")
        assert status == 404
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- span journal through the train workflow ----------------------------------

class _TracedEngine:
    """Minimal duck-typed Engine: train() runs timed() blocks that must
    land in the active span journal as children of the run's root."""

    def train(self, engine_params):
        from predictionio_tpu.utils.tracing import timed

        with timed("read_training"):
            with timed("parse"):
                pass
        with timed("fit"):
            pass
        return [{"weights": [1, 2, 3]}]


def test_span_journal_roundtrip_through_train(fs_storage):
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.obs import spans as obs_spans
    from predictionio_tpu.workflow import core_workflow

    instance = core_workflow.run_train(
        _TracedEngine(), EngineParams(), engine_id="traced",
        storage=fs_storage)
    assert instance.status == "COMPLETED"
    path = obs_spans.journal_path(fs_storage, instance.id)
    # persisted next to the engine instances (under the storage root)
    assert str(path).startswith(
        fs_storage.config.sources["FS"]["path"])
    spans = obs_spans.read_journal(path)
    by_name = {s["name"]: s for s in spans}
    assert {"train", "engine_train", "read_training", "parse", "fit",
            "save_models"} <= set(by_name)
    root = by_name["train"]
    assert root["parent"] is None
    assert by_name["engine_train"]["parent"] == root["id"]
    # timed() inside engine.train nests under the engine_train span
    assert by_name["read_training"]["parent"] == by_name["engine_train"]["id"]
    assert by_name["parse"]["parent"] == by_name["read_training"]["id"]
    assert all(s["duration_s"] >= 0 and s["end"] >= s["start"]
               for s in spans)
    assert root["attrs"]["instance_id"] == instance.id

    # the dashboard serves and renders the journal
    from predictionio_tpu.api.dashboard import run_dashboard

    httpd = run_dashboard(host="127.0.0.1", port=0, storage=fs_storage,
                          background=True)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        status, doc = http("GET", f"{base}/spans/{instance.id}.json")
        assert status == 200 and len(doc["spans"]) == len(spans)
        with urllib.request.urlopen(base + "/") as r:
            assert b"engine_train" in r.read()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_timed_sink_accumulates_seconds_and_count():
    from predictionio_tpu.utils.tracing import timed

    sink = {}
    for _ in range(3):
        with timed("op", sink):
            pass
    assert sink["op"] >= 0 and sink["op.count"] == 3


# -- cross-worker aggregation through real prefork workers --------------------

def test_cross_worker_scrape_sees_both_prefork_workers(tmp_path, monkeypatch):
    """`eventserver --workers 2`: ingest through BOTH workers, then one
    scrape of whichever worker answers must report the group aggregate —
    exactly the number of events acked — and two pio_worker_up series."""
    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.storage.locator import (
        Storage,
        StorageConfig,
        set_storage,
    )

    store = tmp_path / "store"
    env_vars = {
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(store),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        "PIO_JAX_PLATFORM": "cpu",
        "PIO_METRICS_FLUSH_S": "0.2",
    }
    for k, v in env_vars.items():
        monkeypatch.setenv(k, v)
    monkeypatch.delenv("PIO_WRITER_TAG", raising=False)
    meta = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(store)}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    app_id = meta.apps.insert(App(0, "obsxw"))
    key = meta.access_keys.insert(AccessKey("", app_id, []))
    set_storage(None)   # workers>1 resolves storage from env

    def scrape(base):
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            return parse_prometheus_text(r.read().decode())[0]

    httpd = run_event_server(host="127.0.0.1", port=0, background=True,
                             workers=2)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        pids, deadline = set(), time.time() + 90
        while len(pids) < 2 and time.time() < deadline:
            try:
                with urllib.request.urlopen(base + "/", timeout=2) as r:
                    pids.add(json.loads(r.read())["pid"])
            except Exception:
                time.sleep(0.2)
        assert len(pids) == 2, f"second worker never came up: {pids}"
        # baseline: the in-process parent registry may carry counts from
        # earlier tests in this pytest process — assert on the DELTA
        base_fams = scrape(base)
        base_appended = family_total(
            base_fams, "pio_storage_events_appended_total")
        n = 40
        for k2 in range(n):
            body = {"event": "buy", "entityType": "user",
                    "entityId": "u1", "eventId": f"xw-{k2}"}
            for _ in range(5):
                try:
                    s, _b = http("POST",
                                 f"{base}/events.json?accessKey={key}",
                                 body)
                    assert s == 201
                    break
                except Exception:
                    time.sleep(0.2)
            else:
                raise AssertionError(f"event xw-{k2} could not be posted")
        # fresh connections are kernel-balanced; poll until the aggregate
        # converges (sibling snapshots flush on an interval)
        deadline = time.time() + 30
        while time.time() < deadline:
            fams = scrape(base)
            appended = family_total(
                fams, "pio_storage_events_appended_total") - base_appended
            if appended == n and len(fams.get("pio_worker_up", ())) >= 2:
                break
            time.sleep(0.3)
        assert appended == n, f"aggregate scrape saw {appended}/{n}"
        workers_up = {lb["worker"] for lb, v in fams["pio_worker_up"]
                      if v >= 1}
        assert len(workers_up) == 2, workers_up
    finally:
        httpd.shutdown()
        httpd.server_close()
        set_storage(None)


# -- lint ---------------------------------------------------------------------

def test_check_metrics_names_lint_passes():
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_metrics_names.py")],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok:" in r.stdout
