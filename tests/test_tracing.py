"""Flight-recorder coverage: tail-sampling triggers, ring eviction under
concurrent requests, cross-worker /traces.json merge through real
prefork workers, metric exemplars, incremental span-journal persistence
(crash-safe), SDK request-id joinability, quantile interpolation, and
the trace round-trip script."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.obs import tracing as obs_tracing
from predictionio_tpu.obs.tracing import FlightRecorder
from predictionio_tpu.storage import AccessKey, App

REPO = Path(__file__).resolve().parent.parent


def http(method, url, body=None, headers=None):
    import urllib.error

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


@pytest.fixture()
def fresh_recorder():
    """Install a fresh default-config recorder for the test and restore
    the lazy default afterwards (the recorder is process-global)."""
    def install(**kw):
        rec = FlightRecorder(**kw)
        obs_tracing.set_recorder(rec)
        return rec

    yield install
    obs_tracing.set_recorder(None)


@pytest.fixture()
def event_server(mem_storage, fresh_recorder):
    from predictionio_tpu.api.event_server import run_event_server

    app_id = mem_storage.apps.insert(App(0, "traceapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    yield {"base": f"http://127.0.0.1:{httpd.server_address[1]}",
           "key": key, "app_id": app_id, "install": fresh_recorder}
    httpd.shutdown()
    httpd.server_close()


# -- tail-sampling policy (unit) ----------------------------------------------

def test_tail_sampling_reasons():
    rec = FlightRecorder(slow_ms=10_000, sample_n=0, enabled=True)
    assert rec.finish(rec.begin("r1", "GET"), 200, "/x") is None  # boring
    assert rec.finish(rec.begin("r2", "GET"), 500, "/x") == "error"
    assert rec.finish(rec.begin("r3", "GET"), 0, "/x") == "error"
    t = rec.begin("r4", "GET", debug=True)
    assert rec.finish(t, 200, "/x") == "debug"
    slow = FlightRecorder(slow_ms=0.0, sample_n=0, enabled=True)
    assert slow.finish(slow.begin("r5", "GET"), 200, "/x") == "slow"
    always = FlightRecorder(slow_ms=10_000, sample_n=1, enabled=True)
    assert always.finish(always.begin("r6", "GET"), 200, "/x") == "sampled"
    off = FlightRecorder(enabled=False)
    assert off.begin("r7", "GET") is None
    assert off.finish(None, 200, "/x") is None


def test_trace_spans_and_waterfall_text():
    rec = FlightRecorder(slow_ms=0, sample_n=0, enabled=True)
    t = rec.begin("wf1", "POST")
    with t.activate():
        assert obs_tracing.current_trace() is t
        with obs_tracing.trace_span("group_commit_append"):
            pass
        with t.span("ur_predict") as r:
            pass
        t.add_span("history", r["start"], 0.002, parent=r["id"])
    assert obs_tracing.current_trace() is None
    rec.finish(t, 201, "/events.json")
    doc = rec.get("wf1")
    by_name = {s["name"]: s for s in doc["spans"]}
    assert by_name["history"]["parent"] == by_name["ur_predict"]["id"]
    assert by_name["group_commit_append"]["parent"] is None
    text = obs_tracing.render_waterfall_text(doc)
    assert "wf1" in text and "ur_predict" in text and "history" in text


def test_timed_lands_in_active_trace():
    from predictionio_tpu.utils.tracing import timed

    rec = FlightRecorder(slow_ms=0, sample_n=0, enabled=True)
    t = rec.begin("tm1", "GET")
    with t.activate():
        with timed("outer_op"):
            with timed("inner_op"):
                pass
    by_name = {s["name"]: s for s in t.spans()}
    assert by_name["inner_op"]["parent"] == by_name["outer_op"]["id"]


# -- e2e through the event server ---------------------------------------------

def test_debug_header_forces_retention(event_server):
    event_server["install"](slow_ms=10_000, sample_n=0)
    base, key = event_server["base"], event_server["key"]
    s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                {"event": "buy", "entityType": "user", "entityId": "u1"})
    assert s == 201   # boring request: dropped
    s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                {"event": "buy", "entityType": "user", "entityId": "u1"},
                headers={"X-Request-ID": "dbg-1", "X-PIO-Debug": "1"})
    assert s == 201
    s, idx = http("GET", f"{base}/traces.json")
    assert s == 200
    assert {t["rid"] for t in idx["traces"]} == {"dbg-1"}
    assert idx["traces"][0]["reason"] == "debug"
    s, doc = http("GET", f"{base}/traces/dbg-1.json")
    assert s == 200
    assert doc["route"] == "/events.json" and doc["status"] == 201
    # the group-commit span from the storage layer is in the waterfall
    # (memory backend has no group commit; accept either, but the
    # envelope itself must be present)
    assert doc["rid"] == "dbg-1" and doc["durationMs"] > 0
    s, _ = http("GET", f"{base}/traces/unknown.json")
    assert s == 404


def test_slow_threshold_retains_with_spans(event_server):
    event_server["install"](slow_ms=0.0, sample_n=0)
    base, key = event_server["base"], event_server["key"]
    s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                {"event": "buy", "entityType": "user", "entityId": "u2"},
                headers={"X-Request-ID": "slow-1"})
    assert s == 201
    s, doc = http("GET", f"{base}/traces/slow-1.json")
    assert s == 200 and doc["reason"] == "slow"


def test_sample_one_in_one_retains_everything(event_server):
    event_server["install"](slow_ms=10_000, sample_n=1)
    base, key = event_server["base"], event_server["key"]
    for k in range(3):
        s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                    {"event": "buy", "entityType": "user", "entityId": "u3"},
                    headers={"X-Request-ID": f"samp-{k}"})
        assert s == 201
    s, idx = http("GET", f"{base}/traces.json")
    rids = {t["rid"] for t in idx["traces"]}
    assert {"samp-0", "samp-1", "samp-2"} <= rids
    assert all(t["reason"] == "sampled" for t in idx["traces"]
               if t["rid"].startswith("samp-"))


def test_tracing_kill_switch_503(event_server):
    event_server["install"](enabled=False)
    base = event_server["base"]
    s, body = http("GET", f"{base}/traces.json")
    assert s == 503 and "disabled" in body["message"]
    s, _ = http("GET", f"{base}/traces/whatever.json")
    assert s == 503


def test_ring_eviction_under_concurrent_requests(event_server):
    rec = event_server["install"](slow_ms=0.0, sample_n=0, ring=8)
    base, key = event_server["base"], event_server["key"]
    n_threads, per_thread = 8, 6
    errors = []

    def worker(w):
        try:
            for k in range(per_thread):
                s, _ = http(
                    "POST", f"{base}/events.json?accessKey={key}",
                    {"event": "buy", "entityType": "user",
                     "entityId": f"u{w}"},
                    headers={"X-Request-ID": f"ev-{w}-{k}"})
                assert s == 201
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    with rec._lock:
        ring = list(rec._ring)
    assert len(ring) == 8          # bounded, newest survive
    s, idx = http("GET", f"{base}/traces.json")
    assert s == 200
    ev_rids = [t for t in idx["traces"] if t["rid"].startswith("ev-")]
    assert len(ev_rids) <= 8 + 1   # ring + the /traces.json request itself


def test_exemplar_links_metrics_to_trace(event_server, monkeypatch):
    from predictionio_tpu.obs.exposition import parse_exemplars

    # a short window so earlier tests' slower observations (the process
    # registry is shared) age out and this request's id wins the slot
    monkeypatch.setenv("PIO_EXEMPLAR_WINDOW_S", "0.1")
    time.sleep(0.15)
    event_server["install"](slow_ms=0.0, sample_n=0)
    base, key = event_server["base"], event_server["key"]
    s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                {"event": "buy", "entityType": "user", "entityId": "u9"},
                headers={"X-Request-ID": "exemplar-rid-1"})
    assert s == 201
    with urllib.request.urlopen(base + "/metrics") as r:
        text = r.read().decode()
    ex = parse_exemplars(text)
    linked = {(lb.get("route"), rid) for lb, rid, _v in
              ex.get("pio_http_request_duration_seconds_bucket", ())}
    assert any(rid == "exemplar-rid-1" and route == "/events.json"
               for route, rid in linked), ex
    # the exemplar-carrying text still parses cleanly
    from predictionio_tpu.obs.exposition import parse_prometheus_text

    fams, _ = parse_prometheus_text(text)
    assert fams["pio_http_request_duration_seconds_bucket"]


def test_trace_persists_for_dashboard_merge(fs_storage, fresh_recorder,
                                            tmp_path):
    """A single fs-backed server persists retained traces under
    <store>/traces; a dashboard on the same storage merges them."""
    from predictionio_tpu.api.dashboard import run_dashboard
    from predictionio_tpu.api.event_server import run_event_server

    fresh_recorder(slow_ms=10_000, sample_n=0)
    app_id = fs_storage.apps.insert(App(0, "fsapp"))
    key = fs_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=fs_storage,
                             background=True)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                    {"event": "buy", "entityType": "user", "entityId": "u1"},
                    headers={"X-Request-ID": "persist-1",
                             "X-PIO-Debug": "1"})
        assert s == 201
    finally:
        httpd.shutdown()
        httpd.server_close()
    store = Path(fs_storage.config.sources["FS"]["path"])
    files = list((store / "traces").glob("*.json"))
    assert files, "retained trace was not persisted under <store>/traces"
    dash = run_dashboard(host="127.0.0.1", port=0, storage=fs_storage,
                         background=True)
    dbase = f"http://127.0.0.1:{dash.server_address[1]}"
    try:
        s, doc = http("GET", f"{dbase}/traces/persist-1.json")
        assert s == 200 and doc["reason"] == "debug"
        with urllib.request.urlopen(f"{dbase}/traces/persist-1.html") as r:
            page = r.read().decode()
        assert "waterfall" in page and "persist-1" in page
        with urllib.request.urlopen(dbase + "/") as r:
            front = r.read().decode()
        assert "persist-1" in front   # recent-traces table
    finally:
        dash.shutdown()
        dash.server_close()


# -- cross-worker merge through real prefork workers --------------------------

def test_cross_worker_traces_merge(tmp_path, monkeypatch, fresh_recorder):
    """`eventserver --workers 2`: debug-marked requests served by BOTH
    workers must appear in ONE /traces.json (whoever answers), and a
    trace retained by one worker must be fetchable via a request that
    may land on the other."""
    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.storage.locator import (
        Storage,
        StorageConfig,
        set_storage,
    )

    store = tmp_path / "store"
    for k, v in {
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(store),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        "PIO_JAX_PLATFORM": "cpu",
        "PIO_METRICS_FLUSH_S": "0.2",
    }.items():
        monkeypatch.setenv(k, v)
    monkeypatch.delenv("PIO_WRITER_TAG", raising=False)
    fresh_recorder()   # default policy; debug header forces the keeps
    meta = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(store)}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    app_id = meta.apps.insert(App(0, "tracexw"))
    key = meta.access_keys.insert(AccessKey("", app_id, []))
    set_storage(None)
    httpd = run_event_server(host="127.0.0.1", port=0, background=True,
                             workers=2)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        pids, deadline = set(), time.time() + 90
        while len(pids) < 2 and time.time() < deadline:
            try:
                with urllib.request.urlopen(base + "/", timeout=2) as r:
                    pids.add(json.loads(r.read())["pid"])
            except Exception:
                time.sleep(0.2)
        assert len(pids) == 2, f"second worker never came up: {pids}"
        # debug-marked posts: fresh connections are kernel-balanced, so
        # enough of them land on both workers
        n = 24
        for k2 in range(n):
            for _ in range(5):
                try:
                    s, _b = http(
                        "POST", f"{base}/events.json?accessKey={key}",
                        {"event": "buy", "entityType": "user",
                         "entityId": "u1", "eventId": f"txw-{k2}"},
                        headers={"X-Request-ID": f"xw-{k2}",
                                 "X-PIO-Debug": "1"})
                    assert s == 201
                    break
                except Exception:
                    time.sleep(0.2)
            else:
                raise AssertionError(f"event txw-{k2} could not be posted")
        want = {f"xw-{k2}" for k2 in range(n)}
        deadline = time.time() + 30
        workers_seen: set = set()
        got: set = set()
        while time.time() < deadline:
            s, idx = http("GET", f"{base}/traces.json")
            assert s == 200
            entries = [t for t in idx["traces"]
                       if t["rid"].startswith("xw-")]
            got = {t["rid"] for t in entries}
            workers_seen = {t["worker"] for t in entries}
            if got == want and len(workers_seen) == 2:
                break
            time.sleep(0.3)
        assert got == want, f"merged index missing {sorted(want - got)}"
        assert len(workers_seen) == 2, (
            f"all retained traces claim one worker: {workers_seen} "
            "(kernel did not balance, or the merge is broken)")
        # a full waterfall resolves no matter which worker answers
        s, doc = http("GET", f"{base}/traces/xw-0.json")
        assert s == 200 and doc["reason"] == "debug"
        assert doc["route"] == "/events.json"
    finally:
        httpd.shutdown()
        httpd.server_close()
        set_storage(None)


# -- span journal: incremental append + crash safety --------------------------

def test_span_journal_incremental_append(tmp_path):
    from predictionio_tpu.obs.spans import SpanJournal, read_journal

    path = tmp_path / "j.jsonl"
    j = SpanJournal(path)
    with j.span("phase_one"):
        with j.span("child_a"):
            pass
    # flushed at root completion, BEFORE write()
    spans = read_journal(path)
    assert {s["name"] for s in spans} == {"phase_one", "child_a"}
    with j.span("phase_two"):
        pass
    j.write()
    spans = read_journal(path)
    assert {s["name"] for s in spans} == {"phase_one", "child_a",
                                          "phase_two"}
    by_name = {s["name"]: s for s in spans}
    assert by_name["child_a"]["parent"] == by_name["phase_one"]["id"]


def test_span_journal_survives_sigkill(tmp_path):
    """A crashed run keeps every completed root span (the old buffer-
    everything journal lost the whole file)."""
    path = tmp_path / "crash.jsonl"
    code = f"""
import os, signal
from predictionio_tpu.obs.spans import SpanJournal
j = SpanJournal({str(path)!r})
with j.activate():
    with j.span("completed_phase"):
        with j.span("completed_child"):
            pass
    with j.span("doomed_phase"):
        os.kill(os.getpid(), signal.SIGKILL)
"""
    r = subprocess.run([sys.executable, "-c", code], timeout=120,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == -9
    from predictionio_tpu.obs.spans import read_journal

    spans = read_journal(path)
    names = {s["name"] for s in spans}
    assert "completed_phase" in names and "completed_child" in names
    assert "doomed_phase" not in names   # never completed, never flushed


# -- SDK request-id joinability -----------------------------------------------

def test_sdk_error_includes_request_id(event_server):
    from predictionio_tpu.sdk.client import EventClient, PIOError

    base = event_server["base"]
    bad = EventClient("wrong-key", base)
    with pytest.raises(PIOError) as ei:
        bad.create_event("buy", "user", "u1")
    assert ei.value.request_id
    assert f"request-id {ei.value.request_id}" in str(ei.value)
    # the echoed server-side id IS the client's (joinable): a good
    # client's event post must round-trip the minted id
    good = EventClient(event_server["key"], base)
    assert good.create_event("buy", "user", "u1")


def test_sdk_pipeline_error_includes_request_id(event_server):
    from predictionio_tpu.sdk.client import EventClient, PIOError

    bad = EventClient("wrong-key", event_server["base"])
    with bad.pipeline(depth=4) as p:
        h = p.create_event("buy", "user", "u1")
    with pytest.raises(PIOError) as ei:
        h.result()
    assert ei.value.request_id == h.request_id
    assert h.request_id in str(ei.value)


# -- quantile interpolation ---------------------------------------------------

def test_quantile_single_observation_not_upper_bound():
    from predictionio_tpu.obs.exposition import _quantile_from_buckets

    inf = float("inf")
    # one observation, landing in the (0.1, 0.25] bucket
    buckets = [(0.1, 0.0), (0.25, 1.0), (inf, 1.0)]
    p50 = _quantile_from_buckets(buckets, 1.0, 0.50)
    p95 = _quantile_from_buckets(buckets, 1.0, 0.95)
    p99 = _quantile_from_buckets(buckets, 1.0, 0.99)
    for q in (p50, p95, p99):
        assert 0.1 <= q < 0.25, "quantile must stay inside the bucket"
    assert p50 <= p95 <= p99
    assert p99 < 0.25 - 1e-9, "single observation must not report the " \
                              "bucket's upper bound"


def test_summarize_prometheus_quantiles_clamped():
    from predictionio_tpu.obs.exposition import summarize_prometheus
    from predictionio_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("pio_q_seconds", "t", buckets=(0.1, 0.25, 1.0))
    h.observe(0.2)   # crafted: a single observation
    from predictionio_tpu.obs.exposition import render_prometheus

    digest = summarize_prometheus(render_prometheus(reg.snapshot()))
    line = next(ln for ln in digest.splitlines() if "p50" in ln)
    import re

    p50, p95, p99 = (float(x) for x in re.findall(
        r"p\d+≈([0-9.e+-]+)", line))
    assert p50 <= p95 <= p99 < 0.25


# -- route labels + lint + round trip ----------------------------------------

def test_trace_route_labels_bounded():
    from predictionio_tpu.api.http_util import route_label

    assert route_label("/traces.json") == "/traces.json"
    assert route_label("/traces/abc-123.json") == "/traces/{rid}.json"
    assert route_label("/traces/abc-123.html") == "/traces/{rid}.html"


def test_check_trace_roundtrip_script():
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_trace_roundtrip.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok:" in r.stdout


def test_pio_trace_cli(event_server, capsys):
    from predictionio_tpu.cli.main import main as cli_main

    event_server["install"](slow_ms=10_000, sample_n=0)
    base, key = event_server["base"], event_server["key"]
    s, _ = http("POST", f"{base}/events.json?accessKey={key}",
                {"event": "buy", "entityType": "user", "entityId": "u1"},
                headers={"X-Request-ID": "cli-rid-1", "X-PIO-Debug": "1"})
    assert s == 201
    assert cli_main(["trace", base]) == 0
    out = capsys.readouterr().out
    assert "cli-rid-1" in out and "kept=debug" in out
    assert cli_main(["trace", base, "--rid", "cli-rid-1"]) == 0
    out = capsys.readouterr().out
    assert "trace cli-rid-1" in out and "/events.json" in out
    assert cli_main(["trace", base, "--slow"]) == 0
    out = capsys.readouterr().out
    assert "trace " in out
