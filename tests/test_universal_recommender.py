"""Universal Recommender template tests: multi-event CCO train, user/item
queries, business rules, blacklist, popularity fallback."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.universal_recommender import (
    UniversalRecommenderEngine,
    URQuery,
)
from predictionio_tpu.models.universal_recommender.engine import (
    URAlgorithmParams,
    URDataSourceParams,
)
from predictionio_tpu.storage import App


@pytest.fixture()
def ur_app(mem_storage):
    """Synthetic 2-cluster commerce data: electronics fans (u0-u14) buy/view
    e-items; book fans (u15-u29) buy/view b-items.  Plus item category
    properties for business-rule tests."""
    app_id = mem_storage.apps.insert(App(0, "urapp"))
    rng = np.random.default_rng(11)
    events = []
    e_items = [f"e{i}" for i in range(6)]
    b_items = [f"b{i}" for i in range(6)]
    for u in range(30):
        mine, other = (e_items, b_items) if u < 15 else (b_items, e_items)
        for it in mine:
            if rng.random() < 0.7:
                events.append(Event(event="purchase", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
            if rng.random() < 0.9:
                events.append(Event(event="view", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
        # a little cross-cluster noise (odd users only, so the even probe
        # users u2/u20 have clean in-cluster histories)
        if u % 2 == 1 and rng.random() < 0.4:
            events.append(Event(event="view", entity_type="user",
                                entity_id=f"u{u}", target_entity_type="item",
                                target_entity_id=other[0]))
    for it in e_items:
        events.append(Event(event="$set", entity_type="item", entity_id=it,
                            properties=DataMap({"category": "electronics"})))
    for it in b_items:
        events.append(Event(event="$set", entity_type="item", entity_id=it,
                            properties=DataMap({"category": "books"})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_ep(**algo_over):
    algo = dict(app_name="urapp", mesh_dp=1, max_correlators_per_item=8,
                min_llr=2.0)
    algo.update(algo_over)
    return EngineParams(
        data_source_params=URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"]
        ),
        algorithm_params_list=[("ur", URAlgorithmParams(**algo))],
    )


@pytest.fixture()
def trained(ur_app):
    engine = UniversalRecommenderEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    return engine, ep, models


def test_user_recs_stay_in_cluster(trained):
    """In-cluster items must dominate: weak cross-cluster associations from
    the noise views are legitimate CCO output, but their scores must be far
    below the in-cluster scores."""
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    for user, prefix in (("u2", "e"), ("u20", "b")):
        res = predict(URQuery(user=user, num=4))
        assert res.item_scores, f"expected recommendations for {user}"
        assert res.item_scores[0].item.startswith(prefix), res.item_scores
        in_cluster = [s.score for s in res.item_scores if s.item.startswith(prefix)]
        out_cluster = [s.score for s in res.item_scores if not s.item.startswith(prefix)]
        assert in_cluster, res.item_scores
        if out_cluster:
            assert max(in_cluster) >= 5 * max(out_cluster), res.item_scores


def test_user_recs_exclude_purchased(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    model = models[0]
    uid = model.user_dict.id("u2")
    purchased = {model.item_dict.str(int(j)) for j in model.user_seen.row(uid)}
    res = predict(URQuery(user="u2", num=6))
    assert purchased.isdisjoint({s.item for s in res.item_scores})


def test_item_similarity_query(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    res = predict(URQuery(item="e1", num=3))
    assert res.item_scores and all(s.item.startswith("e") for s in res.item_scores)
    assert "e1" not in [s.item for s in res.item_scores]  # returnSelf default false


def test_unknown_user_gets_popularity_fallback(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    res = predict(URQuery(user="stranger", num=5))
    assert len(res.item_scores) == 5
    pop = models[0].popularity
    top_pop = models[0].item_dict.str(int(np.argmax(pop)))
    assert res.item_scores[0].item == top_pop


def test_field_filter_and_boost(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    res = predict(URQuery(user="u2", num=6, fields=[
        {"name": "category", "values": ["books"], "bias": -1}]))
    # electronics user hard-filtered to books: only book recs (may be empty
    # but any result must be books)
    assert all(s.item.startswith("b") for s in res.item_scores)
    res2 = predict(URQuery(user="stranger", num=6, fields=[
        {"name": "category", "values": ["books"], "bias": -1}]))
    assert res2.item_scores and all(s.item.startswith("b") for s in res2.item_scores)


def test_blacklist_items(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    # pick any user who has at least one recommendation (a user may have
    # purchased every in-cluster item, leaving nothing above threshold)
    user, base = None, None
    for u in range(30):
        r = predict(URQuery(user=f"u{u}", num=3))
        if r.item_scores:
            user, base = f"u{u}", r
            break
    assert base is not None, "no user with recommendations"
    banned = base.item_scores[0].item
    res = predict(URQuery(user=user, num=3, blacklist_items=[banned]))
    assert banned not in [s.item for s in res.item_scores]


def test_query_json_roundtrip():
    q = URQuery.from_json({
        "user": "u1", "num": 7,
        "fields": [{"name": "category", "values": ["books"], "bias": -1}],
        "blacklistItems": ["i1"],
    })
    assert q.user == "u1" and q.num == 7
    assert q.fields[0].bias == -1 and q.blacklist_items == ["i1"]


def test_ur_mesh_training_matches(ur_app):
    engine = UniversalRecommenderEngine.apply()
    models1 = engine.train(make_ep(mesh_dp=1))
    models8 = engine.train(make_ep(mesh_dp=8, user_block=8))
    m1, m8 = models1[0], models8[0]
    for name in m1.indicator_idx:
        assert (m1.indicator_idx[name] == m8.indicator_idx[name]).all()
        assert np.allclose(m1.indicator_llr[name], m8.indicator_llr[name], atol=1e-3)


def test_date_range_rule(ur_app, mem_storage):
    """dateRange hard-filters items by a date property (reference UR rule)."""
    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.storage import App

    app = mem_storage.apps.get_by_name("urapp")
    # stamp e-items with a releaseDate inside the range, b-items outside
    stamps = []
    for it, date in [(f"e{i}", "2026-06-01T00:00:00") for i in range(6)] + [
                     (f"b{i}", "2020-01-01T00:00:00") for i in range(6)]:
        stamps.append(Event(event="$set", entity_type="item", entity_id=it,
                            properties=DataMap({"releaseDate": date})))
    mem_storage.l_events.insert_batch(stamps, app.id)

    engine = UniversalRecommenderEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    predictor = engine.predictor(ep, models)

    # u20 is a book fan: without the rule the top rec is a b-item
    res = predictor(URQuery.from_json({"user": "u20", "num": 4}))
    assert res.item_scores and res.item_scores[0].item.startswith("b")
    # with a 2026 dateRange, only e-items qualify -> recs empty or e-only
    res = predictor(URQuery.from_json({
        "user": "u20", "num": 4,
        "dateRange": {"name": "releaseDate",
                      "after": "2026-01-01T00:00:00",
                      "before": "2026-12-31T00:00:00"},
    }))
    assert all(s.item.startswith("e") for s in res.item_scores)


def test_available_expire_dates(ur_app, mem_storage):
    """availableDateName/expireDateName vs currentDate (reference UR rule)."""
    from predictionio_tpu.events.event import DataMap, Event

    app = mem_storage.apps.get_by_name("urapp")
    # b0 not yet available; b1 already expired; b2 missing both dates (an ES
    # range filter matches only docs that HAVE the field, so it is excluded
    # too); the rest carry an open validity window
    stamps = [
        Event(event="$set", entity_type="item", entity_id="b0",
              properties=DataMap({"availableDate": "2027-01-01T00:00:00",
                                  "expireDate": "2028-01-01T00:00:00"})),
        Event(event="$set", entity_type="item", entity_id="b1",
              properties=DataMap({"availableDate": "2024-01-01T00:00:00",
                                  "expireDate": "2025-01-01T00:00:00"})),
    ]
    for it in ["b3", "b4", "b5"] + [f"e{i}" for i in range(6)]:
        stamps.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({"availableDate": "2024-01-01T00:00:00",
                                "expireDate": "2028-01-01T00:00:00"})))
    mem_storage.l_events.insert_batch(stamps, app.id)

    engine = UniversalRecommenderEngine.apply()
    ep = make_ep(available_date_name="availableDate",
                 expire_date_name="expireDate")
    models = engine.train(ep)
    predictor = engine.predictor(ep, models)

    res = predictor(URQuery.from_json({
        "user": "u20", "num": 6, "currentDate": "2026-07-29T00:00:00",
    }))
    items = [s.item for s in res.item_scores]
    assert items, "should still recommend items in their validity window"
    assert "b0" not in items and "b1" not in items
    assert "b2" not in items, "items missing the date property are excluded"
    # without currentDate the availability rules are inert
    res2 = predictor(URQuery.from_json({"user": "u20", "num": 6}))
    assert len(res2.item_scores) >= len(items)


def test_date_range_in_range_items_survive(ur_app, mem_storage):
    """The positive half of dateRange: in-range items ARE returned for a
    user with matching signal, and malformed query dates are rejected."""
    from predictionio_tpu.events.event import DataMap, Event

    app = mem_storage.apps.get_by_name("urapp")
    stamps = [Event(event="$set", entity_type="item", entity_id=f"e{i}",
                    properties=DataMap({"releaseDate": "2026-06-01T00:00:00"}))
              for i in range(6)]
    mem_storage.l_events.insert_batch(stamps, app.id)

    engine = UniversalRecommenderEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    predictor = engine.predictor(ep, models)

    # u2 is an electronics fan: e-items are in range and must survive
    res = predictor(URQuery.from_json({
        "user": "u2", "num": 4,
        "dateRange": {"name": "releaseDate", "after": "2026-01-01T00:00:00"},
    }))
    assert res.item_scores and all(s.item.startswith("e") for s in res.item_scores)

    with pytest.raises(ValueError):
        predictor(URQuery.from_json({
            "user": "u2", "num": 4,
            "dateRange": {"name": "releaseDate", "after": "01/2026"},
        }))
    with pytest.raises(ValueError):
        predictor(URQuery.from_json({"user": "u2", "currentDate": "2026/07/29"}))


def test_expire_date_boundary_instant_valid(ur_app, mem_storage):
    """available <= now <= expire: an item expiring exactly at currentDate
    is still recommendable."""
    from predictionio_tpu.events.event import DataMap, Event

    app = mem_storage.apps.get_by_name("urapp")
    mem_storage.l_events.insert(
        Event(event="$set", entity_type="item", entity_id="b2",
              properties=DataMap({"expireDate": "2026-07-29T00:00:00"})), app.id)

    engine = UniversalRecommenderEngine.apply()
    ep = make_ep(expire_date_name="expireDate")
    models = engine.train(ep)
    predictor = engine.predictor(ep, models)

    at_boundary = predictor(URQuery.from_json({
        "user": "u20", "num": 8, "currentDate": "2026-07-29T00:00:00"}))
    past_boundary = predictor(URQuery.from_json({
        "user": "u20", "num": 8, "currentDate": "2026-07-29T00:00:01"}))
    assert "b2" in [s.item for s in at_boundary.item_scores]
    assert "b2" not in [s.item for s in past_boundary.item_scores]


def test_serving_warm_stages_resolved_scorer(trained, monkeypatch):
    """predictor() pre-stages what the RESOLVED scorer reads (warm):
    device mode stages the indicator tables; host mode builds the CSR
    inversions instead (the other side stays lazy).  Caches are held on
    the model and reused across queries — predict never rebuilds them."""
    import pickle

    engine, ep, models = trained
    model = models[0]
    assert "_dev_indicators" not in model.__dict__
    assert "_host_inv" not in model.__dict__

    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "device")
    predict = engine.predictor(ep, models)
    assert "_dev_indicators" in model.__dict__, "warm must stage tables"
    assert "_host_inv" not in model.__dict__, "host side must stay lazy"
    dev1 = model.device_indicators()
    predict(URQuery(user="u2", num=4))
    assert model.device_indicators() is dev1, "device cache must be stable"

    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    m2 = pickle.loads(pickle.dumps(model))
    # the caches never ride the pickle: a reloaded model re-stages lazily
    assert "_dev_indicators" not in m2.__dict__
    engine.predictor(ep, [m2])
    assert "_host_inv" in m2.__dict__, "host warm must build inversions"
    assert "_dev_indicators" not in m2.__dict__, \
        "device tables must stay lazy under the host scorer"


def test_item_similarity_uses_all_indicators(trained):
    """Item queries score with the item's indicator lists across EVERY event
    type (reference getBiasedSimilarItems), not just the primary."""
    engine, ep, models = trained
    model = models[0]
    predict = engine.predictor(ep, models)
    res = predict(URQuery(item="e1", num=5))
    assert res.item_scores and all(s.item.startswith("e") for s in res.item_scores)
    # the secondary (view) indicator alone must produce item-similarity
    # signal: score e1's virtual history restricted to the view field only —
    # a primary-only implementation would return nothing here
    from predictionio_tpu.models.universal_recommender.engine import URAlgorithm

    algo = next(a for a in [URAlgorithm(ep.algorithm_params_list[0][1])])
    iid = model.item_dict.id("e1")
    view_row = model.indicator_idx["view"][iid]
    view_ids = view_row[view_row >= 0].astype("int32")
    assert len(view_ids), "fixture should give e1 view correlators"
    s_view = algo._score_history(model, {"view": view_ids})
    assert s_view is not None and (s_view > 0).any(), \
        "view-only virtual history must score items"
    # and the combined item-query score reflects more than the primary field
    s_primary_only = algo._score_history(
        model, {"purchase": model.indicator_idx["purchase"][iid][
            model.indicator_idx["purchase"][iid] >= 0].astype("int32")})
    full = predict(URQuery(item="e1", num=5, return_self=True))
    top_full = max(s.score for s in full.item_scores)
    base = float(s_primary_only.max()) if s_primary_only is not None else 0.0
    assert top_full > base, "multi-indicator score must exceed primary-only"


# -- PopModel backfill family (trending / hot / padding) ---------------------


def _pop_app(mem_storage, app_name="popapp"):
    """Time-shaped purchase log: 'old' is popular long ago, 'rising' ramps
    up inside the recent window, 'steady' is flat."""
    import datetime as dt

    app_id = mem_storage.apps.insert(App(0, app_name))
    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    day = dt.timedelta(days=1)
    events = []

    def buy(u, item, when):
        events.append(Event(event="purchase", entity_type="user", entity_id=u,
                            target_entity_type="item", target_entity_id=item,
                            event_time=when))

    # 30-day log. "old": 20 buys in days 0-9, none after.
    for k in range(20):
        buy(f"o{k}", "old", t0 + day * (k % 10))
    # "rising": 12 buys, all in days 24-29 (accelerating).
    for k in range(12):
        buy(f"r{k}", "rising", t0 + day * (24 + (k % 6)))
    # "steady": one buy per day, days 0-29.
    for k in range(30):
        buy(f"s{k}", "steady", t0 + day * k)
    # one shared user giving CCO something to chew on (not under test here)
    for it in ("old", "rising", "steady"):
        buy("shared", it, t0 + day * 15)
    mem_storage.l_events.insert_batch(events, app_id)
    return app_name


def _pop_ep(app_name, **algo_over):
    algo = dict(app_name=app_name, mesh_dp=1)
    algo.update(algo_over)
    return EngineParams(
        data_source_params=URDataSourceParams(
            app_name=app_name, event_names=["purchase"]),
        algorithm_params_list=[("ur", URAlgorithmParams(**algo))],
    )


def _backfill_order(mem_storage, backfill_type, duration):
    app = _pop_app(mem_storage)
    engine = UniversalRecommenderEngine.apply()
    ep = _pop_ep(app, backfill_type=backfill_type, backfill_duration=duration)
    models = engine.train(ep)
    res = engine.predictor(ep, models)(URQuery(user="cold-user", num=3))
    return [s.item for s in res.item_scores]


def test_popular_backfill_counts_window(mem_storage):
    # whole log: old(21) > steady(31)? old=21, steady=31, rising=13
    order = _backfill_order(mem_storage, "popular", "3650 days")
    assert order[0] == "steady" and set(order) == {"old", "rising", "steady"}


def test_trending_backfill_prefers_velocity(mem_storage):
    # 30-day window halves: rising has all events in the recent half →
    # highest velocity; old has everything in the older half → negative
    order = _backfill_order(mem_storage, "trending", "30 days")
    assert order[0] == "rising"
    assert order[-1] == "old"


def test_hot_backfill_prefers_acceleration(mem_storage):
    order = _backfill_order(mem_storage, "hot", "30 days")
    assert order[0] == "rising"


def test_backfill_type_none_returns_empty_for_cold_user(mem_storage):
    order = _backfill_order(mem_storage, "none", "30 days")
    assert order == []


def test_bad_backfill_params_fail_loudly(mem_storage):
    app = _pop_app(mem_storage, "popapp2")
    engine = UniversalRecommenderEngine.apply()
    with pytest.raises(ValueError):
        engine.train(_pop_ep(app, backfill_type="voguish"))
    with pytest.raises(ValueError):
        engine.train(_pop_ep(app, backfill_duration="three fortnights"))


def test_backfill_pads_short_result_lists(trained):
    """A user with real signal still gets `num` items: signal first, then
    popularity-ranked backfill (reference UR fills up to num)."""
    engine, ep, models = trained
    res = engine.predictor(ep, models)(URQuery(user="u2", num=8))
    # u2 has 12 catalog items minus their own purchases (blacklisted), so 8
    # are servable; signal alone yields far fewer — backfill pads to num
    assert len(res.item_scores) == 8
    # signal items (score > padding) come first; padding afterwards
    scores = [s.score for s in res.item_scores]
    n_signal = sum(1 for s in scores if s > 1.0)
    assert n_signal >= 1
    # padded tail respects the primary-event blacklist: u2's purchases
    # never appear even as padding
    from predictionio_tpu.store.event_store import LEventStore

    bought = {e.target_entity_id for e in LEventStore.find_by_entity(
        "urapp", "user", "u2", event_names=["purchase"])}
    assert bought and not (bought & {s.item for s in res.item_scores})


def test_non_primary_blacklist_events(ur_app):
    """blacklist_events: ['purchase', 'view'] removes viewed-but-never-
    bought items too (the round-2 gap: non-primary names were silently
    ignored)."""
    engine = UniversalRecommenderEngine.apply()
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="urapp", mesh_dp=1, max_correlators_per_item=8,
            blacklist_events=["purchase", "view"]))],
    )
    models = engine.train(ep)
    from predictionio_tpu.store.event_store import LEventStore

    seen = set()
    for name in ("purchase", "view"):
        seen |= {e.target_entity_id for e in LEventStore.find_by_entity(
            "urapp", "user", "u2", event_names=[name])}
    res = engine.predictor(ep, models)(URQuery(user="u2", num=12))
    assert seen and not (seen & {s.item for s in res.item_scores})


def test_unknown_blacklist_event_rejected(ur_app):
    engine = UniversalRecommenderEngine.apply()
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="urapp", mesh_dp=1, blacklist_events=["add-to-cart"]))],
    )
    with pytest.raises(ValueError, match="blacklist_events"):
        engine.train(ep)


def test_parse_duration_units():
    from predictionio_tpu.models.universal_recommender.popmodel import parse_duration

    assert parse_duration("90 days") == 90 * 86400
    assert parse_duration("12 hours") == 12 * 3600
    assert parse_duration("45") == 45
    assert parse_duration("2 weeks") == 2 * 604800
    with pytest.raises(ValueError):
        parse_duration("soon")


def test_field_boost_reorders_backfill(trained):
    """A cold user's popularity fallback is reordered by field boosts, like
    the reference's ES boost on the popRank-backed query."""
    engine, ep, models = trained
    pred = engine.predictor(ep, models)
    plain = pred(URQuery(user="cold", num=12))
    boosted = pred(URQuery(user="cold", num=12, fields=[
        {"name": "category", "values": ["books"], "bias": 50.0}]))
    assert len(boosted.item_scores) == len(plain.item_scores) > 0
    top6 = {s.item for s in boosted.item_scores[:6]}
    assert all(i.startswith("b") for i in top6), top6


def test_unknown_property_names_match_nothing(trained):
    """Field/date rules naming properties no item has match NO documents
    (ES semantics) and never build per-name caches from query input."""
    engine, ep, models = trained
    pred = engine.predictor(ep, models)
    res = pred(URQuery(user="u2", num=5, fields=[
        {"name": "no-such-prop", "values": ["x"], "bias": -1}]))
    assert res.item_scores == []
    res2 = pred(URQuery(user="u2", num=5,
                        date_range={"name": "not-a-date", "after": "2020-01-01"}))
    assert res2.item_scores == []
    model = models[0]
    assert not model.__dict__.get("_dev_date")
    assert ("no-such-prop", "x") not in (model.__dict__.get("_dev_value_mask") or {})


def test_item_set_query(trained):
    """itemSet (cart) queries: union of the set's indicators drives the
    scores; the set's own items never come back (returnSelf default)."""
    engine, ep, models = trained
    pred = engine.predictor(ep, models)
    res = pred(URQuery(item_set=["e1", "e3"], num=4))
    assert res.item_scores, "cart query returned nothing"
    got = {s.item for s in res.item_scores}
    assert got.isdisjoint({"e1", "e3"})
    assert all(i.startswith("e") for i in got), got
    # wire-format binding
    q = URQuery.from_json({"itemSet": ["e1", "e3"], "num": 4})
    assert q.item_set == ["e1", "e3"]
    res2 = pred(q)
    assert {s.item for s in res2.item_scores} == got


def test_per_indicator_overrides(ur_app):
    """indicator_params tunes top-k/minLLR per event type (reference UR's
    per-indicator config); unknown names fail loudly."""
    engine = UniversalRecommenderEngine.apply()
    models = engine.train(make_ep(indicator_params={
        "view": {"maxCorrelatorsPerItem": 3, "minLLR": 0.0}}))
    m = models[0]
    assert m.indicator_idx["view"].shape[1] == 3
    assert m.indicator_idx["purchase"].shape[1] == 8  # base param
    with pytest.raises(ValueError, match="indicator_params"):
        engine.train(make_ep(indicator_params={"nope": {"minLLR": 1.0}}))
    # repo-convention camelCase spelling binds too
    m2 = engine.train(make_ep(indicator_params={
        "view": {"maxCorrelatorsPerItem": 2, "minLlr": 0.0}}))[0]
    assert m2.indicator_idx["view"].shape[1] == 2
    # unknown override keys fail loudly instead of silently doing nothing
    with pytest.raises(ValueError, match="unknown key"):
        engine.train(make_ep(indicator_params={"view": {"topK": 5}}))


def test_ur_checkpoint_resume_after_injected_fault(ur_app, tmp_path, monkeypatch):
    """UR training with per-event-type snapshots: a fault on the SECOND
    event type leaves the first type's snapshot; the retry resumes past it
    and the final model equals an un-faulted train."""
    from predictionio_tpu.utils.checkpoint import InjectedFault

    engine = UniversalRecommenderEngine.apply()
    ref = engine.train(make_ep())[0]

    ckdir = str(tmp_path / "ck")
    ep = make_ep(checkpoint=True, checkpoint_dir=ckdir)
    monkeypatch.setenv("PIO_FAULT_INJECT", "ur.indicators:2")
    with pytest.raises(InjectedFault):
        engine.train(ep)
    monkeypatch.delenv("PIO_FAULT_INJECT", raising=False)  # maybe_inject disarms
    # snapshot of the first event type survived the crash
    import pathlib

    assert any(pathlib.Path(ckdir).rglob("step_0.npz"))
    model = engine.train(ep)[0]
    for name in ref.indicator_idx:
        np.testing.assert_array_equal(
            model.indicator_idx[name], ref.indicator_idx[name])
        np.testing.assert_allclose(
            model.indicator_llr[name], ref.indicator_llr[name], rtol=1e-5)


def test_backfill_event_names_widen_popularity(ur_app):
    """backfill_event_names counts the named event types' volume
    (translated into the primary item space); unknown names fail loudly."""
    engine = UniversalRecommenderEngine.apply()
    m_primary = engine.train(make_ep())[0]
    m_views = engine.train(make_ep(
        backfill_event_names=["purchase", "view"]))[0]
    # views add volume: totals strictly grow somewhere
    assert m_views.popularity.sum() > m_primary.popularity.sum()
    assert len(m_views.popularity) == len(m_primary.popularity)
    with pytest.raises(ValueError, match="backfill_event_names"):
        engine.train(make_ep(backfill_event_names=["nope"]))


def test_ur_model_pickle_roundtrip(ur_app):
    """Model blobs survive persistence: every serving-relevant field —
    indicator tables, per-event blacklist CSRs, popularity, properties —
    round-trips, and the reloaded model serves identical results."""
    import pickle

    engine = UniversalRecommenderEngine.apply()
    ep = make_ep(blacklist_events=["purchase", "view"])
    models = engine.train(ep)
    m = models[0]
    m2 = pickle.loads(pickle.dumps(m))
    assert m2.primary_event == m.primary_event
    assert set(m2.indicator_idx) == set(m.indicator_idx)
    for name in m.indicator_idx:
        np.testing.assert_array_equal(m2.indicator_idx[name], m.indicator_idx[name])
        np.testing.assert_allclose(m2.indicator_llr[name], m.indicator_llr[name])
    np.testing.assert_allclose(m2.popularity, m.popularity)
    assert set(m2.user_seen_by_event) == set(m.user_seen_by_event)
    for k, csr in m.user_seen_by_event.items():
        np.testing.assert_array_equal(m2.user_seen_by_event[k].values, csr.values)
    assert m2.item_properties == m.item_properties
    p1 = engine.predictor(ep, models)
    p2 = engine.predictor(ep, [m2])
    for q in (URQuery(user="u2", num=6), URQuery(item="e1", num=4),
              URQuery(user="cold", num=5)):
        r1 = [(s.item, round(s.score, 5)) for s in p1(q).item_scores]
        r2 = [(s.item, round(s.score, 5)) for s in p2(q).item_scores]
        assert r1 == r2, (q, r1, r2)


def test_ur_offline_eval_hit_rate(ur_app):
    """`pio eval` for the flagship: leave-one-out holdout, training-history
    predictions (no leakage from the live store), hit@num well above the
    random baseline on the clustered data."""
    from predictionio_tpu.controller.evaluation import MetricEvaluator
    from predictionio_tpu.models.universal_recommender.engine import HitRateMetric

    engine = UniversalRecommenderEngine.apply()
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"],
            eval_users=25, eval_num=4),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="urapp", mesh_dp=1, max_correlators_per_item=8,
            min_llr=0.0))],
    )
    result = MetricEvaluator(HitRateMetric()).evaluate(engine, [ep])
    # 4 of 11 eligible items at random ≈ 0.36; CCO must beat chance (the
    # tiny dense catalog caps how far above it can get: most in-cluster
    # items are already blacklisted as seen)
    assert result.best_score > 0.40, result.best_score
    # eval disabled -> no folds
    ep0 = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="urapp", mesh_dp=1))],
    )
    assert engine.eval(ep0) == []


def test_ur_eval_holdout_is_sampled_not_first_n(ur_app):
    """When eval_users caps the fold, holdout users are a seeded random
    sample over ALL qualifying users — not the first N in array order
    (stores are commonly sorted by entity id, which would order-bias a
    grid search)."""
    from predictionio_tpu.models.universal_recommender.engine import (
        URDataSource,
    )

    def users(seed):
        ds = URDataSource(URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"],
            eval_users=5, eval_num=4, eval_seed=seed))
        folds = ds.read_eval()
        assert len(folds) == 1
        _, _, qa = folds[0]
        assert len(qa) == 5
        return [q.user for q, _ in qa]

    all_ds = URDataSource(URDataSourceParams(
        app_name="urapp", event_names=["purchase", "view"],
        eval_users=10_000, eval_num=4))
    qualifying = {q.user for q, _ in all_ds.read_eval()[0][2]}

    s0a, s0b, s1 = users(0), users(0), users(1)
    assert s0a == s0b                      # same seed -> deterministic
    assert s0a != s1                       # different seed -> different sample
    assert set(s0a) <= qualifying and set(s1) <= qualifying
    # not simply the first five qualifying users in store order
    first_n = sorted(qualifying, key=lambda u: int(u[1:]))[:5]
    assert set(s0a) != set(first_n) or set(s1) != set(first_n)


def test_rank_metrics_family():
    """NDCG / precision@k / MRR over the leave-one-out protocol."""
    import math

    from predictionio_tpu.models.universal_recommender.engine import (
        HitRateMetric,
        ItemScore,
        MRRMetric,
        NDCGMetric,
        PrecisionAtKMetric,
        URResult,
    )

    def res(*items):
        return URResult([ItemScore(i, 1.0) for i in items])

    # actual at rank 0, rank 2, and missing
    data = [({}, [
        (None, res("a", "b", "c"), "a"),
        (None, res("x", "y", "z"), "z"),
        (None, res("p", "q"), "missing"),
    ])]
    assert abs(HitRateMetric().calculate(data) - 2 / 3) < 1e-9
    expected_ndcg = (1.0 + 1.0 / math.log2(4) + 0.0) / 3
    assert abs(NDCGMetric().calculate(data) - expected_ndcg) < 1e-9
    assert abs(MRRMetric().calculate(data) - (1.0 + 1 / 3) / 3) < 1e-9
    p2 = PrecisionAtKMetric(2)
    assert p2.header() == "Precision@2"
    # rank 0 counts, rank 2 does not, miss does not -> (1/2) / 3
    assert abs(p2.calculate(data) - (0.5) / 3) < 1e-9


@pytest.mark.parametrize("scorer", ["host", "device"])
def test_ur_serve_batch_matches_serial(ur_app, monkeypatch, scorer):
    """serve_batch_predict ≡ predict across every query shape in one
    batch: user, cold user, item-similarity, itemSet, business rules,
    blacklist — live-store semantics, one batched readback.  Runs under
    BOTH scorers (auto would pick host on the CPU test backend, leaving
    the TPU device batch branch uncovered)."""
    from predictionio_tpu.models.universal_recommender.engine import (
        FieldRule,
        URAlgorithm,
    )

    monkeypatch.setenv("PIO_UR_SERVE_SCORER", scorer)
    engine = UniversalRecommenderEngine.apply()
    ep = make_ep(min_llr=0.0)
    models = engine.train(ep)
    model = models[0]
    algo = URAlgorithm(dict(ep.algorithm_params_list)["ur"])
    queries = [
        URQuery(user="u2", num=5),
        URQuery(user="cold-user", num=4),
        URQuery(item="e1", num=4),
        URQuery(item_set=["e0", "e2"], num=6),
        URQuery(user="u20", num=5,
                fields=[FieldRule(name="category", values=["books"], bias=-1)]),
        URQuery(user="u3", num=3, blacklist_items=["e0", "e1"]),
        URQuery(user="u21", num=7),
    ]
    serial = [algo.predict(model, q) for q in queries]
    batched = algo.serve_batch_predict(model, queries)
    assert len(batched) == len(queries)
    for q, s, b in zip(queries, serial, batched):
        s_items = [(r.item, round(r.score, 4)) for r in s.item_scores]
        b_items = [(r.item, round(r.score, 4)) for r in b.item_scores]
        assert s_items == b_items, (q, s_items, b_items)


def test_host_scorer_matches_device_scorer(trained, monkeypatch):
    """The inverted-index host scorer must produce the same signal (and
    the same recommendations) as the device gather program for identical
    queries — only float32 addition order may differ."""
    import numpy as np

    engine, ep, models = trained
    queries = [URQuery(user=u, num=6) for u in ("u2", "u9", "u20", "u27")]

    def run():
        predict = engine.predictor(ep, models)
        return [predict(q) for q in queries]

    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "device")
    dev = run()
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    host = run()
    for d, h in zip(dev, host):
        # f32 addition order differs between scorers, so near-equal
        # scores may legitimately swap rank: compare the item SETS and
        # the sorted score vectors, not the exact ordering
        assert {s.item for s in d.item_scores} == \
            {s.item for s in h.item_scores}
        np.testing.assert_allclose(
            sorted(s.score for s in d.item_scores),
            sorted(s.score for s in h.item_scores), rtol=1e-5)

    # the raw signal too, on the algorithm directly
    from predictionio_tpu.models.universal_recommender.engine import URAlgorithm
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    hist = algo._user_history(model, "u2")
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "device")
    s_dev = np.asarray(algo._score_history(model, hist))
    s_host = algo._sparse_signal_dense(
        len(model.item_dict), algo._score_history_host(model, hist))
    np.testing.assert_allclose(s_dev, s_host, rtol=1e-5, atol=1e-6)


def test_host_scorer_edge_cases(trained, monkeypatch):
    """Host scorer handles: an all-padding indicator table (no
    correlators -> zero signal), out-of-range history ids (skipped), and
    an empty history (None)."""
    import numpy as np

    from predictionio_tpu.models.universal_recommender.engine import URAlgorithm

    engine, ep, models = trained
    model = models[0]
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")

    n_items = len(model.item_dict)
    assert algo._score_history_host(model, {}) is None
    some = next(iter(model.indicator_idx))
    # out-of-range ids are skipped, not crashed on
    s = algo._sparse_signal_dense(n_items, algo._score_history_host(
        model, {some: np.asarray([10**6, -5], np.int32)}))
    assert s is None or not s.any()

    # an event type whose table is all -1 contributes nothing
    blank = {k: np.full_like(v, -1) for k, v in model.indicator_idx.items()}
    monkeypatch.setattr(model, "indicator_idx", blank)
    model.__dict__.pop("_host_inv", None)   # rebuild inversion
    hist = {some: np.asarray([0, 1], np.int32)}
    s = algo._sparse_signal_dense(
        n_items, algo._score_history_host(model, hist))
    assert s is not None and not s.any()


def test_host_inverted_degenerate_table_returns_empty_csr(trained):
    """ADVICE r5: a non-2D indicator table (degenerate/empty training
    shard) must yield an EMPTY CSR inversion, not the broken
    arange(0)/boolean-index fallback that IndexError'd on any non-empty
    non-2D input."""
    _, _, models = trained
    model = models[0]
    name = next(iter(model.indicator_idx))
    model.__dict__.pop("_host_inv", None)
    orig = model.indicator_idx
    try:
        model.indicator_idx = dict(orig)
        # 1-D non-empty table: the exact shape the old guard crashed on
        model.indicator_idx[name] = np.asarray([1, 2, 3], np.int32)
        indptr, rows, w = model.host_inverted(name)
        n_t = max(len(model.event_item_dicts[name]), 1)
        assert indptr.shape == (n_t + 1,) and (indptr == 0).all()
        assert rows.size == 0 and w.size == 0
    finally:
        model.indicator_idx = orig
        model.__dict__.pop("_host_inv", None)
