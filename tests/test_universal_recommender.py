"""Universal Recommender template tests: multi-event CCO train, user/item
queries, business rules, blacklist, popularity fallback."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.universal_recommender import (
    UniversalRecommenderEngine,
    URQuery,
)
from predictionio_tpu.models.universal_recommender.engine import (
    URAlgorithmParams,
    URDataSourceParams,
)
from predictionio_tpu.storage import App


@pytest.fixture()
def ur_app(mem_storage):
    """Synthetic 2-cluster commerce data: electronics fans (u0-u14) buy/view
    e-items; book fans (u15-u29) buy/view b-items.  Plus item category
    properties for business-rule tests."""
    app_id = mem_storage.apps.insert(App(0, "urapp"))
    rng = np.random.default_rng(11)
    events = []
    e_items = [f"e{i}" for i in range(6)]
    b_items = [f"b{i}" for i in range(6)]
    for u in range(30):
        mine, other = (e_items, b_items) if u < 15 else (b_items, e_items)
        for it in mine:
            if rng.random() < 0.7:
                events.append(Event(event="purchase", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
            if rng.random() < 0.9:
                events.append(Event(event="view", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
        # a little cross-cluster noise (odd users only, so the even probe
        # users u2/u20 have clean in-cluster histories)
        if u % 2 == 1 and rng.random() < 0.4:
            events.append(Event(event="view", entity_type="user",
                                entity_id=f"u{u}", target_entity_type="item",
                                target_entity_id=other[0]))
    for it in e_items:
        events.append(Event(event="$set", entity_type="item", entity_id=it,
                            properties=DataMap({"category": "electronics"})))
    for it in b_items:
        events.append(Event(event="$set", entity_type="item", entity_id=it,
                            properties=DataMap({"category": "books"})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_ep(**algo_over):
    algo = dict(app_name="urapp", mesh_dp=1, max_correlators_per_item=8,
                min_llr=2.0)
    algo.update(algo_over)
    return EngineParams(
        data_source_params=URDataSourceParams(
            app_name="urapp", event_names=["purchase", "view"]
        ),
        algorithm_params_list=[("ur", URAlgorithmParams(**algo))],
    )


@pytest.fixture()
def trained(ur_app):
    engine = UniversalRecommenderEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    return engine, ep, models


def test_user_recs_stay_in_cluster(trained):
    """In-cluster items must dominate: weak cross-cluster associations from
    the noise views are legitimate CCO output, but their scores must be far
    below the in-cluster scores."""
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    for user, prefix in (("u2", "e"), ("u20", "b")):
        res = predict(URQuery(user=user, num=4))
        assert res.item_scores, f"expected recommendations for {user}"
        assert res.item_scores[0].item.startswith(prefix), res.item_scores
        in_cluster = [s.score for s in res.item_scores if s.item.startswith(prefix)]
        out_cluster = [s.score for s in res.item_scores if not s.item.startswith(prefix)]
        assert in_cluster, res.item_scores
        if out_cluster:
            assert max(in_cluster) >= 5 * max(out_cluster), res.item_scores


def test_user_recs_exclude_purchased(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    model = models[0]
    uid = model.user_dict.id("u2")
    purchased = {model.item_dict.str(int(j)) for j in model.user_seen.get(uid, [])}
    res = predict(URQuery(user="u2", num=6))
    assert purchased.isdisjoint({s.item for s in res.item_scores})


def test_item_similarity_query(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    res = predict(URQuery(item="e1", num=3))
    assert res.item_scores and all(s.item.startswith("e") for s in res.item_scores)
    assert "e1" not in [s.item for s in res.item_scores]  # returnSelf default false


def test_unknown_user_gets_popularity_fallback(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    res = predict(URQuery(user="stranger", num=5))
    assert len(res.item_scores) == 5
    pop = models[0].popularity
    top_pop = models[0].item_dict.str(int(np.argmax(pop)))
    assert res.item_scores[0].item == top_pop


def test_field_filter_and_boost(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    res = predict(URQuery(user="u2", num=6, fields=[
        {"name": "category", "values": ["books"], "bias": -1}]))
    # electronics user hard-filtered to books: only book recs (may be empty
    # but any result must be books)
    assert all(s.item.startswith("b") for s in res.item_scores)
    res2 = predict(URQuery(user="stranger", num=6, fields=[
        {"name": "category", "values": ["books"], "bias": -1}]))
    assert res2.item_scores and all(s.item.startswith("b") for s in res2.item_scores)


def test_blacklist_items(trained):
    engine, ep, models = trained
    predict = engine.predictor(ep, models)
    # pick any user who has at least one recommendation (a user may have
    # purchased every in-cluster item, leaving nothing above threshold)
    user, base = None, None
    for u in range(30):
        r = predict(URQuery(user=f"u{u}", num=3))
        if r.item_scores:
            user, base = f"u{u}", r
            break
    assert base is not None, "no user with recommendations"
    banned = base.item_scores[0].item
    res = predict(URQuery(user=user, num=3, blacklist_items=[banned]))
    assert banned not in [s.item for s in res.item_scores]


def test_query_json_roundtrip():
    q = URQuery.from_json({
        "user": "u1", "num": 7,
        "fields": [{"name": "category", "values": ["books"], "bias": -1}],
        "blacklistItems": ["i1"],
    })
    assert q.user == "u1" and q.num == 7
    assert q.fields[0].bias == -1 and q.blacklist_items == ["i1"]


def test_ur_mesh_training_matches(ur_app):
    engine = UniversalRecommenderEngine.apply()
    models1 = engine.train(make_ep(mesh_dp=1))
    models8 = engine.train(make_ep(mesh_dp=8, user_block=8))
    m1, m8 = models1[0], models8[0]
    for name in m1.indicator_idx:
        assert (m1.indicator_idx[name] == m8.indicator_idx[name]).all()
        assert np.allclose(m1.indicator_llr[name], m8.indicator_llr[name], atol=1e-3)
