"""Aux-subsystem tests: webhooks, plugins, SDK clients, pio-env loader,
tracing helpers."""

import json
import logging
import urllib.request

import pytest

from predictionio_tpu.api.event_server import run_event_server
from predictionio_tpu.storage import AccessKey, App


@pytest.fixture()
def server(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "auxapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    yield {"base": f"http://127.0.0.1:{httpd.server_address[1]}", "key": key,
           "app_id": app_id, "storage": mem_storage}
    httpd.shutdown()
    httpd.server_close()


def post(url, body):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_segmentio_webhook(server):
    base, key = server["base"], server["key"]
    status, body = post(f"{base}/webhooks/segmentio.json?accessKey={key}", {
        "type": "track", "userId": "u99", "event": "Item Purchased",
        "properties": {"revenue": 39.95},
        "timestamp": "2026-02-01T12:00:00Z",
    })
    assert status == 201, body
    ev = next(iter(server["storage"].l_events.find(server["app_id"])))
    assert ev.event == "Item Purchased" and ev.entity_id == "u99"
    assert ev.properties["revenue"] == 39.95


def test_webhook_unknown_connector_and_bad_payload(server):
    base, key = server["base"], server["key"]
    status, _ = post(f"{base}/webhooks/nope.json?accessKey={key}", {"a": 1})
    assert status == 404
    status, _ = post(f"{base}/webhooks/segmentio.json?accessKey={key}", {"type": "track"})
    assert status == 400


def test_form_webhook(server):
    base, key = server["base"], server["key"]
    status, _ = post(f"{base}/webhooks/form.json?accessKey={key}", {
        "event": "buy", "entityType": "user", "entityId": "u5",
        "targetEntityType": "item", "targetEntityId": "i5", "price": 3})
    assert status == 201
    evs = list(server["storage"].l_events.find(server["app_id"], event_names=["buy"]))
    assert evs and evs[0].properties["price"] == 3


def test_mailchimp_webhook(server):
    base, key = server["base"], server["key"]
    # nested data form (JSON re-post)
    status, _ = post(f"{base}/webhooks/mailchimp.json?accessKey={key}", {
        "type": "subscribe", "fired_at": "2026-02-01 12:00:00",
        "data": {"email": "a@example.com", "list_id": "L1"}})
    assert status == 201
    # flattened data[...] form fields (MailChimp's native shape)
    status, _ = post(f"{base}/webhooks/mailchimp.json?accessKey={key}", {
        "type": "unsubscribe", "data[email]": "a@example.com",
        "data[reason]": "manual"})
    assert status == 201
    evs = {e.event: e for e in server["storage"].l_events.find(server["app_id"])}
    sub = evs["subscribe"]
    assert sub.entity_id == "a@example.com"
    assert sub.properties["list_id"] == "L1"
    assert sub.event_time.isoformat().startswith("2026-02-01T12:00:00")
    assert evs["unsubscribe"].properties["reason"] == "manual"
    # unsupported type and missing member key are 400s
    status, _ = post(f"{base}/webhooks/mailchimp.json?accessKey={key}",
                     {"type": "bogus"})
    assert status == 400
    status, _ = post(f"{base}/webhooks/mailchimp.json?accessKey={key}",
                     {"type": "cleaned", "data": {}})
    assert status == 400


def test_register_custom_connector(server):
    """The documented extension point: one function, one register call."""
    from predictionio_tpu.api.webhooks import register_connector
    from predictionio_tpu.events.event import Event

    def my_connector(payload):
        return Event(event=payload["action"], entity_type="user",
                     entity_id=str(payload["uid"]))

    register_connector("mysystem", my_connector)
    base, key = server["base"], server["key"]
    status, _ = post(f"{base}/webhooks/mysystem.json?accessKey={key}",
                     {"action": "signup", "uid": 7})
    assert status == 201
    evs = list(server["storage"].l_events.find(
        server["app_id"], event_names=["signup"]))
    assert evs and evs[0].entity_id == "7"


def test_plugins_blocker_and_sniffer():
    from predictionio_tpu.api.plugins import (
        OutputBlocker, OutputSniffer, PluginRegistry,
    )

    seen = []

    class Cap(OutputBlocker):
        name = "cap"

        def process(self, query, prediction):
            return min(prediction, 10)

    class Sniff(OutputSniffer):
        name = "sniff"

        def process(self, query, prediction):
            seen.append((query, prediction))

    class Broken(OutputSniffer):
        name = "broken"

        def process(self, query, prediction):
            raise RuntimeError("boom")

    reg = PluginRegistry()
    reg.register(Cap())
    reg.register(Sniff())
    reg.register(Broken())
    out = reg.apply("q", 42)
    assert out == 10          # blocker transformed
    assert seen == [("q", 10)]  # sniffer saw transformed value; broken one ignored


def test_sdk_event_client(server):
    from predictionio_tpu.sdk import EventClient

    c = EventClient(server["key"], server["base"])
    eid = c.record_user_action_on_item("rate", "u1", "i1", {"rating": 4})
    got = c.get_event(eid)
    assert got["event"] == "rate" and got["properties"]["rating"] == 4
    c.set_user("u1", {"plan": "pro"})
    results = c.create_events([
        {"event": "view", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i2"},
    ])
    assert results[0]["status"] == 201
    found = c.find_events(event="view")
    assert len(found) == 1
    c.delete_event(eid)
    from predictionio_tpu.sdk.client import PIOError

    with pytest.raises(PIOError) as ei:
        c.get_event(eid)
    assert ei.value.status == 404


def test_load_pio_env(tmp_path, monkeypatch):
    from predictionio_tpu.utils.config import load_pio_env

    f = tmp_path / "pio-env.sh"
    f.write_text(
        "# storage config\n"
        "export PIO_STORAGE_SOURCES_FS_TYPE=localfs\n"
        'PIO_STORAGE_SOURCES_FS_PATH="$BASE/store"\n'
        "export PIO_STORAGE_REPOSITORIES_METADATA_SOURCE=FS\n"
        "ignored line without assignment\n"
    )
    out = load_pio_env(str(f), apply=False, base={"BASE": "/data"})
    assert out["PIO_STORAGE_SOURCES_FS_TYPE"] == "localfs"
    assert out["PIO_STORAGE_SOURCES_FS_PATH"] == "/data/store"
    assert len(out) == 3
    assert load_pio_env("/nonexistent/pio-env.sh", apply=False) == {}


def test_timed_tracer():
    from predictionio_tpu.utils.tracing import timed

    sink = {}
    with timed("span", sink):
        pass
    assert "span" in sink and sink["span"] >= 0


def test_persistent_compilation_cache_config(tmp_path, monkeypatch):
    import jax
    from jax._src import compilation_cache as _cc

    from predictionio_tpu.utils.config import enable_compilation_cache

    loc = str(tmp_path / "xla_cache")
    monkeypatch.setenv("PIO_JAX_CACHE", loc)
    enable_compilation_cache()
    import os

    assert os.path.isdir(loc)
    assert jax.config.jax_compilation_cache_dir == loc
    # a fresh-process compile lands in the cache (threshold forced to 0
    # for the test; production keeps >=1s programs only)
    saved_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        import numpy as np

        import jax.numpy as jnp

        # jax pins the persistent-cache singleton to the dir in effect at
        # the FIRST in-process compile; reset it so this test's dir takes
        # (otherwise the test is order-sensitive: any earlier compile —
        # e.g. a deploy test — pins the default dir and nothing lands
        # here)
        _cc.reset_cache()
        # and a never-before-compiled program, so the in-memory executable
        # cache can't satisfy it without touching disk
        c = float(np.random.default_rng().uniform(2.0, 3.0))

        @jax.jit
        def f(x):
            return (x @ x * c).sum()

        np.asarray(f(jnp.ones((63, 63))))
        assert len(os.listdir(loc)) >= 1
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", saved_min)
        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()   # unpin our tmp dir for later tests


def test_compilation_cache_off_switch(tmp_path, monkeypatch):
    import jax

    from predictionio_tpu.utils.config import enable_compilation_cache

    before = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("PIO_JAX_CACHE", "off")
    enable_compilation_cache()
    assert jax.config.jax_compilation_cache_dir == before
