"""Shared-memory model plane: arena roundtrip exactness, read-only
mapped views, torn-arena quarantine, GC safety, watcher convergence, and
the prefork e2e (one fold per delta, one /reload converges every
worker).

The plane's contract is that a worker serving mapped views is
bit-indistinguishable from one serving the publisher's private model —
every test here diffs responses/arrays exactly, never approximately.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


# -- fixtures ----------------------------------------------------------------


def _buy(u, i, event="purchase"):
    from predictionio_tpu.events.event import Event

    return Event(event=event, entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def _set_item(i, props):
    from predictionio_tpu.events.event import DataMap, Event

    return Event(event="$set", entity_type="item", entity_id=i,
                 properties=DataMap(props))


def _seed(storage, app_name="mpapp", n_users=14, n_items=9, seed=5):
    from predictionio_tpu.storage.base import App

    app_id = storage.apps.insert(App(0, app_name))
    rng = np.random.default_rng(seed)
    evs = [_buy(f"u{u}", f"i{it}")
           for u in range(n_users) for it in range(n_items)
           if rng.random() < 0.5]
    evs += [_set_item(f"i{it}", {"category": f"c{it % 3}"})
            for it in range(n_items)]
    storage.l_events.insert_batch(evs, app_id)
    return app_id


def _ur(app_name="mpapp"):
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm, URAlgorithmParams, URDataSourceParams,
    )

    engine = UniversalRecommenderEngine.apply()
    ap = URAlgorithmParams(app_name=app_name, mesh_dp=1,
                           max_correlators_per_item=5)
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name=app_name, event_names=["purchase"]),
        algorithm_params_list=[("ur", ap)])
    return engine, ep, URAlgorithm(ap)


def _canon(res):
    return [(s.item, float(s.score)) for s in res.item_scores]


@pytest.fixture()
def host_serving(monkeypatch):
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")


@pytest.fixture()
def plane_dir(tmp_path, monkeypatch):
    d = tmp_path / "plane"
    monkeypatch.setenv("PIO_MODEL_PLANE_POLL_S", "0.05")
    return str(d)


def _corpus():
    from predictionio_tpu.models.universal_recommender import URQuery

    return [URQuery.from_json(b) for b in (
        {"user": "u2", "num": 5},
        {"user": "nobody", "num": 4},
        {"user": "u3", "num": 5,
         "fields": [{"name": "category", "values": ["c1"], "bias": -1}]},
        {"user": "u4", "num": 5,
         "fields": [{"name": "category", "values": ["c0"], "bias": 2.0}]},
        {"user": "u5", "num": 5, "blacklistItems": ["i1", "i2"]},
        {"item": "i1", "num": 4},
    )]


# -- arena roundtrip ---------------------------------------------------------


def test_plane_roundtrip_bit_exact_and_readonly(mem_storage, host_serving,
                                                plane_dir):
    """A mapped generation is array-identical to the published model,
    answers every query identically, carries derived serving state
    pre-built, and rejects in-place mutation of the shared views."""
    from predictionio_tpu.streaming.plane import ModelPlane

    _seed(mem_storage)
    engine, ep, algo = _ur()
    model = engine.train(ep)[0]
    pub = ModelPlane(plane_dir)
    gen = pub.publish([model], {"mode": "test"})
    assert gen == 1
    sub = ModelPlane(plane_dir)
    mapped, info = sub.load(sub.current())
    assert info["planeGeneration"] == 1
    for name in model.indicator_idx:
        assert np.array_equal(mapped.indicator_idx[name],
                              model.indicator_idx[name])
        assert np.array_equal(mapped.indicator_llr[name],
                              model.indicator_llr[name])
        assert (mapped.event_item_dicts[name].strings()
                == model.event_item_dicts[name].strings())
        # derived CSR inversion rode the arena — no rebuild on the worker
        for a, b in zip(mapped.__dict__["_host_inv"][name],
                        model.host_inverted(name)):
            assert np.array_equal(a, b)
    assert np.array_equal(mapped.popularity, model.popularity)
    assert np.array_equal(mapped.__dict__["_host_pop_order"],
                          model.host_pop_order())
    assert np.array_equal(mapped.user_seen.indptr, model.user_seen.indptr)
    assert np.array_equal(mapped.user_seen.values, model.user_seen.values)
    assert dict(mapped.item_properties) == dict(model.item_properties)
    # responses identical (the live-store history path)
    for q in _corpus():
        assert _canon(algo.predict(mapped, q)) == _canon(
            algo.predict(model, q))
    # no worker can corrupt the shared mapping
    for arr in (mapped.indicator_idx["purchase"],
                mapped.indicator_llr["purchase"],
                mapped.popularity, mapped.user_seen.values,
                mapped.__dict__["_host_pop_order"],
                mapped.__dict__["_host_inv"]["purchase"][2]):
        assert not arr.flags.writeable
        with pytest.raises(ValueError):
            arr[..., 0] = 1


def test_plane_dict_carry_and_extension(mem_storage, host_serving,
                                        plane_dir):
    """Unchanged dictionaries carry BY OBJECT across mapped generations;
    an end-grown item dictionary (publisher proves the byte-prefix)
    extends the worker's previous dictionary instead of rebuilding."""
    from predictionio_tpu.store.columnar import IdDict
    from predictionio_tpu.streaming.plane import ModelPlane

    _seed(mem_storage)
    engine, ep, _ = _ur()
    model = engine.train(ep)[0]
    pub, sub = ModelPlane(plane_dir), ModelPlane(plane_dir)
    pub.publish([model])
    m1, _ = sub.load(sub.current())
    rebuilt0 = sub.dicts_rebuilt
    # same model again: every dict carried by content crc
    pub.publish([model])
    m2, _ = sub.load(sub.current())
    assert m2.item_dict is m1.item_dict
    assert m2.user_dict is m1.user_dict
    assert sub.dicts_rebuilt == rebuilt0
    # end-grown item dict: clone + append (the fold engine's new-item
    # case) — worker extends, never re-decodes the covered prefix
    grown = model.item_dict.clone()
    grown.add("brand-new-item")
    import dataclasses as _dc  # noqa: F401  (document intent)
    model.item_dict = grown
    model.event_item_dicts = {"purchase": grown}
    model.indicator_idx = {
        "purchase": np.vstack([model.indicator_idx["purchase"],
                               -np.ones((1, model.indicator_idx[
                                   "purchase"].shape[1]), np.int32)])}
    model.indicator_llr = {
        "purchase": np.vstack([model.indicator_llr["purchase"],
                               np.zeros((1, model.indicator_llr[
                                   "purchase"].shape[1]), np.float32)])}
    model.popularity = np.concatenate(
        [np.asarray(model.popularity, np.float32), [0.0]])
    for k in ("_host_inv", "_host_pop_order", "_host_pop", "_pop_norm"):
        model.__dict__.pop(k, None)
    pub.publish([model])
    ext0 = sub.dicts_extended
    m3, _ = sub.load(sub.current())
    assert sub.dicts_extended == ext0 + 1
    assert m3.item_dict.strings() == grown.strings()
    assert isinstance(m3.item_dict, IdDict)


def test_torn_arena_quarantined_old_generation_serves(
        mem_storage, host_serving, plane_dir):
    """A publisher SIGKILL'd mid-emit leaves either an unreferenced tmp
    file (invisible) or a manifest pointing at a torn arena: the watcher
    quarantines the torn file, keeps the served generation, and heals on
    the next good publish."""
    from predictionio_tpu.streaming.plane import ModelPlane, PlaneWatcher

    _seed(mem_storage)
    engine, ep, algo = _ur()
    model = engine.train(ep)[0]
    pub = ModelPlane(plane_dir)
    pub.publish([model])
    sub = ModelPlane(plane_dir)
    installed = []
    watcher = PlaneWatcher(sub, lambda models, info: (
        installed.append((models[0], info)), True)[1], poll_s=0.05)
    assert watcher.check_now()
    assert watcher.generation == 1
    # a crash between arena write and manifest flip: tmp file only
    (Path(plane_dir) / ".gen-0000000002.arena.tmp-999").write_bytes(
        b"PIOARR01garbage")
    assert not watcher.check_now()          # manifest still at gen 1
    # a torn arena REFERENCED by the manifest (worst case: manifest
    # written, arena bytes truncated by the crash/disk)
    torn = Path(plane_dir) / "gen-0000000002.arena"
    torn.write_bytes(b"PIOARR01" + b"\x00" * 8)
    cur = pub.current()
    pub._write_manifest({**cur, "generation": 2,
                         "file": "gen-0000000002.arena"})
    assert not watcher.check_now()
    assert watcher.generation == 1          # old generation still serves
    assert (Path(plane_dir)
            / "gen-0000000002.arena.quarantine").exists()
    q = _corpus()[0]
    assert _canon(algo.predict(installed[-1][0], q)) == _canon(
        algo.predict(model, q))
    # the next good publish supersedes the quarantined generation
    gen = pub.publish([model])
    assert gen == 3
    assert watcher.check_now()
    assert watcher.generation == 3


def test_gc_keeps_window_and_never_breaks_a_mapped_arena(
        mem_storage, host_serving, plane_dir, monkeypatch):
    """GC unlinks generations past PIO_MODEL_PLANE_KEEP (counted in
    pio_model_plane_gc_total); a model still mapping an unlinked arena
    keeps serving identical responses — POSIX keeps the pages until the
    mapping drops.  Runs with delta arenas OFF — every generation is a
    full arena, so the keep window alone decides reclamation (the
    delta-chain refcount cases live in test_gc_refcount_*)."""
    from predictionio_tpu.obs import metrics as obs_metrics
    from predictionio_tpu.streaming.plane import ModelPlane

    monkeypatch.setenv("PIO_MODEL_PLANE_KEEP", "2")
    monkeypatch.setenv("PIO_MODEL_PLANE_DELTA", "off")
    _seed(mem_storage)
    engine, ep, algo = _ur()
    model = engine.train(ep)[0]
    pub, sub = ModelPlane(plane_dir), ModelPlane(plane_dir)
    pub.publish([model])
    mapped, _ = sub.load(sub.current())     # worker pins generation 1
    ref = [_canon(algo.predict(mapped, q)) for q in _corpus()]
    gc0 = obs_metrics.get_registry().counter(
        "pio_model_plane_gc_total", "x").value()
    for _ in range(4):
        pub.publish([model])                # gens 2..5; GC as it goes
    arenas = sorted(p.name for p in Path(plane_dir).glob("gen-*.arena"))
    assert arenas == ["gen-0000000004.arena", "gen-0000000005.arena"]
    assert obs_metrics.get_registry().counter(
        "pio_model_plane_gc_total", "x").value() > gc0
    # generation 1's file is unlinked, its mapping is not: the stale
    # worker serves bit-identical answers until it converges
    assert [_canon(algo.predict(mapped, q)) for q in _corpus()] == ref


# -- server topology ---------------------------------------------------------


def test_watcher_converges_two_states_and_single_reload(
        mem_storage, host_serving, plane_dir):
    """Two in-process query servers sharing one plane (the prefork
    topology minus process isolation): the initial publish converges
    both, ONE plane_reload on either converges both, and both serve
    identical bytes."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import QueryServerState

    _seed(mem_storage)
    engine, ep, _ = _ur()
    core_workflow.run_train(engine, ep, engine_id="mp-engine",
                            storage=mem_storage)
    a = QueryServerState(engine, ep, URQuery, "mp-engine", "1", "default",
                         storage=mem_storage, plane_dir=plane_dir)
    b = QueryServerState(engine, ep, URQuery, "mp-engine", "1", "default",
                         storage=mem_storage, plane_dir=plane_dir)
    try:
        a.plane_publish_initial()
        deadline = time.time() + 10
        while time.time() < deadline and (
                a.plane_generation < 1 or b.plane_generation < 1):
            time.sleep(0.02)
        assert a.plane_generation == b.plane_generation == 1
        body = {"user": "u2", "num": 5}
        assert a.predict(body).to_json() == b.predict(body).to_json()
        gen, iid = b.plane_reload()
        assert gen == 2 and iid
        assert b.plane_generation == 2      # synchronous on the reloader
        deadline = time.time() + 10
        while time.time() < deadline and a.plane_generation < 2:
            time.sleep(0.02)
        assert a.plane_generation == 2      # sibling converged, no poll
        assert a.predict(body).to_json() == b.predict(body).to_json()
        assert a.info()["planeGeneration"] == 2
        assert a.freshness()["planeGeneration"] == 2
    finally:
        a.stop_auto_reload()
        b.stop_auto_reload()


def test_embedded_follower_publishes_through_plane(
        mem_storage, host_serving, plane_dir):
    """--workers 1 with PIO_MODEL_PLANE=on: the embedded follower IS the
    publisher — folds land in the arena, a sibling state converges, and
    post-drain responses equal a from-scratch retrain EXACTLY."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import QueryServerState

    app_id = _seed(mem_storage)
    engine, ep, algo = _ur()
    core_workflow.run_train(engine, ep, engine_id="mp-engine",
                            storage=mem_storage)
    a = QueryServerState(engine, ep, URQuery, "mp-engine", "1", "default",
                         storage=mem_storage, plane_dir=plane_dir)
    b = QueryServerState(engine, ep, URQuery, "mp-engine", "1", "default",
                         storage=mem_storage, plane_dir=plane_dir)
    follower = None
    try:
        a.plane_publish_initial()
        follower = a.follower = FollowTrainer(
            engine, ep, "mp-engine", storage=mem_storage, interval=0.05,
            on_publish=a.plane_publish, persist=False)
        follower.start()
        g0_deadline = time.time() + 10
        while time.time() < g0_deadline and b.plane_generation < 1:
            time.sleep(0.02)
        gref = b.plane_generation
        mem_storage.l_events.insert_batch(
            [_buy("newbie", f"i{j}") for j in (0, 1, 2)], app_id)
        deadline = time.time() + 20
        while time.time() < deadline and not (
                a.plane_generation > gref
                and b.plane_generation == a.plane_generation
                and follower.last_outcome == "idle"):
            time.sleep(0.05)
        assert a.plane_generation > gref
        assert b.plane_generation == a.plane_generation
        invalidate_staging_cache()
        ref = engine.train(ep)[0]
        # post-drain parity on BOTH states (the publisher's own mapped
        # copy and the pure-consumer sibling) vs a from-scratch retrain
        bodies = [{"user": "u2", "num": 5}, {"user": "newbie", "num": 5},
                  {"user": "u3", "num": 5,
                   "fields": [{"name": "category", "values": ["c1"],
                               "bias": -1}]}]
        for st in (a, b):
            for body in bodies:
                got = st.predict(body).to_json()
                want = algo.predict(
                    ref, URQuery.from_json(body)).to_json()
                assert got == want, (body, got, want)
    finally:
        if follower is not None:
            follower.stop()
        a.stop_auto_reload()
        b.stop_auto_reload()


# -- delta arenas ------------------------------------------------------------


def _fold_state(n_items=1200, hist=4, k=5):
    """A resident fold state over a synthetic catalog (one buy per item,
    hist-item user histories — the freshness-sweep shape)."""
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithmParams, URDataSourceParams,
    )
    from predictionio_tpu.store.columnar import EventBatch
    from predictionio_tpu.streaming.fold import URFoldState

    ap = URAlgorithmParams(app_name="delta", mesh_dp=1,
                           max_correlators_per_item=k)
    dp = URDataSourceParams(app_name="delta", event_names=["buy"])
    evs = [Event(event="buy", entity_type="user",
                 entity_id=f"u{j // hist}", target_entity_type="item",
                 target_entity_id=f"i{j}") for j in range(n_items)]
    batch = EventBatch.from_events(evs)
    batch.prop_columns = {}
    return URFoldState.bootstrap(ap, dp, batch)


def _fold_delta(state, events):
    """Fold a delta batch sharing the state's dictionaries (the
    scan_tail contract) and return the emitted model, serving-state
    warm included."""
    from predictionio_tpu.store.columnar import EventBatch

    d = EventBatch.from_events(
        events, entity_dict=state.batch.entity_dict,
        target_dict=state.batch.target_dict,
        event_dict=state.batch.event_dict)
    d.prop_columns = {}
    model = state.fold(d)
    model.ensure_host_serving_state()
    return model


def _freshness_delta(state, r, n_items):
    """The PR-13 freshness-sweep round shape: new correlated users + a
    brand-new item — marginals move, so every finite LLR score changes
    and pure-ref publishing alone cannot stay small."""
    from predictionio_tpu.events.event import Event

    seed = f"i{(r * 97) % n_items}"
    evs = [Event(event="buy", entity_type="user", entity_id=f"probe{r}",
                 target_entity_type="item", target_entity_id=seed)]
    for j in range(4):
        for tgt in (seed, f"fresh_item_{r}"):
            evs.append(Event(event="buy", entity_type="user",
                             entity_id=f"cob{r}_{j}",
                             target_entity_type="item",
                             target_entity_id=tgt))
    return evs


def _assert_models_identical(a, b):
    """Every serialized array, derived structure, and dictionary —
    bit-exact, dtypes included."""
    for n in b.indicator_idx:
        pairs = [(a.indicator_idx[n], b.indicator_idx[n]),
                 (a.indicator_llr[n], b.indicator_llr[n])]
        pairs += list(zip(a.__dict__["_host_inv"][n], b.host_inverted(n)))
        for x, y in pairs:
            assert x.dtype == y.dtype
            assert np.array_equal(x, y)
        assert (a.event_item_dicts[n].strings()
                == b.event_item_dicts[n].strings())
    assert np.array_equal(a.popularity, b.popularity)
    po_a = a.__dict__["_host_pop_order"]
    po_b = b.host_pop_order()
    assert po_a.dtype == po_b.dtype and np.array_equal(po_a, po_b)
    assert np.array_equal(a.user_seen.indptr, b.user_seen.indptr)
    assert np.array_equal(a.user_seen.values, b.user_seen.values)
    for n, csr in b.user_seen_by_event.items():
        assert np.array_equal(a.user_seen_by_event[n].indptr, csr.indptr)
        assert np.array_equal(a.user_seen_by_event[n].values, csr.values)
    assert a.item_dict.strings() == b.item_dict.strings()
    assert a.user_dict.strings() == b.user_dict.strings()
    assert dict(a.item_properties) == dict(b.item_properties)


def test_delta_composed_bit_exact_vs_full_arena_oracle(
        plane_dir, tmp_path, monkeypatch):
    """The acceptance proof at test scale: freshness-shaped folds
    published as delta generations compose — on an incremental worker
    AND a cold mid-chain joiner — into models bit-identical to the
    PIO_MODEL_PLANE_DELTA=off full-arena oracle, every array, derived
    CSR, and dictionary included, while each delta writes ≤ 10% (and a
    duplicate-only fold ≤ 5%) of the full-arena bytes."""
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.streaming.plane import ModelPlane

    # k=8 is the freshness-sweep shape (maxCorrelatorsPerItem) the
    # acceptance criterion is calibrated to: the delta floor is the
    # finite-LLR values, ≈ (nnz / (I_p·K)) of one table
    n_items = 2000
    state = _fold_state(n_items=n_items, k=8)
    pub = ModelPlane(plane_dir)
    worker = ModelPlane(plane_dir)
    oracle_pub = ModelPlane(str(tmp_path / "oracle"))
    oracle_sub = ModelPlane(str(tmp_path / "oracle"))

    def oracle_load(model):
        monkeypatch.setenv("PIO_MODEL_PLANE_DELTA", "off")
        try:
            oracle_pub.publish([model])
            return oracle_sub.load(oracle_sub.current())[0]
        finally:
            monkeypatch.delenv("PIO_MODEL_PLANE_DELTA")

    m0 = state.model
    m0.ensure_host_serving_state()
    pub.publish([m0], {"mode": "fold"})
    full_bytes = pub.last_publish_stats["written"]
    w0, _ = worker.load(worker.current())
    _assert_models_identical(w0, oracle_load(m0))
    cold = None
    for r in range(3):
        m = _fold_delta(state, _freshness_delta(state, r, n_items))
        pub.publish([m], {"mode": "fold"})
        st = pub.last_publish_stats
        assert os.path.exists(
            os.path.join(plane_dir, f"gen-{r + 2:010d}.delta"))
        assert st["written"] <= 0.10 * full_bytes, st
        wa, info = worker.load(worker.current())
        assert info["planeGeneration"] == r + 2
        ref = oracle_load(m)
        _assert_models_identical(wa, ref)
        if r == 1:
            cold = ModelPlane(plane_dir)    # joins mid-chain
        if cold is not None:
            wc, _ = cold.load(cold.current())
            _assert_models_identical(wc, ref)
        # composed arrays are read-only, like mapped views
        for arr in (wa.indicator_llr["buy"], wa.popularity,
                    wa.__dict__["_host_inv"]["buy"][2]):
            assert not arr.flags.writeable
    # duplicate-only fold: ~zero new bytes, asserted via the counter
    from predictionio_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.get_registry()

    def written_counter():
        c = reg.counter("pio_model_plane_publish_bytes_total", "x")
        return (c.value(path="full") or 0) + (c.value(path="delta") or 0)

    before = written_counter()
    m = _fold_delta(state, [Event(
        event="buy", entity_type="user", entity_id="u0",
        target_entity_type="item", target_entity_id="i0")])
    pub.publish([m], {"mode": "fold"})
    assert pub.last_publish_stats["written"] <= 0.05 * full_bytes
    assert written_counter() - before <= 0.05 * full_bytes
    wa, _ = worker.load(worker.current())
    _assert_models_identical(wa, oracle_load(m))


def test_publisher_sigkill_mid_blob_and_mid_manifest(plane_dir):
    """Delta-chain torture: a publisher killed mid-blob leaves an
    unreferenced tmp file (invisible — the manifest still names the
    previous generation); killed mid-manifest leaves a tmp CURRENT
    (ignored — the flip is an atomic rename).  A REFERENCED torn delta
    (manifest written, bytes truncated by the crash/disk) quarantines
    the torn file, the old generation keeps serving, and a restarted
    publisher — which cannot prove the chain — heals with a keyframe."""
    from predictionio_tpu.streaming.plane import ModelPlane, PlaneWatcher

    n_items = 600
    state = _fold_state(n_items=n_items)
    pub = ModelPlane(plane_dir)
    m0 = state.model
    m0.ensure_host_serving_state()
    pub.publish([m0], {"mode": "fold"})
    m1 = _fold_delta(state, _freshness_delta(state, 0, n_items))
    pub.publish([m1], {"mode": "fold"})
    sub = ModelPlane(plane_dir)
    installed = []
    watcher = PlaneWatcher(sub, lambda models, info: (
        installed.append(models[0]), True)[1], poll_s=0.05)
    assert watcher.check_now() and watcher.generation == 2
    # SIGKILL mid-blob: partial tmp container only
    (Path(plane_dir) / ".gen-0000000003.delta.tmp-999").write_bytes(
        b"PIOARR01" + b"\x00" * 4)
    # SIGKILL mid-manifest: partial CURRENT tmp only
    (Path(plane_dir) / "CURRENT.json.tmp-999").write_bytes(b'{"gen')
    assert not watcher.check_now()
    assert watcher.generation == 2
    # torn REFERENCED delta: manifest flipped, delta bytes truncated
    m2 = _fold_delta(state, _freshness_delta(state, 1, n_items))
    pub.publish([m2], {"mode": "fold"})
    torn = Path(plane_dir) / "gen-0000000003.delta"
    good = torn.read_bytes()
    torn.write_bytes(good[:len(good) // 2])
    assert not watcher.check_now()
    assert watcher.generation == 2          # old generation serves
    assert (Path(plane_dir)
            / "gen-0000000003.delta.quarantine").exists()
    # publisher restart: no in-memory prev state -> full keyframe heals
    pub2 = ModelPlane(plane_dir)
    gen = pub2.publish([m2], {"mode": "fold"})
    assert gen == 4
    assert (Path(plane_dir) / "gen-0000000004.arena").exists()
    assert watcher.check_now() and watcher.generation == 4
    _assert_models_identical(installed[-1], m2)


def test_torn_mid_chain_file_quarantines_the_failing_file(plane_dir):
    """A cold worker composing a chain whose MIDDLE file is torn must
    quarantine that file — not the newest generation, whose bytes are
    fine — and the live publisher's next publish heals the chain with a
    keyframe (chain-intact probe)."""
    from predictionio_tpu.streaming.plane import ModelPlane, PlaneWatcher

    n_items = 600
    state = _fold_state(n_items=n_items)
    pub = ModelPlane(plane_dir)
    m = state.model
    m.ensure_host_serving_state()
    pub.publish([m], {"mode": "fold"})
    for r in range(2):
        m = _fold_delta(state, _freshness_delta(state, r, n_items))
        pub.publish([m], {"mode": "fold"})
    mid = Path(plane_dir) / "gen-0000000002.delta"
    mid.write_bytes(mid.read_bytes()[:64])
    cold = ModelPlane(plane_dir)
    watcher = PlaneWatcher(cold, lambda models, info: True,
                           poll_s=0.05)
    assert not watcher.check_now()
    assert (Path(plane_dir)
            / "gen-0000000002.delta.quarantine").exists()
    assert not (Path(plane_dir)
                / "gen-0000000003.delta.quarantine").exists()
    # the LIVE publisher (prev state intact) notices the missing chain
    # file and publishes a keyframe instead of a delta
    m2 = _fold_delta(state, _freshness_delta(state, 2, n_items))
    gen = pub.publish([m2], {"mode": "fold"})
    assert gen == 4
    assert (Path(plane_dir) / "gen-0000000004.arena").exists()
    assert watcher.check_now() and watcher.generation == 4


def test_keyframe_interval_and_restart_replay(plane_dir, monkeypatch):
    """PIO_MODEL_PLANE_FULL_EVERY bounds the chain: every Nth
    generation is a full arena, and a fresh worker joining at the tip
    composes from the latest keyframe only — files older than it are
    not needed (restart cost is the keyframe + the tail deltas)."""
    from predictionio_tpu.streaming.plane import ModelPlane

    monkeypatch.setenv("PIO_MODEL_PLANE_FULL_EVERY", "3")
    monkeypatch.setenv("PIO_MODEL_PLANE_KEEP", "10")   # no GC here
    n_items = 600
    state = _fold_state(n_items=n_items)
    pub = ModelPlane(plane_dir)
    m = state.model
    m.ensure_host_serving_state()
    pub.publish([m], {"mode": "fold"})          # gen 1: keyframe
    for r in range(5):                          # gens 2..6
        m = _fold_delta(state, _freshness_delta(state, r, n_items))
        pub.publish([m], {"mode": "fold"})
    names = sorted(p.name for p in Path(plane_dir).glob("gen-*"))
    # keyframes at 1 and 4 (gen-1 + 3 = interval), deltas between
    assert "gen-0000000001.arena" in names
    assert "gen-0000000004.arena" in names
    assert "gen-0000000005.delta" in names
    assert "gen-0000000006.delta" in names
    # a fresh worker needs only keyframe 4 + deltas 5..6: delete older
    for p in Path(plane_dir).glob("gen-000000000[123].*"):
        p.unlink()
    fresh = ModelPlane(plane_dir)
    mapped, info = fresh.load(fresh.current())
    assert info["planeGeneration"] == 6
    _assert_models_identical(mapped, m)


def test_gc_refcount_keeps_chain_incl_quarantine_heal(
        plane_dir, monkeypatch):
    """The GC-refcount satellite: with delta chains, GC must never
    unlink a blob a kept generation's manifest still composes from —
    the keyframe survives while any kept delta references it, even
    past the PIO_MODEL_PLANE_KEEP count; after a quarantined-then-
    healed chain, the superseded files (quarantine included) are
    reclaimed once no kept generation needs them, and a fresh worker
    can still compose every kept generation."""
    from predictionio_tpu.obs import metrics as obs_metrics
    from predictionio_tpu.streaming.plane import ModelPlane

    monkeypatch.setenv("PIO_MODEL_PLANE_KEEP", "2")
    monkeypatch.setenv("PIO_MODEL_PLANE_FULL_EVERY", "100")
    n_items = 600
    state = _fold_state(n_items=n_items)
    pub = ModelPlane(plane_dir)
    m = state.model
    m.ensure_host_serving_state()
    pub.publish([m], {"mode": "fold"})          # gen 1: keyframe
    for r in range(4):                          # gens 2..5: deltas
        m = _fold_delta(state, _freshness_delta(state, r, n_items))
        pub.publish([m], {"mode": "fold"})
    names = {p.name for p in Path(plane_dir).glob("gen-*")}
    # count-only GC would have kept {4, 5}; the refcount keeps the
    # whole chain back to the keyframe both compose from
    assert names == {"gen-0000000001.arena", "gen-0000000002.delta",
                     "gen-0000000003.delta", "gen-0000000004.delta",
                     "gen-0000000005.delta"}
    fresh = ModelPlane(plane_dir)
    _assert_models_identical(fresh.load(fresh.current())[0], m)
    # quarantine a chain file -> the next publish heals with a keyframe
    q = Path(plane_dir) / "gen-0000000003.delta"
    q.replace(str(q) + ".quarantine")
    m = _fold_delta(state, _freshness_delta(state, 4, n_items))
    gen = pub.publish([m], {"mode": "fold"})    # gen 6: healing keyframe
    assert (Path(plane_dir) / "gen-0000000006.arena").exists()
    gc0 = obs_metrics.get_registry().counter(
        "pio_model_plane_gc_total", "x").value()
    for r in range(5, 7):                       # gens 7..8: new chain
        m = _fold_delta(state, _freshness_delta(state, r, n_items))
        gen = pub.publish([m], {"mode": "fold"})
    assert gen == 8
    names = {p.name for p in Path(plane_dir).glob("gen-*")}
    # kept gens {7, 8} chain to keyframe 6; everything older —
    # including the quarantined file — was reclaimed
    assert names == {"gen-0000000006.arena", "gen-0000000007.delta",
                     "gen-0000000008.delta"}
    assert obs_metrics.get_registry().counter(
        "pio_model_plane_gc_total", "x").value() > gc0
    fresh2 = ModelPlane(plane_dir)
    _assert_models_identical(fresh2.load(fresh2.current())[0], m)


def test_watcher_inotify_wake_beats_the_poll_period(
        mem_storage, host_serving, plane_dir):
    """The propagation-latency satellite: with a deliberately huge poll
    period, a publish must still install within ~a second — the inotify
    wake on the manifest rename, not the poll, drives the swap.  (Where
    inotify is unavailable the watcher falls back to stat-polling and
    this test is skipped.)"""
    from predictionio_tpu.streaming.plane import (
        ModelPlane, PlaneWatcher, _DirNotify,
    )

    os.makedirs(plane_dir, exist_ok=True)
    try:
        probe = _DirNotify(plane_dir)
        probe.close()
    except OSError:
        pytest.skip("inotify unavailable on this platform")
    _seed(mem_storage)
    engine, ep, _ = _ur()
    model = engine.train(ep)[0]
    pub, sub = ModelPlane(plane_dir), ModelPlane(plane_dir)
    installed = []
    watcher = PlaneWatcher(sub, lambda models, info: (
        installed.append(info["planeGeneration"]), True)[1],
        poll_s=30.0)
    watcher.start()
    try:
        time.sleep(0.3)                  # let the loop enter its wait
        t0 = time.time()
        pub.publish([model])
        deadline = time.time() + 5
        while time.time() < deadline and not installed:
            time.sleep(0.02)
        assert installed == [1]
        assert time.time() - t0 < 5.0    # not the 30 s poll
    finally:
        watcher.stop()


def test_watcher_stat_poll_fallback_converges(
        mem_storage, host_serving, plane_dir, monkeypatch):
    """PIO_MODEL_PLANE_NOTIFY=off: the stat-poll fallback still
    converges within the poll period, and an unchanged manifest costs a
    stat — not an open/parse — per period."""
    from predictionio_tpu.streaming.plane import ModelPlane, PlaneWatcher

    monkeypatch.setenv("PIO_MODEL_PLANE_NOTIFY", "off")
    _seed(mem_storage)
    engine, ep, _ = _ur()
    model = engine.train(ep)[0]
    pub, sub = ModelPlane(plane_dir), ModelPlane(plane_dir)
    installed = []
    watcher = PlaneWatcher(sub, lambda models, info: (
        installed.append(info["planeGeneration"]), True)[1], poll_s=0.05)
    watcher.start()
    try:
        pub.publish([model])
        deadline = time.time() + 5
        while time.time() < deadline and not installed:
            time.sleep(0.02)
        assert installed == [1]
    finally:
        watcher.stop()


# -- prefork e2e (real processes) --------------------------------------------


def _wait_group(base, n_workers, min_gen, deadline_s, proc=None):
    """Poll fresh GET / connections until n_workers distinct pids all
    report planeGeneration >= min_gen; returns {pid: gen}."""
    deadline = time.time() + deadline_s
    seen = {}
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(base + "/", timeout=2) as r:
                d = json.loads(r.read())
            seen[d["pid"]] = d.get("planeGeneration") or 0
        except Exception:
            pass
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(f"deploy died rc {proc.returncode}")
        if len(seen) >= n_workers and all(
                g >= min_gen for g in seen.values()):
            return seen
        time.sleep(0.1)
    raise AssertionError(
        f"group did not converge to gen>={min_gen}: {seen}")


def test_prefork_plane_one_fold_one_reload(tmp_path):
    """The acceptance drill on a REAL ``deploy --workers 2 --follow``
    prefork group: all workers converge on plane generations, appending
    a delta folds exactly ONCE across the group (fold counters from the
    cross-worker /metrics merge), the fold is reflected on every worker,
    and ONE /reload converges every worker onto a new generation."""
    import re

    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )
    from predictionio_tpu.workflow import core_workflow

    store_path = str(tmp_path / "store")
    storage = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": store_path}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    set_storage(storage)
    try:
        app_id = _seed(storage, app_name="mpe2e")
        engine, ep, _ = _ur(app_name="mpe2e")
        variant = {
            "id": "mpe2e-engine",
            "engineFactory": "predictionio_tpu.models."
                             "universal_recommender."
                             "UniversalRecommenderEngine",
            "datasource": {"params": {"appName": "mpe2e",
                                      "eventNames": ["purchase"]}},
            "algorithms": [{"name": "ur", "params": {
                "appName": "mpe2e", "eventNames": [], "meshDp": 1,
                "maxCorrelatorsPerItem": 5}}]}
        ur_json = str(tmp_path / "engine.json")
        with open(ur_json, "w") as f:
            json.dump(variant, f)
        core_workflow.run_train(engine, ep, engine_id="mpe2e-engine",
                                storage=storage)
    finally:
        set_storage(None)
    env = {**os.environ,
           "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
           "PIO_STORAGE_SOURCES_FS_PATH": store_path,
           "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
           "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
           "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
           "PIO_JAX_PLATFORM": "cpu",
           "PIO_METRICS_FLUSH_S": "0.25",
           "PIO_MODEL_PLANE_POLL_S": "0.1",
           "PIO_FOLLOW_INTERVAL_S": "0.3"}
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli.main", "deploy",
         "--engine-json", ur_json, "--ip", "127.0.0.1",
         "--port", str(port), "--workers", "2", "--follow", "0.3"],
        env=env, cwd=str(REPO))
    base = f"http://127.0.0.1:{port}"
    try:
        # generation 1 = the parent's initial publish; generation 2 =
        # the publisher process's bootstrap restage.  Wait for BOTH so
        # the delta below is folded incrementally (not swallowed by a
        # bootstrap that started after the append)
        _wait_group(base, 2, 2, 120, proc)
        # ONE reload converges BOTH workers (the kernel routes the
        # request to one listener; the plane carries it to the rest)
        with urllib.request.urlopen(base + "/reload", timeout=30) as r:
            rel = json.loads(r.read())
        assert rel["reloaded"] is True and rel["generation"] >= 2
        _wait_group(base, 2, rel["generation"], 30)
        # append a delta: the publisher folds it ONCE; every worker
        # converges and reflects it
        storage2 = Storage(StorageConfig(
            sources={"FS": {"type": "localfs", "path": store_path}},
            repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                            "MODELDATA")}))
        storage2.l_events.insert_batch(
            [_buy("newbie", f"i{j}") for j in (0, 1, 2)], app_id)
        seen = _wait_group(base, 2, rel["generation"] + 1, 60)
        pids = set(seen)
        reflected = set()
        deadline = time.time() + 30
        while time.time() < deadline and reflected != pids:
            req = urllib.request.Request(
                base + "/queries.json",
                json.dumps({"user": "newbie", "num": 5}).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read())["itemScores"]
            with urllib.request.urlopen(base + "/", timeout=2) as r:
                reflected.add(json.loads(r.read())["pid"])
        # fold counters across the WHOLE group (any worker's /metrics
        # merges every sibling + the publisher): the delta folded ONCE —
        # with per-worker followers this reads >= 2.  Poll: the
        # publisher's snapshot flush lags the fold by up to
        # PIO_METRICS_FLUSH_S.
        deadline = time.time() + 15
        folds, text = 0.0, ""
        while time.time() < deadline and folds < 1.0:
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            folds = sum(float(m.group(1)) for m in re.finditer(
                r'pio_follow_folds_total\{outcome="fold"\} ([0-9.e+]+)',
                text))
            if folds < 1.0:
                time.sleep(0.3)
        assert folds == 1.0, f"expected exactly one fold, saw {folds}"
        assert len(re.findall(
            r'pio_worker_up\{worker="[^"]+"\} 1', text)) == 3
        gens = {m.group(1): float(m.group(2)) for m in re.finditer(
            r'pio_model_plane_generation\{worker="([^"]+)"\}'
            r' ([0-9.e+]+)', text)}
        assert len(gens) == 3               # 2 workers + the publisher
        assert len(re.findall(r"pio_process_rss_bytes\{", text)) >= 3
    finally:
        for _ in range(16):
            try:
                with urllib.request.urlopen(base + "/stop",
                                            timeout=5) as r:
                    r.read()
                time.sleep(0.3)
            except Exception:
                break
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
