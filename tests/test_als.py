"""ALS op tests: reconstruction quality and single-device vs 8-device mesh
parity (the reference tests MLlib ALS only via its template integration; here
the op itself is tested — SURVEY.md §4 maps SharedSparkContext local[*] to the
virtual CPU mesh)."""

import numpy as np
import pytest

from predictionio_tpu.ops.als import (
    ALSData,
    als_train,
    prepare_als_data,
    recommend_batch,
    recommend_scores,
)
from predictionio_tpu.parallel.mesh import MeshSpec, create_mesh


def synthetic_ratings(n_users=40, n_items=30, k_true=4, density=0.5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_users, k_true))
    Y = rng.normal(size=(n_items, k_true))
    R = X @ Y.T
    mask = rng.random((n_users, n_items)) < density
    u, i = np.nonzero(mask)
    return u.astype(np.int32), i.astype(np.int32), R[u, i].astype(np.float32), R, mask


def rmse_on_observed(X, Y, R, mask):
    pred = X @ Y.T
    return float(np.sqrt(np.mean((pred[mask] - R[mask]) ** 2)))


def test_prepare_als_data_layout():
    u = np.array([0, 1, 2, 3, 4, 0], np.int32)
    i = np.array([0, 0, 1, 1, 2, 2], np.int32)
    r = np.ones(6, np.float32)
    d = prepare_als_data(u, i, r, n_users=5, n_items=3, dp=2)
    assert d.user_rows == 3 and d.item_rows == 2
    assert d.u_user_local.shape[0] == 2
    # user 3 -> shard 1, local row 1
    assert d.u_mask.sum() == 6
    # flat item index targets shard*item_rows + row
    assert d.u_item_flat.max() < 2 * d.item_rows


def test_als_reconstructs_ratings_single_device():
    u, i, r, R, mask = synthetic_ratings()
    data = prepare_als_data(u, i, r, 40, 30, dp=1)
    X, Y = als_train(data, k=8, reg=0.01, iterations=12)
    assert X.shape == (40, 8) and Y.shape == (30, 8)
    assert rmse_on_observed(X, Y, R, mask) < 0.15


def test_als_mesh_matches_single_device():
    u, i, r, R, mask = synthetic_ratings(n_users=33, n_items=17)
    mesh = create_mesh(MeshSpec(dp=8, mp=1))
    data8 = prepare_als_data(u, i, r, 33, 17, dp=8)
    X8, Y8 = als_train(data8, k=6, reg=0.05, iterations=8, mesh=mesh)
    data1 = prepare_als_data(u, i, r, 33, 17, dp=1)
    X1, Y1 = als_train(data1, k=6, reg=0.05, iterations=8)
    # Factors are not identical (different init partitioning) but the
    # reconstruction they produce must match closely.
    r1 = rmse_on_observed(X1, Y1, R, mask)
    r8 = rmse_on_observed(X8, Y8, R, mask)
    assert abs(r1 - r8) < 0.05
    assert r8 < 0.2


def test_recommend_topk_masks_seen():
    Y = np.eye(4, dtype=np.float32)  # items = axis vectors
    x = np.array([0.9, 0.5, 0.1, 0.0], np.float32)
    seen = np.array([1.0, 0, 0, 0], np.float32)  # best item already seen
    scores, idx = recommend_scores(x, Y, seen, top_k=2)
    assert idx.tolist() == [1, 2]
    bscores, bidx = recommend_batch(x[None], Y, seen[None], top_k=2)
    assert bidx[0].tolist() == [1, 2]


def test_als_empty_rows_are_stable():
    # users/items with no events must not produce NaNs
    u = np.array([0, 0], np.int32)
    i = np.array([0, 1], np.int32)
    r = np.array([1.0, 2.0], np.float32)
    data = prepare_als_data(u, i, r, n_users=5, n_items=4, dp=2)
    X, Y = als_train(data, k=3, reg=0.1, iterations=3)
    assert np.isfinite(X).all() and np.isfinite(Y).all()


# -- implicit-feedback ALS (Hu/Koren; MLlib trainImplicit analogue) ----------


def _implicit_numpy_reference(R, y_init, k, reg, alpha, iters):
    """Direct f64 solve of the implicit normal equations, per row."""
    n_users, n_items = R.shape
    Yn = y_init.astype(np.float64).copy()
    Xn = np.zeros((n_users, k))
    C1 = alpha * R
    P = (R > 0).astype(np.float64)
    for _ in range(iters):
        G = Yn.T @ Yn
        for u in range(n_users):
            n_e = (R[u] > 0).sum()
            A = G + (Yn * C1[u][:, None]).T @ Yn + (reg * max(n_e, 1) + 1e-6) * np.eye(k)
            Xn[u] = np.linalg.solve(A, ((1 + C1[u]) * P[u]) @ Yn)
        G = Xn.T @ Xn
        for i in range(n_items):
            n_e = (R[:, i] > 0).sum()
            A = G + (Xn * C1[:, i][:, None]).T @ Xn + (reg * max(n_e, 1) + 1e-6) * np.eye(k)
            Yn[i] = np.linalg.solve(A, ((1 + C1[:, i]) * P[:, i]) @ Xn)
    return Xn, Yn


def implicit_counts(n_users=30, n_items=20, seed=0):
    rng = np.random.default_rng(seed)
    R = np.zeros((n_users, n_items), np.float32)
    for _ in range(200):
        R[rng.integers(n_users), rng.integers(n_items)] += rng.integers(1, 5)
    u, i = np.nonzero(R)
    return u.astype(np.int32), i.astype(np.int32), R[u, i].astype(np.float32), R


def test_implicit_als_matches_direct_solve():
    from predictionio_tpu.ops import als as als_ops

    u, i, r, R = implicit_counts()
    k, reg, alpha, iters = 4, 0.05, 2.0, 6
    data = prepare_als_data(u, i, r, *R.shape, dp=1)
    X, Y = als_train(data, k=k, reg=reg, iterations=iters, seed=7,
                     implicit=True, alpha=alpha)
    _, y0 = als_ops._als_init(data, k, 7)
    y_init = np.asarray(y0).reshape(-1, k)[: R.shape[1]]
    Xn, Yn = _implicit_numpy_reference(R, y_init, k, reg, alpha, iters)
    pj, pn = X @ Y.T, Xn @ Yn.T
    rel = np.abs(pj - pn).max() / np.abs(pn).max()
    assert rel < 5e-3, f"implicit ALS deviates from direct solve: {rel}"
    # preference recovery: observed cells outrank unobserved on average
    assert pj[R > 0].mean() > 2 * pj[R == 0].mean()


def test_implicit_als_mesh_matches_single_device():
    # Init partitioning differs per dp (as in the explicit mesh test), so
    # compare the preference structure the factorizations recover, not the
    # raw factors.
    u, i, r, R = implicit_counts(seed=3)
    k, reg, alpha, iters = 4, 0.05, 1.5, 8
    d1 = prepare_als_data(u, i, r, *R.shape, dp=1)
    X1, Y1 = als_train(d1, k=k, reg=reg, iterations=iters, seed=7,
                       implicit=True, alpha=alpha)
    mesh = create_mesh(MeshSpec(dp=8, mp=1))
    d8 = prepare_als_data(u, i, r, *R.shape, dp=8)
    X8, Y8 = als_train(d8, k=k, reg=reg, iterations=iters, seed=7, mesh=mesh,
                       implicit=True, alpha=alpha)
    p1, p8 = X1 @ Y1.T, X8 @ Y8.T

    def separation(p):
        return float(p[R > 0].mean() - p[R == 0].mean())

    s1, s8 = separation(p1), separation(p8)
    assert s1 > 0 and s8 > 0
    assert abs(s1 - s8) / max(s1, s8) < 0.15, (s1, s8)
