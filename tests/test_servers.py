"""REST server tests: event ingestion + query serving over real HTTP
(reference analogues: EventServiceSpec and the integration harness's
deploy/query loop — SURVEY.md §4)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.api.event_server import run_event_server
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.storage import AccessKey, App


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


@pytest.fixture()
def event_server(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "esapp"))
    key = mem_storage.access_keys.insert(AccessKey("", app_id, []))
    restricted = mem_storage.access_keys.insert(AccessKey("", app_id, ["view"]))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=mem_storage,
                             background=True)
    port = httpd.server_address[1]
    yield {"base": f"http://127.0.0.1:{port}", "key": key,
           "restricted": restricted, "app_id": app_id, "storage": mem_storage}
    httpd.shutdown()
    httpd.server_close()


def test_event_server_alive(event_server):
    import os

    status, body = http("GET", event_server["base"] + "/")
    assert status == 200 and body["status"] == "alive"
    assert body["pid"] == os.getpid()   # identifies the serving worker


def test_post_and_get_event(event_server):
    base, key = event_server["base"], event_server["key"]
    status, body = http("POST", f"{base}/events.json?accessKey={key}", {
        "event": "buy", "entityType": "user", "entityId": "u1",
        "targetEntityType": "item", "targetEntityId": "i1",
        "properties": {"price": 9.99},
    })
    assert status == 201 and "eventId" in body
    eid = body["eventId"]
    status, got = http("GET", f"{base}/events/{eid}.json?accessKey={key}")
    assert status == 200 and got["event"] == "buy" and got["properties"]["price"] == 9.99
    # find with filters
    status, found = http("GET", f"{base}/events.json?accessKey={key}&event=buy")
    assert status == 200 and len(found) == 1
    status, none = http("GET", f"{base}/events.json?accessKey={key}&event=view")
    assert status == 200 and none == []
    # delete
    status, _ = http("DELETE", f"{base}/events/{eid}.json?accessKey={key}")
    assert status == 200
    status, _ = http("GET", f"{base}/events/{eid}.json?accessKey={key}")
    assert status == 404


def test_auth_rejections(event_server):
    base = event_server["base"]
    status, body = http("POST", f"{base}/events.json", {"event": "x"})
    assert status == 401
    status, body = http("POST", f"{base}/events.json?accessKey=WRONG", {"event": "x"})
    assert status == 401
    # restricted key may only write "view"
    rk = event_server["restricted"]
    status, _ = http("POST", f"{base}/events.json?accessKey={rk}", {
        "event": "buy", "entityType": "user", "entityId": "u1"})
    assert status == 403
    status, _ = http("POST", f"{base}/events.json?accessKey={rk}", {
        "event": "view", "entityType": "user", "entityId": "u1"})
    assert status == 201


def test_malformed_event_rejected(event_server):
    base, key = event_server["base"], event_server["key"]
    status, body = http("POST", f"{base}/events.json?accessKey={key}", {
        "event": "$set", "entityType": "user", "entityId": "u1",
        "targetEntityType": "item", "targetEntityId": "i1"})
    assert status == 400
    status, body = http("POST", f"{base}/events.json?accessKey={key}", {
        "entityType": "user", "entityId": "u1"})
    assert status == 400


def test_batch_events(event_server):
    base, key = event_server["base"], event_server["key"]
    batch = [
        {"event": "view", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": "i1"}
        for i in range(3)
    ]
    batch.append({"entityType": "user", "entityId": "broken"})  # missing event
    status, results = http("POST", f"{base}/batch/events.json?accessKey={key}", batch)
    assert status == 200
    assert [r["status"] for r in results] == [201, 201, 201, 400]
    # over-limit batch rejected
    status, _ = http("POST", f"{base}/batch/events.json?accessKey={key}",
                     [batch[0]] * 51)
    assert status == 400


def test_stats(event_server):
    base, key = event_server["base"], event_server["key"]
    for _ in range(2):
        http("POST", f"{base}/events.json?accessKey={key}", {
            "event": "rate", "entityType": "user", "entityId": "u1",
            "targetEntityType": "item", "targetEntityId": "i1",
            "properties": {"rating": 5}})
    status, body = http("GET", f"{base}/stats.json?accessKey={key}")
    assert status == 200 and body["counts"].get("rate") == 2


@pytest.fixture()
def deployed_engine(tmp_path, mem_storage):
    """Full loop: ingest ratings → pio-style train → deploy → HTTP query."""
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import deploy
    from predictionio_tpu.models.recommendation import RecommendationEngine
    from predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithmParams, DataSourceParams,
    )
    from predictionio_tpu.controller.engine import EngineParams

    app_id = mem_storage.apps.insert(App(0, "qsapp"))
    events = []
    rng = np.random.default_rng(2)
    for u in range(12):
        for i in range(8):
            liked = (u < 6) == (i < 4)
            if rng.random() < 0.9:
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": 5.0 if liked else 1.0})))
    mem_storage.l_events.insert_batch(events, app_id)

    variant = {
        "id": "qs-engine",
        "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "qsapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 6, "lambda": 0.05,
                                   "meshDp": 1}}],
    }
    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps(variant))

    engine = RecommendationEngine.apply()
    ep = engine.engine_params_from_variant(variant)
    core_workflow.run_train(engine, ep, engine_id="qs-engine", storage=mem_storage)

    httpd = deploy(engine_json=str(engine_json), host="127.0.0.1", port=0,
                   storage=mem_storage, background=True)
    port = httpd.server_address[1]
    yield {"base": f"http://127.0.0.1:{port}", "storage": mem_storage,
           "engine_json": engine_json}
    httpd.shutdown()
    httpd.server_close()


def test_query_server_predicts(deployed_engine):
    base = deployed_engine["base"]
    status, info = http("GET", base + "/")
    assert status == 200 and info["engineId"] == "qs-engine"
    status, res = http("POST", base + "/queries.json", {"user": "u1", "num": 3})
    assert status == 200
    items = [s["item"] for s in res["itemScores"]]
    assert len(items) == 3
    assert all(int(i[1:]) < 4 for i in items), items


def test_query_server_bad_requests(deployed_engine):
    base = deployed_engine["base"]
    status, _ = http("POST", base + "/queries.json", {"num": 3})  # missing user
    assert status == 400
    status, _ = http("POST", base + "/nope.json", {"user": "u1"})
    assert status == 404


def test_query_server_reload(deployed_engine):
    base = deployed_engine["base"]
    status, body = http("GET", base + "/reload")
    assert status == 200 and body["reloaded"]


def test_query_server_web_ui(deployed_engine):
    """GET / with Accept: text/html renders the deploy web UI
    (reference: CreateServer engine-instance info page)."""
    import urllib.request

    req = urllib.request.Request(deployed_engine["base"] + "/",
                                 headers={"Accept": "text/html"})
    body = urllib.request.urlopen(req).read().decode()
    assert "Engine server: qs-engine" in body
    assert "queries.json" in body


def test_cli_undeploy_stops_server(deployed_engine):
    """`pio undeploy` contacts the deployed server's /stop (reference
    Console.undeploy semantics) and reports failure when nothing listens."""
    import urllib.error
    import urllib.request

    from predictionio_tpu.cli.main import main as pio_main

    base = deployed_engine["base"]
    port = int(base.rsplit(":", 1)[1])
    assert pio_main(["undeploy", "--ip", "127.0.0.1",
                     "--port", str(port)]) == 0
    # server is gone: queries now fail at the connection level
    import time

    for _ in range(50):
        try:
            urllib.request.urlopen(base + "/", timeout=2)
            time.sleep(0.1)
        except (urllib.error.URLError, ConnectionError):
            break
    else:
        raise AssertionError("server still reachable after undeploy")
    assert pio_main(["undeploy", "--ip", "127.0.0.1",
                     "--port", str(port), "--timeout", "2"]) == 1


def test_keepalive_unread_body_drained(event_server):
    """An early-error response (401 auth) must not leave the POST body in
    the stream — the next request on the same keep-alive connection is
    parsed from the request line, not body bytes."""
    import http.client
    import json as _json
    from urllib.parse import urlsplit

    base, key = event_server["base"], event_server["key"]
    u = urlsplit(base)
    conn = http.client.HTTPConnection(u.hostname, u.port)
    body = _json.dumps({"event": "buy", "entityType": "user",
                        "entityId": "u1"})
    conn.request("POST", "/events.json?accessKey=WRONG", body,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 401
    r.read()
    # same connection, now a valid request: must succeed, not 400
    conn.request("POST", f"/events.json?accessKey={key}", body,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 201, r.read()
    r.read()
    conn.close()


def test_header_count_cap(event_server):
    """More than 100 headers on one request is rejected, not accumulated."""
    import socket
    from urllib.parse import urlsplit

    u = urlsplit(event_server["base"])
    s = socket.create_connection((u.hostname, u.port))
    req = b"GET / HTTP/1.1\r\nHost: x\r\n"
    req += b"".join(b"X-Flood-%d: y\r\n" % i for i in range(150))
    req += b"\r\n"
    s.sendall(req)
    data = s.recv(65536)
    assert b"400" in data.split(b"\r\n", 1)[0], data[:100]
    s.close()


def test_auto_reload_hot_swaps_on_retrain(tmp_path, mem_storage):
    """MasterActor parity: train -> deploy --auto-reload -> retrain on new
    data -> queries reflect the NEW model with no manual /reload."""
    import time as _time

    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.recommendation import RecommendationEngine
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import deploy

    app_id = mem_storage.apps.insert(App(0, "arapp"))
    rng = np.random.default_rng(4)

    def rate_cluster(flip):
        evs = []
        for u in range(12):
            for i in range(8):
                liked = ((u < 6) == (i < 4)) != flip
                evs.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": 5.0 if liked else 1.0})))
        return evs

    mem_storage.l_events.insert_batch(rate_cluster(False), app_id)
    variant = {
        "id": "ar-engine",
        "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "arapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 6,
                                   "lambda": 0.05, "meshDp": 1}}],
    }
    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps(variant))
    engine = RecommendationEngine.apply()
    ep = engine.engine_params_from_variant(variant)
    core_workflow.run_train(engine, ep, engine_id="ar-engine",
                            storage=mem_storage)
    httpd = deploy(engine_json=str(engine_json), host="127.0.0.1", port=0,
                   storage=mem_storage, background=True, auto_reload=0.05)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        first_instance = httpd.pio_state.instance.id
        status, r1 = http("POST", base + "/queries.json",
                          {"user": "u1", "num": 3})
        assert status == 200 and r1["itemScores"]

        # retrain against flipped preferences: a NEW engine instance
        mem_storage.l_events.insert_batch(rate_cluster(True) * 3, app_id)
        core_workflow.run_train(engine, ep, engine_id="ar-engine",
                                storage=mem_storage)
        deadline = _time.time() + 10
        while (httpd.pio_state.instance.id == first_instance
               and _time.time() < deadline):
            _time.sleep(0.05)
        assert httpd.pio_state.instance.id != first_instance, \
            "watcher never hot-swapped to the retrained instance"
        status, r2 = http("POST", base + "/queries.json",
                          {"user": "u1", "num": 3})
        assert status == 200 and r2["itemScores"]
    finally:
        httpd.pio_state.stop_auto_reload()
        httpd.shutdown()
        httpd.server_close()


def test_java_sdk_wire_format(event_server):
    """Replays the exact requests sdk/java/PredictionIO.java constructs
    (method, path, query, headers, JSON body shape) against a live event
    server — the wire-format contract the Java client compiles against."""
    import http.client
    import json as _json
    from urllib.parse import urlsplit

    base, key = event_server["base"], event_server["key"]
    u = urlsplit(base)
    conn = http.client.HTTPConnection(u.hostname, u.port)

    # EventClient.createEvent: POST /events.json?accessKey=K
    body = ('{"event":"buy","entityType":"user","entityId":"u1",'
            '"targetEntityType":"item","targetEntityId":"i3",'
            '"properties":{"price":9.5}}')
    conn.request("POST", f"/events.json?accessKey={key}", body,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    out = _json.loads(r.read())
    assert r.status == 201 and out["eventId"]
    eid = out["eventId"]

    # EventClient.createEvents: POST /batch/events.json
    batch = _json.dumps([
        {"event": "view", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i9"}])
    conn.request("POST", f"/batch/events.json?accessKey={key}", batch,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    out = _json.loads(r.read())
    assert r.status == 200 and out[0]["status"] == 201

    # EventClient.getEvent: GET /events/{id}.json
    conn.request("GET", f"/events/{eid}.json?accessKey={key}", None,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    got = _json.loads(r.read())
    assert r.status == 200 and got["properties"]["price"] == 9.5

    # EventClient.findEvents: GET /events.json with filters
    conn.request("GET",
                 f"/events.json?accessKey={key}&entityType=user&entityId=u1",
                 None, {"Content-Type": "application/json"})
    r = conn.getresponse()
    found = _json.loads(r.read())
    assert r.status == 200 and len(found) == 2

    # EventClient.deleteEvent: DELETE /events/{id}.json
    conn.request("DELETE", f"/events/{eid}.json?accessKey={key}", None,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 200
    r.read()
    conn.close()


def test_serve_micro_batching_matches_serial(tmp_path, mem_storage, monkeypatch):
    """PIO_SERVE_BATCH=on: concurrent queries coalesce through the
    group-commit micro-batcher with results identical to serial predict
    (the ALS batch path is the serving-batchable case)."""
    import http.client
    import threading as _threading

    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.recommendation import RecommendationEngine
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import deploy

    app_id = mem_storage.apps.insert(App(0, "mbapp"))
    rng = np.random.default_rng(6)
    events = []
    for u in range(30):
        for i in rng.integers(0, 40, 10):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))})))
    mem_storage.l_events.insert_batch(events, app_id)
    variant = {
        "id": "mb-engine",
        "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "mbapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 8, "numIterations": 4,
                                   "lambda": 0.05, "meshDp": 1}}],
    }
    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps(variant))
    engine = RecommendationEngine.apply()
    ep = engine.engine_params_from_variant(variant)
    core_workflow.run_train(engine, ep, engine_id="mb-engine",
                            storage=mem_storage)

    def run_queries(batch_mode):
        monkeypatch.setenv("PIO_SERVE_BATCH", batch_mode)
        httpd = deploy(engine_json=str(engine_json), host="127.0.0.1",
                       port=0, storage=mem_storage, background=True)
        try:
            assert (httpd.pio_state.batcher is not None) == (batch_mode == "on")
            port = httpd.server_address[1]
            results = {}

            def worker(w):
                conn = http.client.HTTPConnection("127.0.0.1", port)
                for u in range(w, 30, 6):
                    conn.request("POST", "/queries.json",
                                 json.dumps({"user": f"u{u}", "num": 5}),
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    assert r.status == 200
                    results[u] = json.loads(r.read())
                conn.close()

            ts = [_threading.Thread(target=worker, args=(w,)) for w in range(6)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            return results
        finally:
            httpd.shutdown()
            httpd.server_close()

    serial = run_queries("off")
    batched = run_queries("on")
    assert serial.keys() == batched.keys() and len(serial) == 30
    for u in serial:
        s = serial[u]["itemScores"]
        b = batched[u]["itemScores"]
        # matvec vs batched-matmul accumulate in different orders: items
        # must match, scores to f32 tolerance
        assert [r["item"] for r in s] == [r["item"] for r in b], (u, s, b)
        np.testing.assert_allclose([r["score"] for r in s],
                                   [r["score"] for r in b], rtol=2e-5)


def test_prefork_workers_share_port_and_die_with_server(tmp_path, monkeypatch):
    """deploy --workers: N processes bind one port via SO_REUSEPORT, all
    answer queries, and children terminate when the parent closes.
    (This VM has one core, so only lifecycle — not scaling — is
    assertable here.)"""
    import http.client
    import time as _time

    store = tmp_path / "store"
    env_vars = {
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(store),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        "PIO_JAX_PLATFORM": "cpu",
    }
    for k, v in env_vars.items():
        monkeypatch.setenv(k, v)
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage
    st = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(store)}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA", "MODELDATA")}))
    set_storage(st)
    try:
        app_id = st.apps.insert(App(0, "pfapp"))
        rng = np.random.default_rng(5)
        evs = []
        for u in range(20):
            for i in rng.integers(0, 30, 6):
                evs.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(rng.integers(1, 6))})))
        st.l_events.insert_batch(evs, app_id)
        variant = {
            "id": "pf-engine",
            "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
            "datasource": {"params": {"appName": "pfapp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 2, "lambda": 0.05, "meshDp": 1}}],
        }
        ej = tmp_path / "engine.json"
        ej.write_text(json.dumps(variant))
        from predictionio_tpu.models.recommendation import RecommendationEngine
        from predictionio_tpu.workflow import core_workflow
        from predictionio_tpu.workflow.create_server import deploy

        engine = RecommendationEngine.apply()
        ep = engine.engine_params_from_variant(variant)
        core_workflow.run_train(engine, ep, engine_id="pf-engine", storage=st)
        httpd = deploy(engine_json=str(ej), host="127.0.0.1", port=0,
                       background=True, workers=2)
        try:
            assert len(httpd.pio_workers) == 1
            port = httpd.server_address[1]
            deadline = _time.time() + 60
            while (httpd.pio_workers[0].poll() is None
                   and _time.time() < deadline):
                # parent serves regardless; just confirm it answers while
                # the child boots
                conn = http.client.HTTPConnection("127.0.0.1", port)
                conn.request("POST", "/queries.json",
                             json.dumps({"user": "u1", "num": 3}),
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                assert r.status == 200
                r.read()
                conn.close()
                _time.sleep(1.0)
                # child came up and stayed: good enough
                if _time.time() > deadline - 50:
                    break
            assert httpd.pio_workers[0].poll() is None, "child worker died"
        finally:
            httpd.shutdown()
            httpd.server_close()
        httpd.pio_workers[0].wait(timeout=10)
        assert httpd.pio_workers[0].poll() is not None
    finally:
        set_storage(None)


def test_http_pipelined_requests(event_server):
    """Two requests written in ONE TCP segment (HTTP/1.1 pipelining) are
    served in order — the lean request loop must consume exact body
    boundaries from the buffered stream."""
    import socket
    from urllib.parse import urlsplit

    u = urlsplit(event_server["base"])
    key = event_server["key"]
    body = json.dumps({"event": "buy", "entityType": "user",
                       "entityId": "u1", "targetEntityType": "item",
                       "targetEntityId": "i1"}).encode()
    one = (b"POST /events.json?accessKey=" + key.encode() +
           b" HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
           b"Content-Length: %d\r\n\r\n" % len(body) + body)
    s = socket.create_connection((u.hostname, u.port))
    s.sendall(one + one)          # pipelined: both before any read
    data = b""
    while data.count(b"HTTP/1.1 201") < 2:
        chunk = s.recv(65536)
        assert chunk, data
        data += chunk
    assert data.count(b'"eventId"') == 2
    s.close()


def test_micro_batcher_isolates_poisoned_query():
    """One failing query must not 500 its batchmates: the leader re-runs
    the batch serially so only the offender errors.  Also covers
    leadership handoff under sustained concurrent load."""
    import threading as _threading

    from predictionio_tpu.workflow.create_server import _MicroBatcher

    def run_one(q):
        if q == "poison":
            raise ValueError("bad query")
        return f"ok:{q}"

    def run_batch(queries):
        return [run_one(q) for q in queries]

    batcher = _MicroBatcher(run_batch, run_one, max_batch=4)
    results = {}
    errors = {}
    gate = _threading.Barrier(8)

    def worker(q):
        gate.wait()
        try:
            results[q] = batcher.predict(q)
        except ValueError as e:
            errors[q] = str(e)

    qs = [f"q{i}" for i in range(7)] + ["poison"]
    ts = [_threading.Thread(target=worker, args=(q,)) for q in qs]
    [t.start() for t in ts]
    [t.join(timeout=30) for t in ts]
    assert errors == {"poison": "bad query"}
    assert results == {f"q{i}": f"ok:q{i}" for i in range(7)}
    # batcher fully drained and leadership released
    assert batcher._queue == [] and not batcher._leader_active


def test_micro_batcher_soak():
    """Stress the leadership-rotation machinery: many threads, many
    queries each, random poisoned queries and randomly slow batches.
    Every query must get exactly its own result (no lost, duplicated, or
    mis-routed responses) and the batcher must fully drain."""
    import random
    import threading as _threading
    import time as _time

    from predictionio_tpu.workflow.create_server import _MicroBatcher

    rng = random.Random(42)  # only the (single) leader calls run_batch

    def run_one(q):
        if q.endswith(":poison"):
            raise ValueError(q)
        return "ok:" + q

    def run_batch(queries):
        if rng.random() < 0.2:          # a slow batch: mid-flight queries
            _time.sleep(0.002)          # must coalesce into the next one
        return [run_one(q) for q in queries]

    batcher = _MicroBatcher(run_batch, run_one, max_batch=6)
    n_threads, n_queries = 12, 30
    results: dict = {}
    errors: dict = {}
    gate = _threading.Barrier(n_threads)

    def worker(tid):
        trng = random.Random(tid)
        gate.wait()
        for seq in range(n_queries):
            q = f"{tid}:{seq}"
            if trng.random() < 0.1:
                q += ":poison"
            try:
                results[q] = batcher.predict(q)
            except ValueError as e:
                errors[q] = str(e)

    ts = [_threading.Thread(target=worker, args=(i,))
          for i in range(n_threads)]
    start = _time.monotonic()
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    elapsed = _time.monotonic() - start
    assert not any(t.is_alive() for t in ts), "soak deadlocked"
    assert elapsed < 30, f"soak took {elapsed:.1f}s — unbounded waits?"
    assert len(results) + len(errors) == n_threads * n_queries
    for q, r in results.items():
        assert r == "ok:" + q, f"mis-routed response: {q} -> {r}"
    for q, e in errors.items():
        assert q.endswith(":poison") and e == q
    assert batcher._queue == [] and not batcher._leader_active


def test_micro_batcher_recovers_when_nudged_waiter_departed(monkeypatch):
    """Regression for the leadership-handoff wedge: a slow batch makes a
    queued waiter hit its wait timeout and depart; the finishing leader
    must RELEASE leadership (not transfer it to the departed thread), so
    the next query can claim it and be served.  Under the old
    transfer-to-queue[0] scheme this left ``_leader_active`` stuck True
    and every later query timed out until restart."""
    import threading as _threading
    import time as _time

    from predictionio_tpu.workflow import create_server as cs

    monkeypatch.setattr(cs, "_WAIT_TIMEOUT_S", 0.2)
    slow_gate = _threading.Event()

    def run_batch(queries):
        if "slow" in queries:
            slow_gate.wait(timeout=10)
        return ["ok:" + q for q in queries]

    batcher = cs._MicroBatcher(run_batch, lambda q: "ok:" + q, max_batch=1)
    res: dict = {}
    errs: list = []

    def leader():
        res["slow"] = batcher.predict("slow")

    def waiter():
        try:
            res["w"] = batcher.predict("w")
        except TimeoutError as e:
            errs.append(e)

    t1 = _threading.Thread(target=leader)
    t1.start()
    _time.sleep(0.05)        # leader claims the lead, blocks in run_batch
    t2 = _threading.Thread(target=waiter)
    t2.start()
    t2.join(timeout=5)       # waiter times out at 0.2 s and departs
    assert not t2.is_alive() and errs, "waiter should have timed out"
    slow_gate.set()
    t1.join(timeout=5)
    assert res["slow"] == "ok:slow"
    # the actual regression check: the batcher must not be wedged
    assert batcher.predict("after") == "ok:after"
    assert batcher._queue == [] and not batcher._leader_active


def test_http_rejects_transfer_encoding(event_server):
    """We never decode chunked bodies — ignoring the header would leave
    chunk bytes in the stream to be parsed as the next pipelined request
    (request smuggling behind a chunked-forwarding proxy).  RFC 9112
    §6.1: 501 + connection close."""
    import socket
    from urllib.parse import urlsplit

    u = urlsplit(event_server["base"])
    key = event_server["key"]
    req = (b"POST /events.json?accessKey=" + key.encode() +
           b" HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
           b"Transfer-Encoding: chunked\r\n\r\n"
           b"5\r\nhello\r\n0\r\n\r\n")
    s = socket.create_connection((u.hostname, u.port))
    s.sendall(req)
    data = b""
    while True:
        chunk = s.recv(65536)
        if not chunk:
            break
        data += chunk
    s.close()
    assert data.startswith(b"HTTP/1.1 501"), data[:80]
    assert b"connection: close" in data.lower()
    # the connection was closed (recv returned b"") — no smuggled parse


def test_micro_batcher_short_batch_result_falls_back_serial():
    """A batch predictor returning the wrong result count must not strand
    any item: the strict zip raises and the serial fallback serves every
    query individually."""
    from predictionio_tpu.workflow.create_server import _MicroBatcher

    def run_batch(queries):
        return ["ok:" + q for q in queries][:-1]   # one short

    batcher = _MicroBatcher(run_batch, lambda q: "one:" + q, max_batch=4)
    assert batcher.predict("a") == "one:a"
    assert batcher._queue == [] and not batcher._leader_active


def test_sdk_event_pipeline(event_server):
    """Pipelined single-event ingestion: many requests in flight on one
    keep-alive socket, responses drained in order; errors are isolated to
    their own handle."""
    from predictionio_tpu.sdk import EventClient

    c = EventClient(event_server["key"], event_server["base"])
    with c.pipeline(depth=16) as p:
        handles = [p.record_user_action_on_item("buy", f"pu{i}", f"pi{i}")
                   for i in range(50)]
        bad = p.create_event("", "", "")          # server rejects: 400
        more = [p.record_user_action_on_item("view", f"pu{i}", f"pi{i}")
                for i in range(10)]
    ids = [h.result()["eventId"] for h in handles]
    assert len(set(ids)) == 50
    import pytest as _pytest

    from predictionio_tpu.sdk import PIOError
    with _pytest.raises(PIOError):
        bad.result()
    assert all(m.result()["eventId"] for m in more)
    # the events actually landed
    got = c.find_events(entityType="user", entityId="pu3")
    assert {e["event"] for e in got} == {"buy", "view"}


def test_sdk_event_pipeline_abort_fails_pending(event_server):
    """Leaving the pipeline context via an exception must fail the
    outstanding handles cleanly (PIOError), not let a later result()
    drain into the closed socket."""
    import pytest as _pytest

    from predictionio_tpu.sdk import EventClient, PIOError

    c = EventClient(event_server["key"], event_server["base"])
    with _pytest.raises(RuntimeError, match="boom"):
        with c.pipeline(depth=64) as p:
            handles = [p.record_user_action_on_item("buy", "au", f"ai{i}")
                       for i in range(5)]
            raise RuntimeError("boom")
    for h in handles:
        assert h.done
        with _pytest.raises(PIOError, match="aborted"):
            h.result()


def test_sdk_event_pipeline_partial_drain_and_close(event_server):
    """result() on an early handle drains only up to it; close() finishes
    the rest; a closed pipeline refuses new sends."""
    import pytest as _pytest

    from predictionio_tpu.sdk import EventClient, PIOError

    c = EventClient(event_server["key"], event_server["base"])
    p = c.pipeline(depth=64)
    handles = [p.record_user_action_on_item("buy", f"du{i}", f"di{i}")
               for i in range(9)]
    # draining handle 2 completes 0..2 but leaves 3.. pending
    assert handles[2].result()["eventId"]
    assert all(h.done for h in handles[:3])
    assert not any(h.done for h in handles[3:])
    p.close()
    assert all(h.done for h in handles)
    assert all(h.result()["eventId"] for h in handles)
    with _pytest.raises(PIOError, match="closed"):
        p.create_event("buy", "user", "x")


def test_sdk_event_pipeline_honors_connection_close():
    """ADVICE r5: a server 'Connection: close' mid-pipeline must fail the
    outstanding handles with the committed-but-unacknowledged message and
    refuse NEW sends — not surface an opaque 'server closed' for
    everything later."""
    import socket as _socket
    import threading as _threading

    import pytest as _pytest

    from predictionio_tpu.sdk import EventClient, PIOError

    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve():
        c, _ = srv.accept()
        buf = b""
        # read until the FIRST request's body is in, then answer it with
        # Connection: close and drop the socket (http_util does exactly
        # this after e.g. an oversized unread body)
        while b"\r\n\r\n" not in buf:
            buf += c.recv(65536)
        head, _, rest = buf.partition(b"\r\n\r\n")
        clen = 0
        for h in head.split(b"\r\n"):
            if h.lower().startswith(b"content-length:"):
                clen = int(h.split(b":")[1])
        while len(rest) < clen:
            rest += c.recv(65536)
        body = b'{"eventId": "first"}'
        c.sendall(b"HTTP/1.1 201 Created\r\nContent-Type: application/json"
                  b"\r\nContent-Length: %d\r\nConnection: close\r\n\r\n"
                  % len(body) + body)
        c.close()
        srv.close()

    t = _threading.Thread(target=serve, daemon=True)
    t.start()
    c = EventClient("k", f"http://127.0.0.1:{port}")
    p = c.pipeline(depth=64)
    first = p.record_user_action_on_item("buy", "u1", "i1")
    rest = [p.record_user_action_on_item("buy", "u1", f"i{i}")
            for i in range(2, 5)]
    # draining the first handle reads its response AND sees the close
    assert first.result()["eventId"] == "first"
    for h in rest:
        assert h.done
        with _pytest.raises(PIOError, match="Connection: close"):
            h.result()
    # fail fast on new sends after the server signaled close
    with _pytest.raises(PIOError, match="closed"):
        p.record_user_action_on_item("buy", "u1", "i9")
    t.join(timeout=10)


def _rst_close(c):
    import socket as _socket
    import struct

    c.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                 struct.pack("ii", 1, 0))   # linger 0 => RST on close
    c.close()


def test_undeploy_mid_response_death_counts_as_stop():
    """A query server that dies while answering its own /stop (partial
    response then reset, port then dead) must still be reported as
    undeployed — the reset WAS the stop."""
    import socket
    import threading

    from predictionio_tpu.cli.main import main as pio_main

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]

    def one_shot():
        c, _ = srv.accept()
        c.recv(65536)
        c.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 50\r\n\r\n{")
        _rst_close(c)          # truncated body + RST
        srv.close()            # port goes dead: the server is gone

    threading.Thread(target=one_shot, daemon=True).start()
    rc = pio_main(["undeploy", "--ip", "127.0.0.1", "--port", str(port),
                   "--timeout", "2"])
    assert rc == 0


def test_undeploy_persistent_resetter_reports_failure():
    """A listener that keeps dropping /stop mid-response while STAYING
    on the port (not a query server) must not be reported as undeployed."""
    import socket
    import threading

    from predictionio_tpu.cli.main import main as pio_main

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(64)
    port = srv.getsockname()[1]
    alive = True

    def reset_loop():
        preamble = True
        while alive:
            try:
                c, _ = srv.accept()
                c.recv(65536)
                if preamble:   # alternate: with and without any response
                    c.sendall(
                        b"HTTP/1.1 200 OK\r\nContent-Length: 50\r\n\r\n{")
                preamble = not preamble
                _rst_close(c)
            except OSError:
                return

    t = threading.Thread(target=reset_loop, daemon=True)
    t.start()
    try:
        rc = pio_main(["undeploy", "--ip", "127.0.0.1", "--port", str(port),
                       "--timeout", "2"])
        assert rc == 1
    finally:
        alive = False
        srv.close()
