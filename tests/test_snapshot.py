"""Columnar event-store snapshots: build/scan parity, crash safety,
tombstone correctness, delta-aware retrain, multi-writer reuse, and the
scan prefilter / dictionary-merge satellites."""

import datetime as dt
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.storage import App
from predictionio_tpu.storage.localfs import FSEvents
from predictionio_tpu.store.columnar import (
    EventBatch,
    EventIdColumn,
    read_batch,
    write_batch,
)

REPO = Path(__file__).resolve().parent.parent


def ts(h):
    return dt.datetime(2026, 1, 1, h % 24, tzinfo=dt.timezone.utc)


def mixed_events(n):
    """Interactions + $set property events covering every prop kind."""
    out = []
    for k in range(n):
        if k % 5 == 4:
            out.append(Event(
                event="$set", entity_type="item", entity_id=f"i{k % 7}",
                event_time=ts(k),
                properties=DataMap({
                    "color": "red" if k % 2 else "blue",
                    "sizes": ["s", "m"], "stock": k, "active": bool(k % 2),
                    "meta": {"a": k % 3}, "none": None})))
        else:
            out.append(Event(
                event="buy" if k % 2 else "view", entity_type="user",
                entity_id=f"u{k % 11}", target_entity_type="item",
                target_entity_id=f"i{k % 7}", event_time=ts(k),
                properties=DataMap({"rating": float(k % 5)})))
    return out


def batch_tuples(batch):
    """Order-insensitive row signature of a columnar batch."""
    rows = []
    for j in range(len(batch)):
        rows.append((
            batch.event_dict.str(int(batch.event_codes[j])),
            batch.entity_type_dict.str(int(batch.entity_type_codes[j])),
            batch.entity_dict.str(int(batch.entity_ids[j])),
            batch.target_dict.str(int(batch.target_ids[j]))
            if batch.target_ids[j] >= 0 else None,
            int(batch.times_us[j]),
        ))
    return sorted(rows)


def event_tuples(events):
    return sorted(
        (e.event, e.entity_type, e.entity_id, e.target_entity_id,
         int(e.event_time.timestamp() * 1e6))
        for e in events)


@pytest.fixture()
def small_segments(monkeypatch):
    import predictionio_tpu.storage.localfs as lfs

    monkeypatch.setattr(lfs, "SEGMENT_MAX_BYTES", 4000)


@pytest.fixture()
def fsev(tmp_path, small_segments):
    return FSEvents(tmp_path / "store")


# -- container round trip ----------------------------------------------------


def test_columnar_container_roundtrip(tmp_path):
    evs = mixed_events(60)
    batch = EventBatch.from_events(evs)
    ids = EventIdColumn.from_ids([e.event_id for e in evs])
    p = tmp_path / "b.pioc"
    write_batch(p, batch, ids, meta={"x": 1})
    loaded, lids, meta = read_batch(p)
    assert meta == {"x": 1}
    assert batch_tuples(loaded) == batch_tuples(batch)
    assert lids.tolist() == [e.event_id for e in evs]
    assert np.allclose(np.asarray(loaded.ratings),
                       np.asarray(batch.ratings), equal_nan=True)


def test_columnar_container_rejects_torn_file(tmp_path):
    evs = mixed_events(30)
    p = tmp_path / "b.pioc"
    write_batch(p, EventBatch.from_events(evs),
                EventIdColumn.from_ids([e.event_id for e in evs]))
    data = p.read_bytes()
    p.write_bytes(data[: len(data) // 2])    # torn mid-columns
    with pytest.raises(ValueError):
        read_batch(p)
    p.write_bytes(b"garbage-not-a-snapshot")
    with pytest.raises(ValueError):
        read_batch(p)


# -- build + scan parity -----------------------------------------------------


def test_build_scan_parity_and_props(fsev):
    evs = mixed_events(300)
    fsev.insert_batch(evs, 1)
    stats = fsev.build_snapshot(1)
    assert stats["events"] == 300
    res = fsev.snapshot_scan(1)
    assert res is not None and res["tail_events"] == 0
    assert batch_tuples(res["batch"]) == event_tuples(fsev.scan(1))
    # property folding parity: columnar fold over the snapshot batch ==
    # row-event aggregation over the log
    from predictionio_tpu.store.columnar import fold_properties

    folded = {k: dict(v)
              for k, v in fold_properties(res["batch"], "item").items()}
    agg = {k: dict(v)
           for k, v in fsev.aggregate_properties(1, "item").items()}
    assert folded == agg


def test_tail_is_spliced_after_build(fsev):
    fsev.insert_batch(mixed_events(100), 1)
    fsev.build_snapshot(1)
    fsev.insert_batch([Event(event="buy", entity_type="user",
                             entity_id=f"tail{k}", target_entity_type="item",
                             target_entity_id="i0", event_time=ts(k))
                       for k in range(17)], 1)
    res = fsev.snapshot_scan(1)
    assert res is not None
    assert res["snap_events"] == 100 and res["tail_events"] == 17
    assert batch_tuples(res["batch"]) == event_tuples(fsev.scan(1))
    # the tail extends the snapshot's dictionaries in place (shared-dict
    # concat fast path): no duplicate entity strings, codes stay aligned
    ent = res["batch"].entity_dict
    assert ent.id("tail0") is not None


def test_find_batches_serves_snapshot_with_filters(fsev):
    fsev.insert_batch(mixed_events(200), 1)
    fsev.build_snapshot(1)
    out = list(fsev.find_batches(1, event_names=["buy"]))
    assert len(out) == 1
    want = event_tuples(fsev.scan(1, event_names=["buy"]))
    assert batch_tuples(out[0]) == want
    # unsupported filter (target_entity_type) falls back to the scan path
    out2 = list(fsev.find_batches(1, target_entity_type="item"))
    got = sorted(batch_tuples(b)[0] for b in out2 if len(b))
    assert got  # scanned rows exist; fallback produced real batches


# -- tombstones --------------------------------------------------------------


def test_tombstoned_events_never_resurface(fsev):
    evs = mixed_events(120)
    fsev.insert_batch(evs, 1)
    fsev.build_snapshot(1)
    tail = [Event(event="buy", entity_type="user", entity_id="late",
                  target_entity_type="item", target_entity_id="i1",
                  event_time=ts(3)) for _ in range(3)]
    fsev.insert_batch(tail, 1)
    # delete one event covered by the PRE-delete snapshot and one in the tail
    assert fsev.delete(evs[10].event_id, 1)
    assert fsev.delete(tail[1].event_id, 1)
    res = fsev.snapshot_scan(1)
    assert res is not None
    assert len(res["batch"]) == 120 + 3 - 2
    assert res["ids"].index_of(evs[10].event_id) == -1
    assert res["ids"].index_of(tail[1].event_id) == -1
    assert batch_tuples(res["batch"]) == event_tuples(fsev.scan(1))
    # rebuilding folds the tombstones in permanently
    fsev.build_snapshot(1)
    res2 = fsev.snapshot_scan(1)
    assert len(res2["batch"]) == 121 and res2["tail_events"] == 0


def test_recreated_segments_invalidate_snapshot_and_watermark(fsev, tmp_path):
    """data-delete + re-import restarts segment numbering, so a stale
    manifest (e.g. left by an auto-build racing the delete) points its
    byte offsets into a DIFFERENT file generation under the same names.
    The head fingerprint must turn that into a miss — never a crash, and
    never the old app's events."""
    import shutil

    fsev.insert_batch(mixed_events(60), 1)
    fsev.build_snapshot(1)
    res = fsev.snapshot_scan(1)
    wm, heads = res["watermark"], res["heads"]
    d = fsev._chan_dir(1, None)
    saved = tmp_path / "stale_snapshot"
    shutil.copytree(d / "snapshot", saved)
    fsev.remove(1)
    fsev.init(1)
    fsev.insert_batch(mixed_events(400), 1)   # bigger: offsets "fit" again
    shutil.copytree(saved, d / "snapshot")    # the race's stale leftovers
    assert fsev.snapshot_scan(1) is None      # head mismatch → clean miss
    # a retained pre-delete watermark (delta cache) is equally invalid
    assert fsev.scan_tail_from(1, None, wm, heads=heads) is None
    assert len(list(fsev.scan(1))) == 400


def test_compaction_invalidates_snapshot(fsev):
    evs = mixed_events(80)
    fsev.insert_batch(evs, 1)
    fsev.build_snapshot(1)
    fsev.delete(evs[0].event_id, 1)
    fsev.compact(1)                    # rewrites segments, clears tombstones
    assert fsev.snapshot_scan(1) is None     # stale manifest → miss, not lies
    assert len(list(fsev.scan(1))) == 79
    fsev.build_snapshot(1)
    res = fsev.snapshot_scan(1)
    assert res is not None and len(res["batch"]) == 79


# -- crash safety ------------------------------------------------------------


def _spawn_slow_build(root: Path, delay: str):
    script = (
        "import os, sys\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        f"os.environ['PIO_SNAPSHOT_TEST_DELAY_S'] = {delay!r}\n"
        "from pathlib import Path\n"
        "from predictionio_tpu.storage.localfs import FSEvents\n"
        f"fs = FSEvents(Path({str(root)!r}))\n"
        "print('START', flush=True)\n"
        "fs.build_snapshot(1)\n"
        "print('DONE', flush=True)\n"
    )
    return subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True)


def test_sigkill_mid_build_leaves_store_readable(fsev, tmp_path):
    evs = mixed_events(60)
    fsev.insert_batch(evs, 1)
    fsev.build_snapshot(1)
    before = (fsev._chan_dir(1, None) / "snapshot" / "manifest.json").read_text()
    fsev.insert_batch(mixed_events(400), 1)

    proc = _spawn_slow_build(tmp_path / "store", "0.02")
    assert proc.stdout.readline().strip() == "START"
    time.sleep(1.0)                  # well inside the ~9s parse window
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    snap_dir = fsev._chan_dir(1, None) / "snapshot"
    # manifest unchanged: the kill hit before the atomic flip
    assert (snap_dir / "manifest.json").read_text() == before
    # store fully readable; the old snapshot serves, new events as tail
    res = fsev.snapshot_scan(1)
    assert res is not None
    assert res["snap_events"] == 60 and res["tail_events"] == 400
    assert len(list(fsev.scan(1))) == 460
    # next build succeeds and cleans the orphaned tmp file
    fsev.build_snapshot(1)
    assert not list(snap_dir.glob("*.tmp*"))
    res2 = fsev.snapshot_scan(1)
    assert res2["snap_events"] == 460 and res2["tail_events"] == 0


def test_torn_snapshot_quarantined_and_rebuilt(fsev):
    fsev.insert_batch(mixed_events(90), 1)
    fsev.build_snapshot(1)
    snap_dir = fsev._chan_dir(1, None) / "snapshot"
    m = json.loads((snap_dir / "manifest.json").read_text())
    snap_file = snap_dir / m["snapshot"]
    data = snap_file.read_bytes()
    snap_file.write_bytes(data[: len(data) // 3])    # torn file
    assert fsev.snapshot_scan(1) is None             # miss, store readable
    assert len(list(fsev.scan(1))) == 90
    assert list(snap_dir.glob("*.quarantine"))       # set aside
    assert not (snap_dir / "manifest.json").exists()
    fsev.build_snapshot(1)                           # next trigger rebuilds
    res = fsev.snapshot_scan(1)
    assert res is not None and len(res["batch"]) == 90


def test_concurrent_build_is_exactly_once(fsev, tmp_path):
    from predictionio_tpu.storage.snapshot import LOCK, SNAP_DIR

    fsev.insert_batch(mixed_events(500), 1)
    # hold the builder's flock from another process and only signal once
    # it is HELD — deterministic, unlike the old fixed sleep (which raced
    # the child's startup under suite load) or probing the lock from here
    # (a probe's own momentary exclusive flock could steal the child's
    # single acquisition attempt)
    lock_path = fsev._chan_dir(1, None) / SNAP_DIR / LOCK
    lock_path.parent.mkdir(parents=True, exist_ok=True)
    script = (
        "import fcntl, sys, time\n"
        f"f = open({str(lock_path)!r}, 'a')\n"
        "fcntl.flock(f.fileno(), fcntl.LOCK_EX)\n"
        "print('LOCKED', flush=True)\n"
        "time.sleep(120)\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "LOCKED"
        with pytest.raises(RuntimeError, match="already in progress"):
            fsev.build_snapshot(1)
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
    # lock released with the holder: the next build succeeds
    assert fsev.build_snapshot(1)["events"] == 500


# -- delta-aware retrain -----------------------------------------------------


def test_delta_retrain_restages_only_new_events(fsev, tmp_path, monkeypatch):
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage
    from predictionio_tpu.store.event_store import (
        PEventStore, invalidate_staging_cache, staging_counts,
    )

    storage = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(tmp_path / "store2")}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA", "MODELDATA")},
    ))
    set_storage(storage)
    invalidate_staging_cache()
    try:
        app_id = storage.apps.insert(App(0, "deltaapp"))
        evs = mixed_events(250)
        storage.l_events.insert_batch(evs, app_id)
        storage.l_events.build_snapshot(app_id)
        c0 = staging_counts()
        b1 = PEventStore.batch("deltaapp", storage=storage)
        c1 = staging_counts()
        assert len(b1) == 250
        assert c1["snapshot"] - c0["snapshot"] == 250
        # retrain after 13 new events: EXACTLY 13 staged, all from delta
        storage.l_events.insert_batch(
            [Event(event="buy", entity_type="user", entity_id=f"d{k}",
                   target_entity_type="item", target_entity_id="i0")
             for k in range(13)], app_id)
        b2 = PEventStore.batch("deltaapp", storage=storage)
        c2 = staging_counts()
        assert len(b2) == 263
        assert c2["delta"] - c1["delta"] == 13
        assert c2["snapshot"] - c1["snapshot"] == 0
        assert c2["tail"] - c1["tail"] == 0
        # a delete invalidates the retained batch: full restage, honored
        victim = evs[5].event_id
        storage.l_events.delete(victim, app_id)
        b3 = PEventStore.batch("deltaapp", storage=storage)
        assert len(b3) == 262
        # the kill switch forces the old full path
        monkeypatch.setenv("PIO_DELTA_STAGING", "off")
        invalidate_staging_cache()
        c3 = staging_counts()
        b4 = PEventStore.batch("deltaapp", storage=storage)
        c4 = staging_counts()
        assert len(b4) == 262 and c4["delta"] == c3["delta"]
    finally:
        invalidate_staging_cache()
        set_storage(None)


# -- multi-writer / sharedfs -------------------------------------------------


def test_sharedfs_reuses_snapshot_across_writer_tags(tmp_path, small_segments):
    from predictionio_tpu.storage.sharedfs import SharedFSEvents

    a = SharedFSEvents(tmp_path / "shared", writer_tag="hostA-1")
    b = SharedFSEvents(tmp_path / "shared", writer_tag="hostB-2")
    a.insert_batch(mixed_events(80), 1)
    b.insert_batch(mixed_events(40), 1)
    stats = a.build_snapshot(1)            # host A builds
    assert stats["events"] == 120
    res = b.snapshot_scan(1)               # host B mmap-loads A's snapshot
    assert res is not None and res["snap_events"] == 120
    assert res["manifest"]["writer"] == "hostA-1"
    # host B keeps ingesting; its tail rides on A's snapshot
    b.insert_batch(mixed_events(10), 1)
    res2 = b.snapshot_scan(1)
    assert res2["tail_events"] == 10
    assert batch_tuples(res2["batch"]) == event_tuples(b.scan(1))


def test_auto_trigger_builds_in_background(tmp_path, small_segments,
                                           monkeypatch):
    monkeypatch.setenv("PIO_SNAPSHOT_SEGMENTS", "2")
    fs = FSEvents(tmp_path / "store")
    snap_dir = fs._chan_dir(1, None) / "snapshot"
    # many small appends force rotations past the 4000-byte cap
    for k in range(40):
        fs.insert_batch(mixed_events(10), 1)
        if (snap_dir / "manifest.json").exists():
            break
    deadline = time.time() + 10
    while time.time() < deadline:
        if (snap_dir / "manifest.json").exists():
            break
        time.sleep(0.1)
    assert (snap_dir / "manifest.json").exists(), \
        "auto-trigger never built a snapshot"
    deadline = time.time() + 10
    while time.time() < deadline:     # wait out an in-flight build
        res = fs.snapshot_scan(1)
        if res is not None:
            break
        time.sleep(0.1)
    assert res is not None
    assert batch_tuples(res["batch"]) == event_tuples(fs.scan(1))


# -- integrity script + stats surface ---------------------------------------


def test_check_snapshot_integrity_script(fsev, tmp_path):
    evs = mixed_events(150)
    fsev.insert_batch(evs, 1)
    fsev.delete(evs[3].event_id, 1)     # applied tombstone
    fsev.build_snapshot(1)
    root = str(tmp_path / "store")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_snapshot_integrity.py"),
         root], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 snapshot(s)" in r.stdout
    # corrupt the watermark → the script must catch it
    mp = fsev._chan_dir(1, None) / "snapshot" / "manifest.json"
    m = json.loads(mp.read_text())
    m["events"] += 1
    mp.write_text(json.dumps(m))
    r2 = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_snapshot_integrity.py"),
         root], capture_output=True, text=True)
    assert r2.returncode == 1
    assert "watermark" in r2.stderr


def test_event_server_stats_reports_snapshot_coverage(tmp_path,
                                                      small_segments):
    import urllib.request

    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.storage import AccessKey
    from predictionio_tpu.storage.locator import Storage, StorageConfig

    storage = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(tmp_path / "store")}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA", "MODELDATA")},
    ))
    app_id = storage.apps.insert(App(0, "statsapp"))
    key = storage.access_keys.insert(AccessKey("", app_id, []))
    storage.l_events.insert_batch(mixed_events(50), app_id)
    storage.l_events.build_snapshot(app_id)
    storage.l_events.insert_batch(mixed_events(5), app_id)
    httpd = run_event_server(host="127.0.0.1", port=0, storage=storage,
                             background=True)
    try:
        port = httpd.server_address[1]
        doc = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats.json?accessKey={key}"))
        snap = doc["snapshot"][""]
        assert snap["events"] == 50 and snap["tailEvents"] == 5
        assert 0 < snap["coverage"] < 1
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- satellites: scan prefilter + dictionary merge ---------------------------


def test_scan_prefilter_parity(fsev):
    """Name-filtered scans must return exactly what an unfiltered scan +
    Python filter returns, including adversarial property values that
    CONTAIN the needle text (false positives must be re-filtered) and
    unicode event names (escaping must match the writers')."""
    evs = [
        Event(event="buy", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1"),
        Event(event="view", entity_type="user", entity_id="u2",
              # property value that embeds the needle for "buy"
              properties=DataMap({"note": '"event":"buy"'})),
        Event(event="café", entity_type="user", entity_id="u3"),
        Event(event="buyer", entity_type="user", entity_id="u4"),
    ]
    fsev.insert_batch(evs, 1)
    for names in (["buy"], ["view"], ["café"], ["buy", "café"],
                  ["missing"]):
        got = sorted(e.event_id for e in fsev.scan(1, event_names=names))
        want = sorted(e.event_id for e in fsev.scan(1)
                      if e.event in names)
        assert got == want, names


def test_concat_shared_dict_fast_path_matches_slow_path():
    evs = mixed_events(50)
    a = EventBatch.from_events(evs[:30])
    # tail staged into a's dictionaries (the snapshot+tail contract)
    from predictionio_tpu.storage.snapshot import ColumnarBuilder

    builder = ColumnarBuilder(base=a)
    for e in evs[30:]:
        builder.add(json.loads(e.to_json_line()))
    b, _ids = builder.finish()
    fast = EventBatch.concat([a, b])
    assert fast.event_dict is a.event_dict          # no dict rebuild
    slow = EventBatch.concat([EventBatch.from_events(evs[:30]),
                              EventBatch.from_events(evs[30:])])
    assert batch_tuples(fast) == batch_tuples(slow) == event_tuples(evs)


def test_iddict_encode_lookup_roundtrip():
    from predictionio_tpu.store.columnar import IdDict

    d = IdDict(["a", "b"])
    codes = d.encode(["b", "c", "a", "c", "d"])
    assert codes.tolist() == [1, 2, 0, 2, 3]
    assert d.lookup_many(["a", "zz", "d"]).tolist() == [0, -1, 3]
