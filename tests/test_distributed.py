"""Multi-host runtime helpers (reference analogue: Spark cluster topology
config; here jax.distributed + host-sharded ingest)."""

import jax
import numpy as np
import pytest

from predictionio_tpu.parallel.distributed import (
    DistributedConfig,
    init_distributed,
    process_local_rows,
    shard_segments,
)
from predictionio_tpu.parallel.mesh import MeshSpec, create_mesh


def test_config_from_env(monkeypatch):
    monkeypatch.delenv("PIO_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("PIO_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("PIO_PROCESS_ID", raising=False)
    cfg = DistributedConfig.from_env()
    assert cfg.num_processes == 1 and cfg.process_id == 0
    assert not cfg.is_multi_process

    monkeypatch.setenv("PIO_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    monkeypatch.setenv("PIO_NUM_PROCESSES", "4")
    monkeypatch.setenv("PIO_PROCESS_ID", "2")
    cfg = DistributedConfig.from_env()
    assert cfg.is_multi_process
    assert cfg.coordinator_address == "10.0.0.1:8476"
    assert (cfg.num_processes, cfg.process_id) == (4, 2)


def test_init_single_process_noop():
    cfg = init_distributed(DistributedConfig(None, 1, 0))
    assert not cfg.is_multi_process
    # jax still works, nothing was torn down
    assert len(jax.devices()) >= 1


def test_shard_segments_partition():
    segs = [f"seg-{i:05d}" for i in range(23)]
    shares = [shard_segments(segs, n_processes=4, process_id=p) for p in range(4)]
    # full coverage, no overlap
    merged = sorted(s for share in shares for s in share)
    assert merged == sorted(segs)
    # strided balance: share sizes differ by at most 1
    sizes = [len(s) for s in shares]
    assert max(sizes) - min(sizes) <= 1
    # deterministic
    assert shares[1] == shard_segments(segs, n_processes=4, process_id=1)


def test_shard_segments_bad_process():
    with pytest.raises(ValueError):
        shard_segments([1, 2], n_processes=2, process_id=2)


def test_process_local_rows_single_host_mesh():
    mesh = create_mesh(MeshSpec(dp=8, mp=1), devices=jax.devices()[:8])
    start, stop = process_local_rows(800, mesh)
    # single process owns every dp shard
    assert (start, stop) == (0, 800)
    with pytest.raises(ValueError):
        process_local_rows(801, mesh)


def test_process_local_rows_simulated_two_hosts(monkeypatch):
    """Pretend the mesh's second dp half belongs to another process."""
    import predictionio_tpu.parallel.distributed as dist

    mesh = create_mesh(MeshSpec(dp=8, mp=1), devices=jax.devices()[:8])
    devs = list(mesh.devices.flatten())
    half = {id(d) for d in devs[4:]}

    class FakeDev:
        def __init__(self, dev, pidx):
            self._dev = dev
            self.process_index = pidx

    fake = np.array(
        [FakeDev(d, 1 if id(d) in half else 0) for d in devs]
    ).reshape(mesh.devices.shape)

    class FakeMesh:
        shape = {"dp": 8}
        devices = fake

    monkeypatch.setattr(dist, "process_index", lambda: 0)
    assert process_local_rows(800, FakeMesh()) == (0, 400)
    monkeypatch.setattr(dist, "process_index", lambda: 1)
    assert process_local_rows(800, FakeMesh()) == (400, 800)


def test_batch_local_shard(mem_storage, monkeypatch):
    """PEventStore.batch(local_shard=True) reads only this process's stride."""
    import predictionio_tpu.parallel.distributed as dist
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.store.event_store import PEventStore

    app_id = mem_storage.apps.insert(App(0, "shardapp"))
    events = [
        Event(event="view", entity_type="user", entity_id=f"u{i}",
              target_entity_type="item", target_entity_id=f"i{i % 5}")
        for i in range(10)
    ]
    mem_storage.l_events.insert_batch(events, app_id)

    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setattr(dist, "process_index", lambda: 0)
    b0 = PEventStore.batch("shardapp", storage=mem_storage, local_shard=True)
    monkeypatch.setattr(dist, "process_index", lambda: 1)
    b1 = PEventStore.batch("shardapp", storage=mem_storage, local_shard=True)
    assert len(b0) + len(b1) == 10
    assert len(b0) == 5 and len(b1) == 5
    full = PEventStore.batch("shardapp", storage=mem_storage)
    assert len(full) == 10


def test_process_local_rows_mp_mesh():
    """mp > 1 duplicates each dp position across mp columns; still contiguous."""
    mesh = create_mesh(MeshSpec(dp=4, mp=2), devices=jax.devices()[:8])
    assert process_local_rows(400, mesh) == (0, 400)
