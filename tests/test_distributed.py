"""Multi-host runtime helpers (reference analogue: Spark cluster topology
config; here jax.distributed + host-sharded ingest)."""

import jax
import numpy as np
import pytest

from predictionio_tpu.parallel.distributed import (
    DistributedConfig,
    init_distributed,
    process_local_rows,
    shard_segments,
)
from predictionio_tpu.parallel.mesh import MeshSpec, create_mesh


def test_config_from_env(monkeypatch):
    monkeypatch.delenv("PIO_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("PIO_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("PIO_PROCESS_ID", raising=False)
    cfg = DistributedConfig.from_env()
    assert cfg.num_processes == 1 and cfg.process_id == 0
    assert not cfg.is_multi_process

    monkeypatch.setenv("PIO_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    monkeypatch.setenv("PIO_NUM_PROCESSES", "4")
    monkeypatch.setenv("PIO_PROCESS_ID", "2")
    cfg = DistributedConfig.from_env()
    assert cfg.is_multi_process
    assert cfg.coordinator_address == "10.0.0.1:8476"
    assert (cfg.num_processes, cfg.process_id) == (4, 2)


def test_init_single_process_noop():
    cfg = init_distributed(DistributedConfig(None, 1, 0))
    assert not cfg.is_multi_process
    # jax still works, nothing was torn down
    assert len(jax.devices()) >= 1


def test_shard_segments_partition():
    segs = [f"seg-{i:05d}" for i in range(23)]
    shares = [shard_segments(segs, n_processes=4, process_id=p) for p in range(4)]
    # full coverage, no overlap
    merged = sorted(s for share in shares for s in share)
    assert merged == sorted(segs)
    # strided balance: share sizes differ by at most 1
    sizes = [len(s) for s in shares]
    assert max(sizes) - min(sizes) <= 1
    # deterministic
    assert shares[1] == shard_segments(segs, n_processes=4, process_id=1)


def test_shard_segments_bad_process():
    with pytest.raises(ValueError):
        shard_segments([1, 2], n_processes=2, process_id=2)


def test_process_local_rows_single_host_mesh():
    mesh = create_mesh(MeshSpec(dp=8, mp=1), devices=jax.devices()[:8])
    start, stop = process_local_rows(800, mesh)
    # single process owns every dp shard
    assert (start, stop) == (0, 800)
    with pytest.raises(ValueError):
        process_local_rows(801, mesh)


def test_process_local_rows_simulated_two_hosts(monkeypatch):
    """Pretend the mesh's second dp half belongs to another process."""
    import predictionio_tpu.parallel.distributed as dist

    mesh = create_mesh(MeshSpec(dp=8, mp=1), devices=jax.devices()[:8])
    devs = list(mesh.devices.flatten())
    half = {id(d) for d in devs[4:]}

    class FakeDev:
        def __init__(self, dev, pidx):
            self._dev = dev
            self.process_index = pidx

    fake = np.array(
        [FakeDev(d, 1 if id(d) in half else 0) for d in devs]
    ).reshape(mesh.devices.shape)

    class FakeMesh:
        shape = {"dp": 8}
        devices = fake

    monkeypatch.setattr(dist, "process_index", lambda: 0)
    assert process_local_rows(800, FakeMesh()) == (0, 400)
    monkeypatch.setattr(dist, "process_index", lambda: 1)
    assert process_local_rows(800, FakeMesh()) == (400, 800)


def test_batch_local_shard(mem_storage, monkeypatch):
    """PEventStore.batch(local_shard=True) reads only this process's stride."""
    import predictionio_tpu.parallel.distributed as dist
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.store.event_store import PEventStore

    app_id = mem_storage.apps.insert(App(0, "shardapp"))
    events = [
        Event(event="view", entity_type="user", entity_id=f"u{i}",
              target_entity_type="item", target_entity_id=f"i{i % 5}")
        for i in range(10)
    ]
    mem_storage.l_events.insert_batch(events, app_id)

    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setattr(dist, "process_index", lambda: 0)
    b0 = PEventStore.batch("shardapp", storage=mem_storage, local_shard=True)
    monkeypatch.setattr(dist, "process_index", lambda: 1)
    b1 = PEventStore.batch("shardapp", storage=mem_storage, local_shard=True)
    assert len(b0) + len(b1) == 10
    assert len(b0) == 5 and len(b1) == 5
    full = PEventStore.batch("shardapp", storage=mem_storage)
    assert len(full) == 10


def test_process_local_rows_mp_mesh():
    """mp > 1 duplicates each dp position across mp columns; still contiguous."""
    mesh = create_mesh(MeshSpec(dp=4, mp=2), devices=jax.devices()[:8])
    assert process_local_rows(400, mesh) == (0, 400)




def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(cmds, envs, timeout=180):
    """Start worker subprocesses and ALWAYS reap them — a worker deadlocked
    in a collective must not outlive the test and squat the coordinator."""
    import subprocess

    procs = [subprocess.Popen(c, env=e, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for c, e in zip(cmds, envs)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


@pytest.mark.slow
def test_two_process_runtime_end_to_end(tmp_path):
    """REAL multi-process proof: two OS processes join one JAX runtime via
    the env-driven init (PIO_COORDINATOR_ADDRESS/...), each reads its
    host-shard of a sharedfs event log, and a cross-process collective
    verifies the shards union to the full log with no overlap."""
    import json
    import subprocess
    import sys
    import textwrap

    # seed a sharedfs store with a known number of events
    from predictionio_tpu.events.event import Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.locator import Storage, StorageConfig
    import predictionio_tpu.storage.localfs as lfs

    store = str(tmp_path / "shared")
    storage = Storage(StorageConfig(
        sources={"S": {"type": "sharedfs", "path": store}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA", "MODELDATA")},
    ))
    app_id = storage.apps.insert(App(0, "distapp"))
    n_events = 360
    # several small segments so both processes get a share
    old = lfs.SEGMENT_MAX_BYTES
    lfs.SEGMENT_MAX_BYTES = 4096
    try:
        for s in range(0, n_events, 40):
            storage.l_events.insert_batch(
                [Event(event="buy", entity_type="user", entity_id=f"u{k % 50}",
                       target_entity_type="item", target_entity_id=f"i{k % 11}")
                 for k in range(s, s + 40)], app_id)
    finally:
        lfs.SEGMENT_MAX_BYTES = old

    worker = textwrap.dedent("""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from predictionio_tpu.parallel.distributed import init_distributed
        cfg = init_distributed()
        from predictionio_tpu.store.event_store import PEventStore
        batch = PEventStore.batch("distapp", local_shard=True)
        local = len(batch)
        from jax.experimental import multihost_utils
        import numpy as np
        counts = multihost_utils.process_allgather(np.asarray([local]))
        print("RESULT", jax.process_index(), local, int(counts.sum()), flush=True)
    """)
    import os as _os

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    env_base = {
        "PYTHONPATH": repo_root,
        "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "PIO_NUM_PROCESSES": "2",
        "PIO_STORAGE_SOURCES_S_TYPE": "sharedfs",
        "PIO_STORAGE_SOURCES_S_PATH": store,
        "PATH": _os.environ.get("PATH", ""),
        "HOME": _os.environ.get("HOME", "/root"),
    }
    for r in ("METADATA", "EVENTDATA", "MODELDATA"):
        env_base[f"PIO_STORAGE_REPOSITORIES_{r}_SOURCE"] = "S"
    results = _run_workers(
        [[sys.executable, "-c", worker] for _ in range(2)],
        [dict(env_base, PIO_PROCESS_ID=str(pid)) for pid in range(2)],
        timeout=150)
    locals_seen = {}
    for rc, out, err in results:
        assert rc == 0, err[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT")][0]
        _, pid_s, local_s, total_s = line.split()
        locals_seen[int(pid_s)] = int(local_s)
        assert int(total_s) == n_events  # the collective saw the full log
    # disjoint shards that union to everything, both non-empty
    assert sum(locals_seen.values()) == n_events
    assert all(v > 0 for v in locals_seen.values()), locals_seen


@pytest.mark.slow
def test_two_process_cco_training_matches_single(tmp_path):
    """Multi-HOST CCO training: two OS processes, one global mesh (dp=4
    spanning both), cross-process psum of count tiles — the result must
    equal a single-process train on the same data."""
    import subprocess
    import sys
    import textwrap
    import os as _os

    import numpy as np

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    worker = textwrap.dedent("""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from predictionio_tpu.parallel.distributed import init_distributed
        init_distributed()
        import numpy as np
        from jax.sharding import Mesh
        from predictionio_tpu.ops.cco import cco_train_indicators
        rng = np.random.default_rng(7)
        n_users, n_items = 64, 12
        pu = rng.integers(0, n_users, 300).astype(np.int32)
        pi = rng.integers(0, n_items, 300).astype(np.int32)
        vu = rng.integers(0, n_users, 500).astype(np.int32)
        vi = rng.integers(0, n_items, 500).astype(np.int32)
        mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("dp", "mp"))
        out = cco_train_indicators(
            pu, pi, [("buy", pu, pi, n_items), ("view", vu, vi, n_items)],
            n_users, n_items, top_k=4, exclude_self_for="buy", mesh=mesh)
        np.savez(sys.argv[1],
                 buy=out["buy"][0], view=out["view"][0])
        print("TRAIN OK", jax.process_index(), len(jax.devices()), flush=True)
    """)
    env_base = {
        "PYTHONPATH": repo_root,
        "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "PIO_NUM_PROCESSES": "2",
        "PATH": _os.environ.get("PATH", ""),
        "HOME": _os.environ.get("HOME", "/root"),
    }
    results = _run_workers(
        [[sys.executable, "-c", worker, str(out_dir / f"p{pid}.npz")]
         for pid in range(2)],
        [dict(env_base, PIO_PROCESS_ID=str(pid)) for pid in range(2)])
    for rc, out, err in results:
        assert rc == 0, err[-2000:]
        assert "TRAIN OK" in out

    # single-process reference on the SAME data
    from predictionio_tpu.ops.cco import cco_train_indicators

    rng = np.random.default_rng(7)
    n_users, n_items = 64, 12
    pu = rng.integers(0, n_users, 300).astype(np.int32)
    pi = rng.integers(0, n_items, 300).astype(np.int32)
    vu = rng.integers(0, n_users, 500).astype(np.int32)
    vi = rng.integers(0, n_items, 500).astype(np.int32)
    ref = cco_train_indicators(
        pu, pi, [("buy", pu, pi, n_items), ("view", vu, vi, n_items)],
        n_users, n_items, top_k=4, exclude_self_for="buy")
    for pid in range(2):
        got = np.load(out_dir / f"p{pid}.npz")
        np.testing.assert_allclose(got["buy"], ref["buy"][0], rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(got["view"], ref["view"][0], rtol=1e-4,
                                   atol=1e-4)


@pytest.mark.slow
def test_two_process_als_training_matches_single(tmp_path):
    """Two-process ALS training over one global mesh equals single-process
    (factor staging via stage_global, all_gather across processes)."""
    import subprocess
    import sys
    import textwrap
    import os as _os

    import numpy as np

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    worker = textwrap.dedent("""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from predictionio_tpu.parallel.distributed import init_distributed
        init_distributed()
        import numpy as np
        from jax.sharding import Mesh
        from predictionio_tpu.ops.als import als_train, prepare_als_data
        rng = np.random.default_rng(5)
        n_users, n_items = 32, 24
        u = rng.integers(0, n_users, 400).astype(np.int32)
        i = rng.integers(0, n_items, 400).astype(np.int32)
        r = (rng.integers(1, 6, 400)).astype(np.float32)
        mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("dp", "mp"))
        data = prepare_als_data(u, i, r, n_users, n_items, dp=4)
        X, Y = als_train(data, k=6, reg=0.1, iterations=2, mesh=mesh)
        np.savez(sys.argv[1], X=np.asarray(X), Y=np.asarray(Y))
        print("ALS OK", jax.process_index(), flush=True)
    """)
    env_base = {
        "PYTHONPATH": repo_root,
        "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "PIO_NUM_PROCESSES": "2",
        "PATH": _os.environ.get("PATH", ""),
        "HOME": _os.environ.get("HOME", "/root"),
    }
    results = _run_workers(
        [[sys.executable, "-c", worker, str(out_dir / f"p{pid}.npz")]
         for pid in range(2)],
        [dict(env_base, PIO_PROCESS_ID=str(pid)) for pid in range(2)])
    for rc, out, err in results:
        assert rc == 0, err[-2000:]
        assert "ALS OK" in out

    from predictionio_tpu.ops.als import als_train, prepare_als_data

    rng = np.random.default_rng(5)
    n_users, n_items = 32, 24
    u = rng.integers(0, n_users, 400).astype(np.int32)
    i = rng.integers(0, n_items, 400).astype(np.int32)
    r = (rng.integers(1, 6, 400)).astype(np.float32)
    data = prepare_als_data(u, i, r, n_users, n_items, dp=4)
    X, Y = als_train(data, k=6, reg=0.1, iterations=2)
    for pid in range(2):
        got = np.load(out_dir / f"p{pid}.npz")
        np.testing.assert_allclose(got["X"], np.asarray(X), rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(got["Y"], np.asarray(Y), rtol=2e-3, atol=2e-3)


def test_graft_dryrun_multichip_8():
    """The driver's multichip validation entry point must stay green:
    sharded ALS + CCO (both strategies) + the engine-level UR pipeline
    (run_train → persist → predict) on an 8-device mesh, all asserted
    equal to single-device."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        from __graft_entry__ import dryrun_multichip
    finally:
        sys.path.pop(0)
    dryrun_multichip(8)
