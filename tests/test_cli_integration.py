"""Full CLI loop via subprocess — the reference's `pio_tests` integration
harness analogue (SURVEY.md §4): app new → import → train → deploy → HTTP
query → eval → export, all through the `pio` entry point."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pio_env(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PIO_FS_BASEDIR"] = str(tmp_path / "pio_store")
    # keep subprocess JAX on CPU regardless of ambient TPU state — the CLI
    # applies this programmatically (env JAX_PLATFORMS alone is overridden
    # by this VM's sitecustomize)
    env["PIO_JAX_PLATFORM"] = "cpu"
    return env


def pio(args, tmp_path, **kw):
    return subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.cli.main", *args],
        env=pio_env(tmp_path), capture_output=True, text=True, timeout=180, **kw,
    )


@pytest.mark.slow
def test_full_cli_loop(tmp_path):
    # 1. app new
    r = pio(["app", "new", "MyApp"], tmp_path)
    assert r.returncode == 0, r.stderr
    assert "Created app" in r.stdout

    # duplicate rejected
    r = pio(["app", "new", "MyApp"], tmp_path)
    assert r.returncode == 1

    # 2. import events (ML-100K-like tiny ratings file)
    rng = np.random.default_rng(0)
    events_file = tmp_path / "events.jsonl"
    with open(events_file, "w") as f:
        for u in range(15):
            for i in range(10):
                liked = (u < 8) == (i < 5)
                if rng.random() < 0.85:
                    f.write(json.dumps({
                        "event": "rate", "entityType": "user", "entityId": f"u{u}",
                        "targetEntityType": "item", "targetEntityId": f"i{i}",
                        "properties": {"rating": 5.0 if liked else 1.0},
                        "eventTime": "2026-01-01T00:00:00Z",
                    }) + "\n")
    r = pio(["import", "--app-name", "MyApp", "--input", str(events_file)], tmp_path)
    assert r.returncode == 0, r.stderr
    assert "Imported" in r.stdout

    # 3. train
    engine_json = os.path.join(REPO, "examples", "recommendation", "engine.json")
    r = pio(["train", "--engine-json", engine_json], tmp_path)
    assert r.returncode == 0, r.stderr + r.stdout
    assert "Training completed" in r.stdout

    # 4. deploy (background process) + query over HTTP
    port = 18321
    proc = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli.main", "deploy",
         "--engine-json", engine_json, "--ip", "127.0.0.1", "--port", str(port)],
        env=pio_env(tmp_path), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        deadline = time.time() + 60
        last_err = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=json.dumps({"user": "u1", "num": 3}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as resp:
                    body = json.loads(resp.read())
                break
            except Exception as e:  # server not up yet
                last_err = e
                assert proc.poll() is None, proc.stderr.read().decode()
                time.sleep(0.5)
        else:
            raise AssertionError(f"query server never came up: {last_err}")
        items = [s["item"] for s in body["itemScores"]]
        assert len(items) == 3
        assert all(int(i[1:]) < 5 for i in items), items  # u1 is in group 0
    finally:
        proc.send_signal(signal.SIGINT)
        proc.wait(timeout=10)

    # 5. eval (uses the example Evaluation over the same store)
    r = pio(["eval", "examples.recommendation.evaluation.RecommendationEvaluation"],
            tmp_path)
    assert r.returncode == 0, r.stderr + r.stdout
    assert "Evaluation completed" in r.stdout

    # 6. export round-trips the events
    out = tmp_path / "export.jsonl"
    r = pio(["export", "--app-name", "MyApp", "--output", str(out)], tmp_path)
    assert r.returncode == 0, r.stderr
    exported = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(exported) > 100 and all("eventId" in e for e in exported)

    # 7. status reports the trained instance's storage
    r = pio(["status"], tmp_path)
    assert r.returncode == 0 and "apps: 1" in r.stdout


def sharedfs_env(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PIO_JAX_PLATFORM"] = "cpu"
    env.pop("PIO_FS_BASEDIR", None)
    env["PIO_STORAGE_SOURCES_SH_TYPE"] = "sharedfs"
    env["PIO_STORAGE_SOURCES_SH_PATH"] = str(tmp_path / "shared_store")
    for r in ("METADATA", "EVENTDATA", "MODELDATA"):
        env[f"PIO_STORAGE_REPOSITORIES_{r}_SOURCE"] = "SH"
    return env


def pio_sh(args, tmp_path, **kw):
    return subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.cli.main", *args],
        env=sharedfs_env(tmp_path), capture_output=True, text=True,
        timeout=180, **kw)


@pytest.mark.slow
def test_cli_loop_on_sharedfs_with_concurrent_importers(tmp_path):
    """The full product path on the multi-host backend: app new → TWO
    concurrent importer PROCESSES (per-writer segments in one shared log)
    → UR train → deploy → HTTP query."""
    r = pio_sh(["app", "new", "ShopApp"], tmp_path)
    assert r.returncode == 0, r.stderr

    rng = np.random.default_rng(23)
    files = []
    for w in range(2):
        lines = []
        for k in range(400):
            u, it = int(rng.integers(0, 40)), int(rng.integers(0, 15))
            lines.append(json.dumps({
                "event": "buy", "entityType": "user", "entityId": f"u{u}",
                "targetEntityType": "item", "targetEntityId": f"i{it}"}))
        f = tmp_path / f"events{w}.jsonl"
        f.write_text("\n".join(lines) + "\n")
        files.append(f)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli.main", "import",
         "--app-name", "ShopApp", "--input", str(f)],
        env=sharedfs_env(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for f in files]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err
    # two writer processes → per-writer segments, one log
    segs = list((tmp_path / "shared_store" / "events").rglob("seg-*.jsonl"))
    assert len(segs) >= 2
    assert len({s.name.rsplit("-", 1)[0] for s in segs}) >= 2

    variant = {
        "id": "sh-ur",
        "engineFactory":
            "predictionio_tpu.models.universal_recommender.UniversalRecommenderEngine",
        "datasource": {"params": {"appName": "ShopApp", "eventNames": ["buy"]}},
        "algorithms": [{"name": "ur", "params": {
            "appName": "ShopApp", "meshDp": 1, "maxCorrelatorsPerItem": 5}}],
    }
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps(variant))
    r = pio_sh(["train", "--engine-json", str(ej)], tmp_path)
    assert r.returncode == 0, r.stderr

    server = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli.main", "deploy",
         "--engine-json", str(ej), "--ip", "127.0.0.1", "--port", "18731"],
        env=sharedfs_env(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 90
        body = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    "http://127.0.0.1:18731/queries.json",
                    data=json.dumps({"user": "u1", "num": 3}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as resp:
                    body = json.loads(resp.read())
                break
            except Exception:
                time.sleep(1.5)
        assert body is not None and "itemScores" in body, body
        assert len(body["itemScores"]) > 0
    finally:
        server.send_signal(signal.SIGINT)
        try:
            server.wait(timeout=20)
        except subprocess.TimeoutExpired:
            server.kill()


def test_train_stop_after_read_and_prepare(tmp_path):
    """--stop-after-read/--stop-after-prepare sanity-check the pipeline
    without training or persisting an instance (reference WorkflowParams)."""
    r = pio(["app", "new", "DbgApp"], tmp_path)
    assert r.returncode == 0, r.stderr
    events = tmp_path / "ev.jsonl"
    events.write_text("\n".join(
        json.dumps({"event": "rate", "entityType": "user", "entityId": f"u{k}",
                    "targetEntityType": "item", "targetEntityId": f"i{k % 4}",
                    "properties": {"rating": 4.0}})
        for k in range(12)) + "\n")
    r = pio(["import", "--app-name", "DbgApp", "--input", str(events)], tmp_path)
    assert r.returncode == 0, r.stderr
    variant = {
        "id": "dbg", "engineFactory":
            "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "DbgApp"}},
        "algorithms": [{"name": "als", "params": {"rank": 2,
                                                  "numIterations": 2,
                                                  "meshDp": 1}}],
    }
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps(variant))
    r = pio(["train", "--engine-json", str(ej), "--stop-after-read"], tmp_path)
    assert r.returncode == 0, r.stderr
    assert "read_training ->" in r.stdout and "Stopped before training" in r.stdout
    r = pio(["train", "--engine-json", str(ej), "--stop-after-prepare"], tmp_path)
    assert r.returncode == 0, r.stderr
    assert "prepare ->" in r.stdout
    # no engine instance was persisted by the debug runs
    r = pio(["deploy", "--engine-json", str(ej), "--port", "0"], tmp_path)
    assert r.returncode != 0  # nothing trained yet
