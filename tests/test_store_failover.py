"""Sharded, replicated event store: routing, the semi-sync replication
barrier, promotion, and the fault-injection drill (PR 9 tentpole).

The heavyweight kill/tear/partition scenarios live in
``scripts/check_store_failover.py`` (wrapped here for tier-1, same
pattern as check_serve_parity.py); this file keeps the fast unit-level
contracts close to the code."""

import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.events.event import Event
from predictionio_tpu.storage import AccessKey, App
from predictionio_tpu.storage.sharded import ShardedEvents, shard_of

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def sharded2(tmp_path):
    """2 shards × 2 replicas, strict durability."""
    os.environ["PIO_FSYNC"] = "always"
    ev = ShardedEvents(tmp_path / "store", shards=2, replicas=2)
    yield ev
    ev.close()
    os.environ.pop("PIO_FSYNC", None)


def _ingest(ev, n, prefix="e", app_id=1):
    res = ev.insert_json_batch(
        [{"event": "buy", "entityType": "user", "entityId": f"u{k}",
          "eventId": f"{prefix}{k}"} for k in range(n)], app_id)
    assert all(r["status"] == 201 for r in res), res
    return {f"{prefix}{k}" for k in range(n)}


def test_routing_is_stable_and_partitions(tmp_path):
    """Every entity lands on exactly the shard the hash names; the union
    across shards is complete; entity-targeted find touches one shard."""
    ev = ShardedEvents(tmp_path / "s", shards=4, replicas=1)
    ids = _ingest(ev, 64)
    for k in range(64):
        want = shard_of("user", f"u{k}", 4)
        d = (tmp_path / "s" / f"shard_{want:02d}" / "a" / "events"
             / "app_1" / "_default")
        raw = "".join(p.read_text() for p in d.glob("seg-*.jsonl"))
        assert f'"e{k}"' in raw or f'"eventId":"e{k}"' in raw, (k, want)
    assert {e.event_id for e in ev.scan(1)} == ids
    got = list(ev.find(1, entity_type="user", entity_id="u5"))
    assert [e.event_id for e in got] == ["e5"]
    ev.close()


def test_insert_json_batch_preserves_order_and_statuses(tmp_path):
    """Per-item results come back in INPUT order with the same statuses a
    single-shard store would give, even though the batch is partitioned
    across shards."""
    ev = ShardedEvents(tmp_path / "s", shards=3, replicas=1)
    items = []
    for k in range(12):
        items.append({"event": "buy", "entityType": "user",
                      "entityId": f"u{k}", "eventId": f"e{k}"})
        if k % 4 == 3:
            items.append({"entityType": "user", "entityId": "broken"})
    res = ev.insert_json_batch(items, 1)
    assert len(res) == len(items)
    for item, r in zip(items, res):
        if "event" in item:
            assert r == {"status": 201, "eventId": item["eventId"]}
        else:
            assert r["status"] == 400
    ev.close()


def test_acked_event_is_on_both_nodes(sharded2, tmp_path):
    """The semi-sync barrier: by the time insert returns, the replica
    holds byte-identical copies of every acked segment, and the acked
    offsets match the file sizes."""
    _ingest(sharded2, 30)
    root = tmp_path / "store"
    for k in (0, 1):
        proot = root / f"shard_{k:02d}" / "a"
        rroot = root / f"shard_{k:02d}" / "b"
        segs = sorted(p.relative_to(proot)
                      for p in proot.glob("events/app_1/_default/seg-*.jsonl"))
        assert segs, f"shard {k} empty"
        acked = json.loads((rroot / "repl" / "acked.json").read_text())
        for rel in segs:
            pbytes = (proot / rel).read_bytes()
            assert (rroot / rel).read_bytes() == pbytes, rel
            assert acked[str(rel)]["off"] == len(pbytes)


def test_promotion_preserves_acked_and_resyncs(sharded2, tmp_path):
    """Yank both primaries: a fresh instance promotes, serves every acked
    event exactly once, keeps ingesting, and the re-sync lag drains to
    0 with the yanked node recreated."""
    ids = _ingest(sharded2, 40)
    sharded2.close()
    root = tmp_path / "store"
    for k in (0, 1):
        shutil.move(str(root / f"shard_{k:02d}" / "a"),
                    str(root / f"shard_{k:02d}" / "a.lost"))
    ev = ShardedEvents(root, shards=2, replicas=2)
    try:
        got = [e.event_id for e in ev.scan(1)]
        assert sorted(got) == sorted(ids)
        topo = ev.topology_status()
        assert all(p["primary"] == "b" and p["epoch"] == 1
                   for p in topo["perShard"])
        ids |= _ingest(ev, 10, prefix="post")
        deadline = time.time() + 10
        while time.time() < deadline:
            topo = ev.topology_status()
            if all(p["replicaLagEvents"] == 0 for p in topo["perShard"]):
                break
            time.sleep(0.05)
        assert all(p["replicaLagEvents"] == 0 for p in topo["perShard"])
        assert {e.event_id for e in ev.scan(1)} == ids
        # the recreated node a holds every acked byte again
        for k in (0, 1):
            proot = root / f"shard_{k:02d}" / "b"
            rroot = root / f"shard_{k:02d}" / "a"
            for seg in proot.glob("events/app_1/_default/seg-*.jsonl"):
                rel = seg.relative_to(proot)
                assert (rroot / rel).read_bytes() == seg.read_bytes()
    finally:
        ev.close()


def test_fenced_writer_cannot_ack_after_promotion(sharded2, tmp_path):
    """A writer bound to the demoted node is fenced at its next commit
    (the group NACKs) — split-brain acks are impossible — and the
    sharded wrapper retries the write onto the new primary."""
    _ingest(sharded2, 4)
    shard = sharded2._shards[0]
    stale = shard.events()          # node 'a' writer
    shard.promote("test")
    with pytest.raises(OSError, match="fenced"):
        stale.insert_json_batch(
            [{"event": "buy", "entityType": "user", "entityId": "uX",
              "eventId": "fenced-1"}], 1)
    # the same write through ShardedEvents lands on the new primary
    k = shard_of("user", "uX", 2)
    if k == 0:          # only meaningful when the entity routes to shard 0
        res = sharded2.insert_json_batch(
            [{"event": "buy", "entityType": "user", "entityId": "uX",
              "eventId": "fenced-2"}], 1)
        assert res[0]["status"] == 201
        assert "fenced-2" in {e.event_id for e in sharded2.scan(1)}


def test_delta_staging_namespaced_watermarks(sharded2):
    """snapshot_scan → scan_tail_from roundtrip with shard-namespaced
    watermarks: the delta covers exactly the appended suffix, and a
    foreign watermark reads None (full restage)."""
    _ingest(sharded2, 20)
    snap = sharded2.snapshot_scan(1, None)
    assert snap["events"] == 20
    assert all("|" in k for k in snap["watermark"])
    tail = sharded2.scan_tail_from(1, None, snap["watermark"],
                                   base=snap["batch"], heads=snap["heads"])
    assert tail["events"] == 0
    _ingest(sharded2, 5, prefix="d")
    tail = sharded2.scan_tail_from(1, None, snap["watermark"],
                                   base=snap["batch"], heads=snap["heads"])
    assert tail["events"] == 5
    assert sorted(tail["ids"].tolist()) == sorted(f"d{k}" for k in range(5))
    bound = sharded2.scan_events_up_to(1, None, snap["watermark"],
                                       heads=snap["heads"])
    assert bound["events"] == 20
    assert sharded2.scan_tail_from(1, None, {"not-namespaced": 3}) is None


def test_staged_cache_delta_retrain_on_sharded(tmp_path, monkeypatch):
    """PEventStore.batch on a sharded store: the first read stages the
    whole log, the second stages ONLY the delta (PR 3's retained-batch
    cache, driven by the shard-namespaced watermark)."""
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )
    from predictionio_tpu.store import event_store
    from predictionio_tpu.storage import snapshot as _snap

    cfg = StorageConfig(
        sources={"S": {"type": "sharded", "path": str(tmp_path / "st"),
                       "shards": "2", "replicas": "1"}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA",
                                       "MODELDATA")})
    storage = Storage(cfg)
    set_storage(storage)
    try:
        app_id = storage.apps.insert(App(0, "shardapp"))
        ev = storage.l_events
        _ingest(ev, 25, app_id=app_id)
        event_store.invalidate_staging_cache()
        b1 = event_store.PEventStore.batch("shardapp", storage=storage)
        assert len(b1) == 25
        before = _snap.staged_counts()["delta"]
        _ingest(ev, 7, prefix="d", app_id=app_id)
        b2 = event_store.PEventStore.batch("shardapp", storage=storage)
        assert len(b2) == 32
        assert _snap.staged_counts()["delta"] - before == 7
    finally:
        event_store.invalidate_staging_cache()
        set_storage(None)
        storage.l_events.close()


def test_stats_json_store_topology(tmp_path, monkeypatch):
    """/stats.json on an event server over a sharded store carries the
    storeTopology document (shards, per-shard primary/epoch/lag)."""
    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    cfg = StorageConfig(
        sources={"S": {"type": "sharded", "path": str(tmp_path / "st"),
                       "shards": "2", "replicas": "2"}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA",
                                       "MODELDATA")})
    storage = Storage(cfg)
    set_storage(storage)
    httpd = None
    try:
        app_id = storage.apps.insert(App(0, "topoapp"))
        key = storage.access_keys.insert(AccessKey("", app_id, []))
        _ingest(storage.l_events, 10, app_id=app_id)
        httpd = run_event_server(host="127.0.0.1", port=0, storage=storage,
                                 background=True)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        with urllib.request.urlopen(
                f"{base}/stats.json?accessKey={key}", timeout=10) as r:
            doc = json.loads(r.read())
        topo = doc["storeTopology"]
        assert topo["shards"] == 2 and topo["replicas"] == 2
        assert len(topo["perShard"]) == 2
        for s in topo["perShard"]:
            assert s["primary"] in ("a", "b")
            assert s["replicaLagEvents"] == 0
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        set_storage(None)
        storage.l_events.close()


def test_sdk_backoff_rides_through_promotion_window(tmp_path):
    """EventClient retries connection-refused with backoff: a request
    issued while the server is down succeeds once the server comes up
    inside the retry window (the failover promotion scenario), and still
    fails fast once the window is exhausted."""
    import socket

    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.sdk.client import EventClient
    from predictionio_tpu.storage.locator import (
        Storage, StorageConfig, set_storage,
    )

    cfg = StorageConfig(
        sources={"S": {"type": "localfs", "path": str(tmp_path / "st")}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA",
                                       "MODELDATA")})
    storage = Storage(cfg)
    set_storage(storage)
    app_id = storage.apps.insert(App(0, "boapp"))
    key = storage.access_keys.insert(AccessKey("", app_id, []))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    started = {}

    def come_up_late():
        time.sleep(0.6)
        started["httpd"] = run_event_server(
            host="127.0.0.1", port=port, storage=storage, background=True)

    t = threading.Thread(target=come_up_late)
    t.start()
    try:
        client = EventClient(key, f"http://127.0.0.1:{port}",
                             retry_window=8.0)
        t0 = time.monotonic()
        eid = client.create_event("buy", "user", "u1")
        assert eid and time.monotonic() - t0 >= 0.3   # it actually waited
        # exhausted window on a port nobody will serve → the original
        # ConnectionRefusedError surfaces (type preserved)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        fast = EventClient(key, f"http://127.0.0.1:{dead_port}",
                           retry_window=0.3)
        t0 = time.monotonic()
        with pytest.raises(ConnectionRefusedError):
            fast.create_event("buy", "user", "u2")
        assert time.monotonic() - t0 < 5.0
    finally:
        t.join()
        h = started.get("httpd")
        if h is not None:
            h.shutdown()
            h.server_close()
        set_storage(None)


# -- the drill ---------------------------------------------------------------


def test_check_store_failover_script():
    """Tier-1 wrapper for scripts/check_store_failover.py: SIGKILL a
    primary mid-group-commit, yank node dirs, tear replica tails,
    partition a shard mid-scan — zero acked-event loss, zero duplicates,
    re-sync lag drains to 0."""
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_store_failover.py")],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
