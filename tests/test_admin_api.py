"""Admin REST API tests (reference analogue: AdminAPISpec)."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.api.admin import run_admin_server


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


@pytest.fixture()
def admin(mem_storage):
    httpd = run_admin_server(port=0, storage=mem_storage, background=True)
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_admin_app_lifecycle(admin):
    status, body = http("GET", admin + "/")
    assert status == 200 and body["status"] == "alive"

    status, created = http("POST", admin + "/cmd/app", {"name": "adm1"})
    assert status == 201 and created["accessKey"]

    status, dup = http("POST", admin + "/cmd/app", {"name": "adm1"})
    assert status == 409

    status, apps = http("GET", admin + "/cmd/app")
    assert [a["name"] for a in apps["apps"]] == ["adm1"]

    status, keys = http("GET", admin + "/cmd/app/adm1/accesskeys")
    assert status == 200 and len(keys["accessKeys"]) == 1

    status, newkey = http("POST", admin + "/cmd/app/adm1/accesskeys",
                          {"events": ["view"]})
    assert status == 201
    status, keys = http("GET", admin + "/cmd/app/adm1/accesskeys")
    assert len(keys["accessKeys"]) == 2

    status, _ = http("DELETE", admin + "/cmd/app/adm1/data")
    assert status == 200

    status, _ = http("DELETE", admin + "/cmd/app/adm1")
    assert status == 200
    status, apps = http("GET", admin + "/cmd/app")
    assert apps["apps"] == []

    status, _ = http("GET", admin + "/cmd/app/ghost/accesskeys")
    assert status == 404


def test_adminserver_cli_registered():
    """`pio adminserver` exists (reference: Console adminserver)."""
    from predictionio_tpu.cli.main import build_parser

    args = build_parser().parse_args(["adminserver", "--port", "0"])
    assert args.port == 0 and callable(args.func)
