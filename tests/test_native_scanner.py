"""Native C++ event-log scanner tests: parity with the Python path, escape/
unicode handling, and throughput sanity."""

import datetime as dt
import json
import time

import numpy as np
import pytest

from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.native import native_available, scan_segments
from predictionio_tpu.storage import App
from predictionio_tpu.store import PEventStore

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ unavailable; native scanner not built"
)


def ts(h):
    return dt.datetime(2026, 1, 2, h, tzinfo=dt.timezone.utc)


def seed(fs_storage, n=500):
    app_id = fs_storage.apps.insert(App(0, "natapp"))
    rng = np.random.default_rng(9)
    events = []
    for k in range(n):
        events.append(Event(
            event="rate" if k % 3 else "view",
            entity_type="user", entity_id=f"u{k % 17}",
            target_entity_type="item", target_entity_id=f"i{k % 31}",
            properties=DataMap({"rating": float(k % 5 + 1)} if k % 3 else {}),
            event_time=ts(k % 23),
        ))
    # escape/unicode torture rows
    events.append(Event(event="rate", entity_type="user",
                        entity_id='u"quoted\\slash',
                        target_entity_type="item", target_entity_id="naïve—item",
                        properties=DataMap({"rating": 2.5, "note": "line\nbreak\tand \"q\""}),
                        event_time=ts(1)))
    fs_storage.l_events.insert_batch(events, app_id)
    return app_id


def test_native_matches_python_path(fs_storage):
    app_id = seed(fs_storage)
    nat = PEventStore.batch("natapp", storage=fs_storage)  # native fast path
    events = list(fs_storage.p_events.scan(app_id))
    assert len(nat) == len(events)
    # compare as multisets of tuples
    def key(e):
        return (e.event, e.entity_id, e.target_entity_id,
                int(e.event_time.timestamp() * 1e6))

    py_keys = sorted(key(e) for e in events)
    nat_keys = sorted(
        (nat.event_dict.str(int(nat.event_codes[r])),
         nat.entity_dict.str(int(nat.entity_ids[r])),
         nat.target_dict.str(int(nat.target_ids[r])) if nat.target_ids[r] >= 0 else None,
         int(nat.times_us[r]))
        for r in range(len(nat))
    )
    assert py_keys == nat_keys
    # unicode/escape row survived intact
    assert 'u"quoted\\slash' in nat.entity_dict.strings()
    assert "naïve—item" in nat.target_dict.strings()


def test_native_filters(fs_storage):
    seed(fs_storage)
    rate_only = PEventStore.batch("natapp", event_names=["rate"], storage=fs_storage)
    assert len(rate_only) > 0
    rate_code = rate_only.event_dict.id("rate")
    assert (rate_only.event_codes == rate_code).all()
    windowed = PEventStore.batch("natapp", start_time=ts(5), until_time=ts(10),
                                 storage=fs_storage)
    assert ((windowed.times_us >= int(ts(5).timestamp() * 1e6)) &
            (windowed.times_us < int(ts(10).timestamp() * 1e6))).all()


def test_native_ratings_parse(fs_storage):
    seed(fs_storage)
    batch = PEventStore.batch("natapp", event_names=["rate"], storage=fs_storage)
    finite = np.isfinite(batch.ratings)
    assert finite.all()
    assert set(np.unique(batch.ratings)).issubset({1.0, 2.0, 2.5, 3.0, 4.0, 5.0})


def test_tombstones_force_python_fallback(fs_storage):
    app_id = seed(fs_storage, n=50)
    some_event = next(iter(fs_storage.l_events.find(app_id, limit=1)))
    fs_storage.l_events.delete(some_event.event_id, app_id)
    batch = PEventStore.batch("natapp", storage=fs_storage)
    # deleted event must not appear even though the scanner can't see tombstones
    ids = [batch.entity_dict.str(int(i)) for i in batch.entity_ids]
    assert len(batch) == 50  # 51 seeded rows (incl torture row) minus 1 deleted


def test_malformed_lines_skipped(tmp_path):
    seg = tmp_path / "seg-00000.jsonl"
    good = {"event": "view", "entityType": "user", "entityId": "u1",
            "eventTime": "2026-01-01T00:00:00+00:00"}
    seg.write_text(
        json.dumps(good) + "\n" +
        "this is not json\n" +
        '{"event": "", "entityType": "user", "entityId": "u2"}\n' +  # empty verb
        json.dumps(good) + "\n"
    )
    batch = scan_segments([seg])
    assert len(batch) == 2
