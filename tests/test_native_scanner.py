"""Native C++ event-log scanner tests: parity with the Python path, escape/
unicode handling, and throughput sanity."""

import datetime as dt
import json
import time

import numpy as np
import pytest

from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.native import native_available, scan_segments
from predictionio_tpu.storage import App
from predictionio_tpu.store import PEventStore

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ unavailable; native scanner not built"
)


def ts(h):
    return dt.datetime(2026, 1, 2, h, tzinfo=dt.timezone.utc)


def seed(fs_storage, n=500):
    app_id = fs_storage.apps.insert(App(0, "natapp"))
    rng = np.random.default_rng(9)
    events = []
    for k in range(n):
        events.append(Event(
            event="rate" if k % 3 else "view",
            entity_type="user", entity_id=f"u{k % 17}",
            target_entity_type="item", target_entity_id=f"i{k % 31}",
            properties=DataMap({"rating": float(k % 5 + 1)} if k % 3 else {}),
            event_time=ts(k % 23),
        ))
    # escape/unicode torture rows
    events.append(Event(event="rate", entity_type="user",
                        entity_id='u"quoted\\slash',
                        target_entity_type="item", target_entity_id="naïve—item",
                        properties=DataMap({"rating": 2.5, "note": "line\nbreak\tand \"q\""}),
                        event_time=ts(1)))
    fs_storage.l_events.insert_batch(events, app_id)
    return app_id


def test_native_matches_python_path(fs_storage):
    app_id = seed(fs_storage)
    nat = PEventStore.batch("natapp", storage=fs_storage)  # native fast path
    events = list(fs_storage.p_events.scan(app_id))
    assert len(nat) == len(events)
    # compare as multisets of tuples
    def key(e):
        return (e.event, e.entity_id, e.target_entity_id,
                int(e.event_time.timestamp() * 1e6))

    py_keys = sorted(key(e) for e in events)
    nat_keys = sorted(
        (nat.event_dict.str(int(nat.event_codes[r])),
         nat.entity_dict.str(int(nat.entity_ids[r])),
         nat.target_dict.str(int(nat.target_ids[r])) if nat.target_ids[r] >= 0 else None,
         int(nat.times_us[r]))
        for r in range(len(nat))
    )
    assert py_keys == nat_keys
    # unicode/escape row survived intact
    assert 'u"quoted\\slash' in nat.entity_dict.strings()
    assert "naïve—item" in nat.target_dict.strings()


def test_native_filters(fs_storage):
    seed(fs_storage)
    rate_only = PEventStore.batch("natapp", event_names=["rate"], storage=fs_storage)
    assert len(rate_only) > 0
    rate_code = rate_only.event_dict.id("rate")
    assert (rate_only.event_codes == rate_code).all()
    windowed = PEventStore.batch("natapp", start_time=ts(5), until_time=ts(10),
                                 storage=fs_storage)
    assert ((windowed.times_us >= int(ts(5).timestamp() * 1e6)) &
            (windowed.times_us < int(ts(10).timestamp() * 1e6))).all()


def test_native_ratings_parse(fs_storage):
    seed(fs_storage)
    batch = PEventStore.batch("natapp", event_names=["rate"], storage=fs_storage)
    finite = np.isfinite(batch.ratings)
    assert finite.all()
    assert set(np.unique(batch.ratings)).issubset({1.0, 2.0, 2.5, 3.0, 4.0, 5.0})


def test_tombstones_force_python_fallback(fs_storage):
    app_id = seed(fs_storage, n=50)
    some_event = next(iter(fs_storage.l_events.find(app_id, limit=1)))
    fs_storage.l_events.delete(some_event.event_id, app_id)
    batch = PEventStore.batch("natapp", storage=fs_storage)
    # deleted event must not appear even though the scanner can't see tombstones
    ids = [batch.entity_dict.str(int(i)) for i in batch.entity_ids]
    assert len(batch) == 50  # 51 seeded rows (incl torture row) minus 1 deleted


def test_malformed_lines_skipped(tmp_path):
    seg = tmp_path / "seg-00000.jsonl"
    good = {"event": "view", "entityType": "user", "entityId": "u1",
            "eventTime": "2026-01-01T00:00:00+00:00"}
    seg.write_text(
        json.dumps(good) + "\n" +
        "this is not json\n" +
        '{"event": "", "entityType": "user", "entityId": "u2"}\n' +  # empty verb
        json.dumps(good) + "\n"
    )
    batch = scan_segments([seg])
    assert len(batch) == 2


# -- full property columns (round-3 generalization) --------------------------


def test_property_columns_all_types(fs_storage):
    """The scanner parses the FULL property map into typed sparse columns:
    numbers, bools, strings, string lists; numeric list elements are
    stringified; nested objects/nulls are dropped without killing the line."""
    app_id = fs_storage.apps.insert(App(0, "propapp"))
    events = [
        Event(event="$set", entity_type="item", entity_id="i1",
              properties=DataMap({
                  "price": 9.5, "inStock": True,
                  "category": "books", "tags": ["a", "b", 3],
                  "nested": {"x": 1}, "nothing": None,
                  "releaseDate": "2026-03-01T00:00:00+00:00"}),
              event_time=ts(2)),
        Event(event="$set", entity_type="item", entity_id="i2",
              properties=DataMap({"price": 4, "category": "music"}),
              event_time=ts(3)),
        Event(event="buy", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              event_time=ts(4)),
    ]
    fs_storage.l_events.insert_batch(events, app_id)
    paths = fs_storage.p_events.segment_paths(app_id, None)
    batch = scan_segments(paths)
    pc = batch.prop_columns
    assert pc is not None
    assert set(pc) >= {"price", "inStock", "category", "tags",
                       "releaseDate", "nested", "nothing"}
    # reconstruct i1's values through value_at
    row_i1 = int(np.flatnonzero(
        batch.entity_ids == batch.entity_dict.id("i1"))[0])
    vals = {}
    for key, col in pc.items():
        j = np.flatnonzero(col.rows == row_i1)
        if len(j):
            vals[key] = col.value_at(int(j[0]))
    assert vals["price"] == 9.5 and vals["inStock"] is True
    assert vals["category"] == "books"
    assert vals["tags"] == ["a", "b", "3"]
    assert vals["releaseDate"].startswith("2026-03-01")
    assert vals["nested"] == {"x": 1}   # raw-JSON kind, decoded lazily
    assert vals["nothing"] is None


def test_native_fold_matches_python_aggregate(fs_storage):
    """aggregate_properties through the native columnar fold equals the
    pure-Python l_events fold: $set merge, $unset removal, $delete drop,
    eventTime ordering."""
    app_id = fs_storage.apps.insert(App(0, "foldapp"))
    events = [
        Event(event="$set", entity_type="item", entity_id="a",
              properties=DataMap({"p": 1, "q": "x"}), event_time=ts(1)),
        Event(event="$set", entity_type="item", entity_id="a",
              properties=DataMap({"p": 2}), event_time=ts(5)),
        Event(event="$unset", entity_type="item", entity_id="a",
              properties=DataMap({"q": None}), event_time=ts(6)),
        Event(event="$set", entity_type="item", entity_id="b",
              properties=DataMap({"cats": ["x", "y"]}), event_time=ts(2)),
        Event(event="$set", entity_type="item", entity_id="gone",
              properties=DataMap({"p": 9}), event_time=ts(2)),
        Event(event="$delete", entity_type="item", entity_id="gone",
              properties=DataMap({}), event_time=ts(3)),
        # out-of-order arrival: older $set lands AFTER the newer one in the
        # log but must lose the fold
        Event(event="$set", entity_type="item", entity_id="a",
              properties=DataMap({"p": 0}), event_time=ts(0)),
        Event(event="$set", entity_type="user", entity_id="u",
              properties=DataMap({"p": 7}), event_time=ts(1)),
    ]
    fs_storage.l_events.insert_batch(events, app_id)
    native = PEventStore.aggregate_properties("foldapp", "item", storage=fs_storage)
    python = fs_storage.l_events.aggregate_properties(app_id, "item")
    assert set(native) == set(python) == {"a", "b"}
    for k in native:
        assert dict(native[k]) == dict(python[k]), (k, native[k], python[k])
    assert dict(native["a"]) == {"p": 2}
    assert dict(native["b"]) == {"cats": ["x", "y"]}


def test_malformed_line_corpus(fs_storage, tmp_path):
    """Fuzz-ish corpus at the C++ boundary: malformed lines are skipped,
    well-formed ones survive, and nothing crashes."""
    good = [
        json.dumps({"event": "buy", "entityType": "user", "entityId": f"u{k}",
                    "targetEntityType": "item", "targetEntityId": f"i{k}",
                    "properties": {"rating": k * 0.5, "tags": ["t"]},
                    "eventTime": "2026-01-01T00:00:00+00:00"})
        for k in range(5)
    ]
    bad = [
        "",                                     # empty
        "not json at all",
        "{",                                    # truncated object
        '{"event": "x"',                        # unterminated
        '{"event": 42}',                        # wrong type for event
        '{"entityId": "u1"}',                   # missing event
        '{"event": "x", "entityId": "u1", "properties": {"k": }}',  # bad value
        '{"event": "x", "entityId": "u1", "eventTime": "garbage-date"}',
        '{"event": "x", "entityId": "u1", "properties": [1,2,]}',
        '{"event": "\\ud800", "entityId": "u1"}',  # lone surrogate
        '{"event": "x", "entityId": "u1", "properties": {"a": {"deep": [1, {"b": 2}]}}}',
    ]
    seg = tmp_path / "seg-fuzz.jsonl"
    lines = []
    for i, g in enumerate(good):
        lines.append(g)
        lines.extend(bad[i * 2:(i + 1) * 2])
    seg.write_text("\n".join(lines + bad) + "\n")
    batch = scan_segments([seg])
    # exactly the good lines with an 'event' and entityId survive (the
    # nested-props bad line IS structurally valid JSON → also survives)
    events = [batch.event_dict.str(int(c)) for c in batch.event_codes]
    assert events.count("buy") == 5
    assert len(batch) >= 5


def test_ur_trains_through_native_scan(fs_storage):
    """UR training on a segment-file backend ingests via the C++ scanner
    (interactions AND item properties) and serves field rules from them."""
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine, URQuery)
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithmParams, URDataSourceParams)

    app_id = fs_storage.apps.insert(App(0, "urnat"))
    rng = np.random.default_rng(13)
    events = []
    for u in range(20):
        mine = "e" if u < 10 else "b"
        for i in range(5):
            if rng.random() < 0.8:
                events.append(Event(event="buy", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=f"{mine}{i}", event_time=ts(u % 20)))
    for pfx, cat in (("e", "electronics"), ("b", "books")):
        for i in range(5):
            events.append(Event(event="$set", entity_type="item",
                                entity_id=f"{pfx}{i}",
                                properties=DataMap({"category": cat}),
                                event_time=ts(1)))
    fs_storage.l_events.insert_batch(events, app_id)

    from predictionio_tpu.storage.locator import set_storage
    set_storage(fs_storage)
    try:
        engine = UniversalRecommenderEngine.apply()
        ep = EngineParams(
            data_source_params=URDataSourceParams(
                app_name="urnat", event_names=["buy"]),
            algorithm_params_list=[("ur", URAlgorithmParams(
                app_name="urnat", mesh_dp=1))],
        )
        models = engine.train(ep)
        pred = engine.predictor(ep, models)
        res = pred(URQuery(user="u2", num=3))
        assert res.item_scores
        filt = pred(URQuery(user="u2", num=3, fields=[
            {"name": "category", "values": ["books"], "bias": -1}]))
        assert all(s.item.startswith("b") for s in filt.item_scores)
    finally:
        set_storage(None)


def test_hostile_property_keys(tmp_path):
    """Lone-surrogate and embedded-NUL property keys neither crash the scan
    nor collide columns."""
    seg = tmp_path / "seg-keys.jsonl"
    seg.write_text("\n".join([
        json.dumps({"event": "buy", "entityType": "user", "entityId": "u1",
                    "properties": {"a": 1}}),
        '{"event": "buy", "entityType": "user", "entityId": "u2", '
        '"properties": {"\\ud800key": 2}}',
        '{"event": "buy", "entityType": "user", "entityId": "u3", '
        '"properties": {"a\\u0000b": 3, "a": 4}}',
    ]) + "\n")
    batch = scan_segments([seg])
    assert len(batch) == 3
    pc = batch.prop_columns
    # 'a' and 'a\x00b' stay distinct columns
    assert "a" in pc and "a\x00b" in pc
    assert len(pc["a"]) == 2 and len(pc["a\x00b"]) == 1
    assert len([k for k in pc if k.endswith("key")]) == 1


def test_fold_with_interaction_only_property_keys(fs_storage):
    """A property key that appears only on non-special events (e.g. price
    on buy) must not break aggregate_properties — its column is empty after
    the special-event filter."""
    app_id = fs_storage.apps.insert(App(0, "mixprops"))
    fs_storage.l_events.insert_batch([
        Event(event="buy", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              properties=DataMap({"price": 3.5}), event_time=ts(1)),
        Event(event="$set", entity_type="item", entity_id="i1",
              properties=DataMap({"category": "x"}), event_time=ts(2)),
    ], app_id)
    props = PEventStore.aggregate_properties("mixprops", "item", storage=fs_storage)
    assert dict(props["i1"]) == {"category": "x"}


def test_native_layout_matches_numpy():
    """The C++ counting layout equals the numpy staging (same chunk
    grouping, counts and in-chunk order is irrelevant to the consumer, but
    contents per chunk must match as multisets)."""
    from predictionio_tpu.native import layout_chunks

    rng = np.random.default_rng(17)
    n_users, chunk, n_chunks = 1000, 256, 4
    u = rng.integers(0, n_users, 5000).astype(np.int32)
    i = rng.integers(0, 300, 5000).astype(np.int32)
    out = layout_chunks(u, i, chunk, n_chunks)
    assert out is not None
    lu, it, cnt = out
    assert lu.shape == it.shape and lu.shape[0] == n_chunks
    assert cnt.sum() == 5000
    for b in range(n_chunks):
        c = int(cnt[b])
        sel = (u // chunk) == b
        want = sorted(zip((u[sel] % chunk).tolist(), i[sel].tolist()))
        got = sorted(zip(lu[b, :c].tolist(), it[b, :c].tolist()))
        assert got == want
        assert (lu[b, c:] == 0).all() and (it[b, c:] == 0).all()
    # invalid input fails LOUDLY (same contract as the numpy path)
    bad = np.array([chunk * n_chunks + 5], np.int32)
    with pytest.raises(ValueError):
        layout_chunks(bad, bad, chunk, n_chunks)
    with pytest.raises(ValueError):
        layout_chunks(np.array([-1], np.int32), np.array([0], np.int32),
                      chunk, n_chunks)
    with pytest.raises(ValueError):
        layout_chunks(u, i[:100], chunk, n_chunks)


def test_native_layout_perf_sanity():
    from predictionio_tpu.native import layout_chunks

    rng = np.random.default_rng(3)
    n = 2_000_000
    u = rng.integers(0, 100_000, n).astype(np.int32)
    i = rng.integers(0, 8192, n).astype(np.int32)
    t0 = time.perf_counter()
    out = layout_chunks(u, i, 32768, 4)
    dt = time.perf_counter() - t0
    assert out is not None and out[2].sum() == n
    assert dt < 2.0, f"native layout too slow: {dt:.2f}s for {n} events"
