"""Event model tests (reference analogues: DataMapSpec, EventJson4sSupport
round-trip tests, LEventAggregator tests — SURVEY.md §4)."""

import datetime as dt

import pytest

from predictionio_tpu.events import (
    DataMap,
    Event,
    aggregate_properties,
)


def ts(h):
    return dt.datetime(2026, 1, 1, h, tzinfo=dt.timezone.utc)


def test_event_json_roundtrip():
    e = Event(
        event="buy",
        entity_type="user",
        entity_id="u1",
        target_entity_type="item",
        target_entity_id="i9",
        properties=DataMap({"price": 3.5, "cat": ["a", "b"]}),
        event_time=ts(5),
        tags=("t1",),
        pr_id="pr-1",
    )
    e2 = Event.from_json(e.to_json())
    assert e2.event == "buy"
    assert e2.entity_id == "u1"
    assert e2.target_entity_id == "i9"
    assert e2.properties["price"] == 3.5
    assert e2.event_time == e.event_time
    assert e2.event_id == e.event_id
    assert e2.tags == ("t1",)


def test_event_validation():
    with pytest.raises(ValueError):
        Event(event="", entity_type="user", entity_id="u1")
    with pytest.raises(ValueError):
        Event(event="$set", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1")
    with pytest.raises(ValueError):
        Event(event="$unset", entity_type="user", entity_id="u1")
    with pytest.raises(ValueError):
        Event(event="$bogus", entity_type="user", entity_id="u1")
    with pytest.raises(ValueError):
        Event.from_json({"event": "buy", "entityType": "user", "entityId": "u1",
                         "bogusField": 1})


def test_datamap_typed_getters():
    d = DataMap({"a": 1, "b": "x", "c": 2.5})
    assert d.get_as("a", int) == 1
    assert d.get_as("a", float) == 1.0
    assert d.get_as("c", float) == 2.5
    assert d.get_opt("zz", 7) == 7
    with pytest.raises(KeyError):
        d.get_as("zz", int)
    with pytest.raises(TypeError):
        d.get_as("b", int)


def test_aggregate_properties_set_unset_delete():
    events = [
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"a": 1, "b": 2}), event_time=ts(1)),
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"b": 3, "c": 4}), event_time=ts(2)),
        Event(event="$unset", entity_type="user", entity_id="u1",
              properties=DataMap({"a": None}), event_time=ts(3)),
        Event(event="$set", entity_type="user", entity_id="u2",
              properties=DataMap({"x": 1}), event_time=ts(1)),
        Event(event="$delete", entity_type="user", entity_id="u3",
              event_time=ts(2)),
        Event(event="$set", entity_type="user", entity_id="u3",
              properties=DataMap({"y": 1}), event_time=ts(1)),
        Event(event="view", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1", event_time=ts(2)),
    ]
    snap = aggregate_properties(events)
    assert snap["u1"] == {"b": 3, "c": 4}
    assert snap["u1"].first_updated == ts(1)
    assert snap["u1"].last_updated == ts(3)
    assert snap["u2"] == {"x": 1}
    assert "u3" not in snap  # $delete at ts(2) wins over $set at ts(1)


def test_aggregate_orders_by_event_time_not_arrival():
    events = [
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"v": "late"}), event_time=ts(5)),
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"v": "early"}), event_time=ts(1)),
    ]
    assert aggregate_properties(events)["u1"]["v"] == "late"


def test_canonical_event_json_matches_event_round_trip():
    """The ingest fast path must produce byte-identical storage lines to
    the Event object path for the same eventId/creationTime."""
    import json as _json

    from predictionio_tpu.events.event import Event, canonical_event_json

    corpus = [
        {"event": "buy", "entityType": "user", "entityId": 7,
         "targetEntityType": "item", "targetEntityId": 3,
         "eventTime": "2026-01-02T03:04:05Z"},
        {"event": "view", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"k": [1, 2], "s": "x", "b": True, "n": None},
         "eventTime": "2026-01-02T03:04:05+02:00", "tags": ["a", "b"],
         "prId": "p1"},
        {"event": "$set", "entityType": "item", "entityId": "i9",
         "properties": {"categories": ["c1"]}},
        {"event": "$unset", "entityType": "item", "entityId": "i9",
         "properties": {"categories": None}},
        {"event": "$delete", "entityType": "item", "entityId": "i9"},
        {"event": "rate", "entityType": "user", "entityId": "u",
         "targetEntityType": "item", "targetEntityId": "i",
         "properties": {"rating": 4.5}, "eventTime": 1750000000},
        # falsy-but-present eventId is preserved identically on both paths
        {"event": "buy", "entityType": "u", "entityId": "x", "eventId": ""},
    ]
    for d in corpus:
        fixed = dict(d, creationTime="2026-02-03T04:05:06+00:00")
        fixed.setdefault("eventId", "fixedid")   # keeps the corpus's "" case
        fixed.setdefault("eventTime", "2026-02-03T04:05:06+00:00")
        fast = _json.dumps(canonical_event_json(fixed),
                           separators=(",", ":"), sort_keys=True)
        slow = Event.from_json(fixed).to_json_line()
        assert fast == slow, (d, fast, slow)


def test_canonical_event_json_rejects_what_from_json_rejects():
    import pytest as _pytest

    from predictionio_tpu.events.event import Event, canonical_event_json

    bad = [
        {"event": "buy", "entityType": "user"},                    # no id
        {"event": "buy", "entityType": "user", "entityId": None},  # null id
        {"event": "", "entityType": "user", "entityId": "u"},      # empty verb
        {"event": 5, "entityType": "user", "entityId": "u"},       # non-str verb
        {"event": "buy", "entityType": "u", "entityId": "x",
         "properties": [["a", 1]]},                                # non-object props
        {"event": "$set", "entityType": "u", "entityId": "x",
         "targetEntityId": "t"},                                   # target on $set
        {"event": "$unset", "entityType": "u", "entityId": "x"},   # empty unset
        {"event": "$frobnicate", "entityType": "u", "entityId": "x"},
        {"event": "buy", "entityType": "u", "entityId": "x", "nope": 1},
    ]
    for d in bad:
        with _pytest.raises((ValueError, KeyError, TypeError)):
            canonical_event_json(d)
        with _pytest.raises((ValueError, KeyError, TypeError)):
            Event.from_json(d)


def test_insert_json_batch_statuses_and_readback(mem_storage):
    from predictionio_tpu.storage import App

    app_id = mem_storage.apps.insert(App(0, "jb"))
    items = [
        {"event": "buy", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1"},
        {"event": "buy", "entityType": "user"},   # invalid: no entityId
        {"event": "$set", "entityType": "item", "entityId": "i1",
         "properties": {"categories": ["c"]}},
    ]
    out = mem_storage.l_events.insert_json_batch(items, app_id)
    assert [r["status"] for r in out] == [201, 400, 201]
    got = list(mem_storage.l_events.find(app_id))
    assert len(got) == 2
    assert {e.event for e in got} == {"buy", "$set"}


def test_canonical_rejects_falsy_numeric_target_on_special_events():
    """A numeric-falsy target (0) coerces to truthy \"0\" — both paths must
    reject it on $set, or the stored line would poison every log read."""
    import pytest as _pytest

    from predictionio_tpu.events.event import Event, canonical_event_json

    bad = {"event": "$set", "entityType": "u", "entityId": "x",
           "targetEntityId": 0}
    with _pytest.raises(ValueError):
        canonical_event_json(bad)
    with _pytest.raises(ValueError):
        Event.from_json(bad)
