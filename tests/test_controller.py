"""DASE controller tests (reference analogues: EngineTest, EvaluationTest,
MetricEvaluatorTest — SURVEY.md §4). Uses toy identity-style components like
the reference's FakeWorkflow fixtures."""

import dataclasses
from typing import List, Tuple

import pytest

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    EmptyParams,
    Engine,
    EngineParams,
    FirstServing,
    MetricEvaluator,
    Params,
    Preparator,
    Serving,
)


@dataclasses.dataclass
class DSParams(Params):
    n: int = 10
    folds: int = 2


class ToyDataSource(DataSource):
    params_class = DSParams

    def read_training(self):
        return list(range(self.params.n))

    def read_eval(self):
        folds = []
        for f in range(self.params.folds):
            td = [x for x in range(self.params.n) if x % self.params.folds != f]
            qa = [(x, x * 2) for x in range(self.params.n) if x % self.params.folds == f]
            folds.append((td, {"fold": f}, qa))
        return folds


class ToyPreparator(Preparator):
    def prepare(self, td):
        return {"sum": sum(td), "data": td}


@dataclasses.dataclass
class AlgoParams(Params):
    mult: float = 2.0


class ToyAlgorithm(Algorithm):
    params_class = AlgoParams

    def train(self, pd):
        return {"mult": self.params.mult, "seen": len(pd["data"])}

    def predict(self, model, query):
        return query * model["mult"]


class ToyServing(Serving):
    def serve(self, query, predictions):
        return max(predictions)


def make_engine():
    return Engine(ToyDataSource, ToyPreparator,
                  {"toy": ToyAlgorithm, "toy2": ToyAlgorithm}, ToyServing)


def test_engine_train_chains_dase():
    engine = make_engine()
    ep = EngineParams(
        data_source_params=DSParams(n=5),
        algorithm_params_list=[("toy", AlgoParams(mult=3.0))],
    )
    models = engine.train(ep)
    assert models == [{"mult": 3.0, "seen": 5}]


def test_engine_multiple_algorithms_and_serving():
    engine = make_engine()
    ep = EngineParams(
        algorithm_params_list=[("toy", AlgoParams(mult=2.0)), ("toy2", AlgoParams(mult=5.0))],
    )
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    assert predict(3) == 15.0  # serving takes max over the two algorithms


def test_engine_unknown_algorithm_rejected():
    engine = make_engine()
    ep = EngineParams(algorithm_params_list=[("nope", AlgoParams())])
    with pytest.raises(ValueError, match="unknown algorithm"):
        engine.train(ep)


def test_engine_eval_produces_qpa_triples():
    engine = make_engine()
    ep = EngineParams(
        data_source_params=DSParams(n=6, folds=2),
        algorithm_params_list=[("toy", AlgoParams(mult=2.0))],
    )
    results = engine.eval(ep)
    assert len(results) == 2
    info, qpa = results[0]
    assert info == {"fold": 0}
    for q, p, a in qpa:
        assert p == q * 2.0 and a == q * 2


class AbsErrorMetric(AverageMetric):
    higher_is_better = False

    def score_one(self, q, p, a):
        return abs(p - a)


def test_metric_evaluator_picks_best_params():
    engine = make_engine()
    candidates = [
        EngineParams(data_source_params=DSParams(n=6),
                     algorithm_params_list=[("toy", AlgoParams(mult=m))])
        for m in (1.0, 2.0, 3.5)
    ]
    result = MetricEvaluator(AbsErrorMetric()).evaluate(engine, candidates)
    # actual = 2*q, so mult=2.0 has zero error and must win
    assert result.best_index == 1
    assert result.best_score == 0.0
    assert result.best_engine_params.algorithm_params_list[0][1].mult == 2.0


def test_engine_params_from_variant_json():
    engine = make_engine()
    variant = {
        "id": "default",
        "engineFactory": "whatever.Factory",
        "datasource": {"params": {"n": 7}},
        "algorithms": [{"name": "toy", "params": {"mult": 4.0}}],
    }
    ep = engine.engine_params_from_variant(variant)
    assert ep.data_source_params.n == 7
    assert ep.algorithm_params_list[0][1].mult == 4.0
    models = engine.train(ep)
    assert models == [{"mult": 4.0, "seen": 7}]


def test_params_binding_strictness():
    with pytest.raises(ValueError, match="unknown parameter"):
        DSParams.from_json({"bogus": 1})
    with pytest.raises(TypeError):
        AlgoParams.from_json({"mult": "not-a-number"})
    assert AlgoParams.from_json({"mult": 3}).mult == 3.0  # int→float coercion
    assert DSParams.from_json(None).n == 10
    assert EmptyParams.from_json({}) == EmptyParams()
