"""Multi-worker event ingestion: per-writer segments, group commit,
prefork event-server workers, crash safety.

The PR-1 tentpole's correctness contract: N concurrent writer processes
appending to one (app, channel) — each via its own ``seg-<tag>-NNNNN``
series — lose nothing and duplicate nothing across segment rotation, and
a SIGKILLed writer leaves a log every acknowledged event survives in
(PIO_FSYNC=always) that readers scan without crashing."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.events.event import Event
from predictionio_tpu.storage import AccessKey, App
from predictionio_tpu.storage.localfs import FSEvents


def _writer_script(root, tag, n, rotate_bytes=4096, fsync="rotate",
                   ack_each=False):
    """A real OS-process writer: inserts ``n`` events with client-supplied
    ids ``<tag>-<k>``, tiny segments so rotation happens constantly."""
    return textwrap.dedent(f"""
        import os, sys
        os.environ["PIO_FSYNC"] = {fsync!r}
        from predictionio_tpu.storage import localfs
        localfs.SEGMENT_MAX_BYTES = {rotate_bytes}
        ev = localfs.FSEvents({root!r}, writer_tag={tag!r})
        for k in range({n}):
            ev.insert_json_batch(
                [{{"event": "buy", "entityType": "user",
                   "entityId": "u%d" % k,
                   "eventId": "{tag}-%d" % k}}], 1)
            if {ack_each!r}:
                print("{tag}-%d" % k, flush=True)
        # close writers so the tail is flushed (rotate policy)
        for w in ev._writers.values():
            w.close()
        print("DONE", flush=True)
    """)


def test_concurrent_writer_processes_no_loss_no_dup(tmp_path):
    """Two real writer processes, one (app, channel), constant rotation:
    the union of their per-writer segments holds every event exactly
    once."""
    n = 300
    procs = [
        subprocess.Popen([sys.executable, "-c",
                          _writer_script(str(tmp_path), tag, n)],
                         stdout=subprocess.PIPE, text=True)
        for tag in ("wA", "wB")
    ]
    for p in procs:
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0 and "DONE" in out
    reader = FSEvents(tmp_path)
    ids = [e.event_id for e in reader._iter_raw(1, None)]
    expect = {f"{t}-{k}" for t in ("wA", "wB") for k in range(n)}
    assert len(ids) == len(expect), (len(ids), len(expect))
    assert set(ids) == expect
    # rotation actually happened, per writer, with per-writer naming
    chan = tmp_path / "events" / "app_1" / "_default"
    for tag in ("wA", "wB"):
        own = list(chan.glob(f"seg-{tag}-*.jsonl"))
        assert len(own) > 1, f"writer {tag} never rotated"


def test_kill_writer_mid_stream_acked_events_survive(tmp_path):
    """SIGKILL a writer mid-append (PIO_FSYNC=always): every event acked
    BEFORE the kill is recovered; the torn tail neither crashes the scan
    nor corrupts later appends by a restarted writer."""
    p = subprocess.Popen(
        [sys.executable, "-c",
         _writer_script(str(tmp_path), "wK", 100_000, fsync="always",
                        ack_each=True)],
        stdout=subprocess.PIPE, text=True)
    acked = []
    for line in p.stdout:
        acked.append(line.strip())
        if len(acked) >= 50:
            break
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=30)
    # under fsync=always every acked insert is durable
    reader = FSEvents(tmp_path)
    got = {e.event_id for e in reader._iter_raw(1, None)}   # must not raise
    missing = set(acked) - got
    assert not missing, f"acked events lost after SIGKILL: {missing}"
    # a restarted writer with the same tag heals any torn tail and
    # continues; the union stays readable and gains the new event
    w2 = FSEvents(tmp_path, writer_tag="wK")
    w2.insert(Event(event="buy", entity_type="user", entity_id="after",
                    event_id="after-kill"), 1)
    got2 = {e.event_id for e in FSEvents(tmp_path)._iter_raw(1, None)}
    assert "after-kill" in got2
    assert set(acked) <= got2


def test_group_commit_many_threads_exactly_once(tmp_path, monkeypatch):
    """In-process group commit: concurrent request threads' appends all
    land exactly once across rotation, under the strictest fsync policy
    (where group commit matters most)."""
    from predictionio_tpu.storage import localfs

    monkeypatch.setenv("PIO_FSYNC", "always")
    monkeypatch.setattr(localfs, "SEGMENT_MAX_BYTES", 8192)
    ev = FSEvents(tmp_path)
    n_threads, per_thread = 8, 40
    errs = []

    def work(t):
        try:
            for k in range(per_thread):
                r = ev.insert_json_batch(
                    [{"event": "buy", "entityType": "user",
                      "entityId": f"u{t}",
                      "eventId": f"t{t}-{k}"}], 1)
                assert r[0]["status"] == 201
        except Exception as e:   # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=work, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    ids = [e.event_id for e in ev._iter_raw(1, None)]
    assert len(ids) == n_threads * per_thread
    assert len(set(ids)) == n_threads * per_thread


def test_append_error_nacks_whole_group(tmp_path, monkeypatch):
    """A failed write (ENOSPC analogue) must raise for EVERY member of
    the commit group — no event may be acked without landing on disk —
    and the group must recover for subsequent appends."""
    from predictionio_tpu.storage import localfs

    ev = FSEvents(tmp_path)
    boom = {"on": True}
    orig_append = localfs._SegmentWriter.append

    def flaky_append(self, text):
        if boom["on"]:
            raise OSError(28, "No space left on device")
        return orig_append(self, text)

    monkeypatch.setattr(localfs._SegmentWriter, "append", flaky_append)
    errs = []

    def work(k):
        try:
            ev.insert(Event(event="buy", entity_type="user",
                            entity_id=f"u{k}"), 1)
        except OSError as e:
            errs.append(e)

    ts = [threading.Thread(target=work, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(errs) == 4   # every member NACKed
    boom["on"] = False
    ev.insert(Event(event="buy", entity_type="user", entity_id="ok",
                    event_id="recovered"), 1)
    assert {e.event_id for e in ev._iter_raw(1, None)} == {"recovered"}


def test_torn_tail_skipped_and_healed(tmp_path):
    """An unterminated final line (writer killed mid-append) is skipped by
    scans and truncated away when the owning writer reopens the segment."""
    ev = FSEvents(tmp_path)
    ev.insert(Event(event="buy", entity_type="user", entity_id="u1",
                    event_id="whole"), 1)
    for w in ev._writers.values():
        w.close()
    ev._writers.clear()
    chan = tmp_path / "events" / "app_1" / "_default"
    seg = sorted(chan.glob("seg-*.jsonl"))[-1]
    with open(seg, "a") as f:
        f.write('{"eventId": "torn", "event": "bu')   # no newline
    got = [e.event_id for e in FSEvents(tmp_path)._iter_raw(1, None)]
    assert got == ["whole"]
    # the writer truncates the torn tail before appending
    ev2 = FSEvents(tmp_path)
    ev2.insert(Event(event="buy", entity_type="user", entity_id="u2",
                     event_id="next"), 1)
    got = sorted(e.event_id for e in FSEvents(tmp_path)._iter_raw(1, None))
    assert got == ["next", "whole"]
    raw = seg.read_text()
    assert "torn" not in raw and raw.endswith("\n")


def test_kill_replicated_shard_primary_mid_group_commit(tmp_path):
    """The replicated-path extension of the SIGKILL crash test: a real
    writer process ingests through the sharded store's semi-sync
    replication barrier (every printed ack means BOTH nodes hold the
    event), is SIGKILLed mid-group-commit, and the primary node dirs are
    yanked away.  The promoted follower must serve every acked event
    exactly once, and the un-acked tail is either absent or present at
    most once (at-least-once ingest contract); a restarted writer
    continues on the promoted topology."""
    from pathlib import Path

    from predictionio_tpu.storage.sharded import ShardedEvents

    scripts_dir = str(Path(__file__).resolve().parent.parent / "scripts")
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    from check_store_failover import writer_script

    script = writer_script(str(tmp_path / "store"), "rk", 100_000)
    p = subprocess.Popen([sys.executable, "-c", script],
                         stdout=subprocess.PIPE, text=True)
    acked = []
    for line in p.stdout:
        acked.append(line.strip())
        if len(acked) >= 60:
            break
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=30)
    # the "node died" injection: both shard primaries vanish outright
    import shutil

    for k in (0, 1):
        pdir = tmp_path / "store" / f"shard_{k:02d}" / "a"
        shutil.move(str(pdir), str(pdir) + ".lost")
    os.environ["PIO_FSYNC"] = "always"
    ev = ShardedEvents(tmp_path / "store", shards=2, replicas=2)
    try:
        got = [e.event_id for e in ev.scan(1)]
        missing = set(acked) - set(got)
        assert not missing, f"acked events lost after promotion: {missing}"
        assert len(got) == len(set(got)), "duplicated events after promotion"
        # un-acked tail: absent or healed (each id at most once) — already
        # covered by the uniqueness assert; promotion happened on both
        topo = ev.topology_status()
        assert all(s["primary"] == "b" and s["epoch"] == 1
                   for s in topo["perShard"]), topo
        # a restarted writer keeps ingesting on the promoted topology
        res = ev.insert_json_batch(
            [{"event": "buy", "entityType": "user", "entityId": "uZ",
              "eventId": "after-kill"}], 1)
        assert res[0]["status"] == 201
        assert "after-kill" in {e.event_id for e in ev.scan(1)}
    finally:
        ev.close()
        os.environ.pop("PIO_FSYNC", None)


# -- HTTP layer ------------------------------------------------------------


@pytest.fixture()
def fs_event_server(fs_storage):
    from predictionio_tpu.api.event_server import run_event_server

    app_id = fs_storage.apps.insert(App(0, "mwapp"))
    key = fs_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=fs_storage,
                             background=True)
    yield {"base": f"http://127.0.0.1:{httpd.server_address[1]}",
           "key": key, "app_id": app_id, "storage": fs_storage}
    httpd.shutdown()
    httpd.server_close()


def _post(url, body):
    data = json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method="POST",
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read() or b"null")


def test_batch_path_matches_n_single_posts(fs_event_server):
    """Group-commit batch parity: one N-event batch stores the same events
    (modulo server-assigned ids/times) as N single posts, with identical
    per-item statuses."""
    base, key = fs_event_server["base"], fs_event_server["key"]
    events = [
        {"event": "buy", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": f"i{i}",
         "properties": {"price": float(i)}}
        for i in range(5)
    ]
    bad = {"entityType": "user", "entityId": "broken"}   # missing event
    status, results = _post(
        f"{base}/batch/events.json?accessKey={key}", events + [bad])
    assert status == 200
    assert [r["status"] for r in results] == [201] * 5 + [400]
    single_statuses = []
    for e in events:
        s, _ = _post(f"{base}/events.json?accessKey={key}", e)
        single_statuses.append(s)
    try:
        _post(f"{base}/events.json?accessKey={key}", bad)
        single_statuses.append(200)
    except urllib.error.HTTPError as e:
        single_statuses.append(e.code)
    assert single_statuses == [201] * 5 + [400]

    def strip(e):
        d = e.to_json()
        for k in ("eventId", "eventTime", "creationTime"):
            d.pop(k, None)
        return json.dumps(d, sort_keys=True)

    st = fs_event_server["storage"]
    got = sorted(strip(e) for e in st.l_events.scan(fs_event_server["app_id"]))
    # every event stored twice (once per path), identically
    assert got == sorted(
        2 * [strip(Event.from_json(e)) for e in events])


def test_pio_max_batch_env(fs_storage, monkeypatch):
    """PIO_MAX_BATCH raises the batch cap (default 50 stays for reference
    parity)."""
    from predictionio_tpu.api.event_server import run_event_server

    monkeypatch.setenv("PIO_MAX_BATCH", "10")
    app_id = fs_storage.apps.insert(App(0, "capapp"))
    key = fs_storage.access_keys.insert(AccessKey("", app_id, []))
    httpd = run_event_server(host="127.0.0.1", port=0, storage=fs_storage,
                             background=True)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        ok = [{"event": "buy", "entityType": "user", "entityId": f"u{i}"}
              for i in range(10)]
        status, results = _post(f"{base}/batch/events.json?accessKey={key}", ok)
        assert status == 200 and all(r["status"] == 201 for r in results)
        try:
            status, _ = _post(f"{base}/batch/events.json?accessKey={key}",
                              ok + ok[:1])
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 400
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_eventserver_prefork_workers_end_to_end(tmp_path, monkeypatch):
    """`pio eventserver --workers 2` semantics, driven programmatically:
    both workers answer on one port (distinct pids), events ingested
    through the group land exactly once in the per-writer segment union,
    SIGKILLing one worker loses nothing acked (fsync=always), and the
    survivors keep ingesting."""
    from predictionio_tpu.api.event_server import run_event_server
    from predictionio_tpu.storage.locator import set_storage

    store = tmp_path / "store"
    env_vars = {
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(store),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        "PIO_FSYNC": "always",
        "PIO_JAX_PLATFORM": "cpu",
    }
    for k, v in env_vars.items():
        monkeypatch.setenv(k, v)
    monkeypatch.delenv("PIO_WRITER_TAG", raising=False)
    from predictionio_tpu.storage.locator import Storage, StorageConfig
    meta = Storage(StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(store)}},
        repositories={r: "FS" for r in ("METADATA", "EVENTDATA",
                                        "MODELDATA")}))
    app_id = meta.apps.insert(App(0, "pfesapp"))
    key = meta.access_keys.insert(AccessKey("", app_id, []))
    set_storage(None)   # workers>1 resolves storage from env
    httpd = run_event_server(host="127.0.0.1", port=0, background=True,
                             workers=2)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        assert len(httpd.pio_workers) == 1
        # wait until BOTH workers answer (child needs interpreter startup)
        pids, deadline = set(), time.time() + 90
        while len(pids) < 2 and time.time() < deadline:
            try:
                with urllib.request.urlopen(base + "/", timeout=2) as r:
                    pids.add(json.loads(r.read())["pid"])
            except Exception:
                time.sleep(0.2)
        assert len(pids) == 2, f"second worker never came up: {pids}"

        def post_event(eid):
            # fresh connection each time so the kernel balances across
            # workers; retry on the error surfaced when a connection lands
            # on the killed worker (client-supplied id keeps it idempotent)
            body = {"event": "buy", "entityType": "user",
                    "entityId": "u1", "eventId": eid}
            for _ in range(5):
                try:
                    s, r = _post(f"{base}/events.json?accessKey={key}", body)
                    assert s == 201
                    return
                except Exception:
                    time.sleep(0.2)
            raise AssertionError(f"event {eid} could not be posted")

        acked = []
        for k2 in range(30):
            post_event(f"pre-{k2}")
            acked.append(f"pre-{k2}")
        # kill the CHILD worker outright; the parent keeps serving
        child = httpd.pio_workers[0]
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
        for k2 in range(30):
            post_event(f"post-{k2}")
            acked.append(f"post-{k2}")
        reader = FSEvents(store)
        got = [e.event_id for e in reader._iter_raw(app_id, None)]
        assert set(acked) <= set(got), f"lost: {set(acked) - set(got)}"
        # idempotent retries may legitimately duplicate an id; anything
        # never retried must appear exactly once — and the union must come
        # from BOTH writers' segment series
        chan = store / "events" / f"app_{app_id}" / "_default"
        tags = {p.name.split("-")[1] for p in chan.glob("seg-*.jsonl")}
        assert "w0" in tags and len(tags) >= 2, tags
    finally:
        httpd.shutdown()
        httpd.server_close()
        set_storage(None)


def test_native_scan_skips_torn_tail(tmp_path):
    """The native scanner and the Python scan must agree on torn tails:
    an unterminated final line is unacknowledged and skipped by both."""
    from predictionio_tpu.native.scanner import native_available, scan_segments

    if not native_available():
        pytest.skip("native scanner unavailable")
    seg = tmp_path / "seg-00000.jsonl"
    good = {"event": "view", "entityType": "user", "entityId": "u1",
            "eventTime": "2026-01-01T00:00:00+00:00"}
    seg.write_text(json.dumps(good) + "\n" + json.dumps(good))  # torn tail
    assert len(scan_segments([seg])) == 1
