"""Multi-node plane replication: wire-level fault injection + parity.

The replication contract mirrors the plane's local one: a subscriber
node serving replicated generations is bit-indistinguishable from the
publisher node — so every test here diffs arrays/responses exactly.
Fault injection covers the three wire failure modes: a torn mid-blob
transfer (quarantined, re-requested, never served), a killed subscriber
resuming from its last-acked generation (incremental, no re-sync), and
a cold subscriber catching up from the nearest keyframe.

The multi-process drill (publisher deploy + 2 subscriber deploys, live
folds, mid-stream kill) lives in scripts/check_plane_replication.py,
wrapped for tier-1 at the bottom.
"""

import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from test_model_plane import _canon, _corpus, _seed, _ur  # shared helpers

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def host_serving(monkeypatch):
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")


@pytest.fixture()
def fast_repl(monkeypatch):
    monkeypatch.setenv("PIO_MODEL_PLANE_POLL_S", "0.05")
    monkeypatch.setenv("PIO_PLANE_REPL_PING_S", "0.3")
    monkeypatch.setenv("PIO_PLANE_REPL_BACKOFF_S", "0.1")


def _publisher(tmp_path, mem_storage, n_gens=1):
    """A trained model published ``n_gens`` times into a fresh plane
    dir; returns (plane, model, algo)."""
    from predictionio_tpu.streaming.plane import ModelPlane

    _seed(mem_storage)
    engine, ep, algo = _ur()
    model = engine.train(ep)[0]
    pub = ModelPlane(str(tmp_path / "pub-plane"))
    for _ in range(n_gens):
        pub.publish([model], {"mode": "test"})
    return pub, model, algo


def _start_pair(pub, sub_dir, node="t-sub"):
    from predictionio_tpu.streaming.replicate import (
        PlaneReplicator, PlaneSubscriber,
    )

    repl = PlaneReplicator(pub, bind="127.0.0.1:0")
    repl.start()
    sub = PlaneSubscriber(str(sub_dir), f"127.0.0.1:{repl.port}",
                          node=node)
    sub.start()
    return repl, sub


def _assert_parity(sub_dir, model, algo):
    """The subscriber's current generation answers every corpus query
    bit-identically to the publisher's private model."""
    from predictionio_tpu.streaming.plane import ModelPlane

    reader = ModelPlane(str(sub_dir))
    mapped, _info = reader.load(reader.current())
    for name in model.indicator_idx:
        assert np.array_equal(mapped.indicator_idx[name],
                              model.indicator_idx[name])
    for q in _corpus():
        assert _canon(algo.predict(mapped, q)) == _canon(
            algo.predict(model, q))


# -- cold catch-up -----------------------------------------------------------


def test_cold_subscriber_keyframe_catchup_bit_exact(
        mem_storage, host_serving, fast_repl, tmp_path):
    """A fresh subscriber joins mid-chain: the publisher re-plans from
    the nearest keyframe and replays the delta chain forward; the
    composed model on the subscriber node is bit-exact vs the
    publisher's, and the manifest carries the replication marker."""
    from predictionio_tpu.streaming.plane import REPLICA_KEY, ModelPlane

    pub, model, algo = _publisher(tmp_path, mem_storage, n_gens=4)
    assert pub.current()["generation"] == 4
    repl, sub = _start_pair(pub, tmp_path / "sub-plane")
    try:
        assert sub.wait_generation(4, timeout=20)
        _assert_parity(tmp_path / "sub-plane", model, algo)
        cur = ModelPlane(str(tmp_path / "sub-plane")).current()
        assert cur[REPLICA_KEY] == sub.source
        st = sub.status()
        assert st["role"] == "subscriber"
        assert st["lagGenerations"] == 0
        # publisher-side view converges too (the ack carried have=4)
        for _ in range(100):
            pst = repl.status()
            if pst["subscribers"] and \
                    pst["subscribers"][0]["ackedGeneration"] == 4:
                break
            time.sleep(0.05)
        assert pst["role"] == "publisher"
        assert pst["subscribers"][0]["lagGenerations"] == 0
    finally:
        sub.stop()
        repl.stop()


def test_live_publishes_stream_to_subscriber(
        mem_storage, host_serving, fast_repl, tmp_path):
    """Generations published WHILE a subscriber is connected propagate
    incrementally (no re-sync) — the delta wire bytes are a fraction of
    the keyframe's."""
    from predictionio_tpu.obs import metrics as obs_metrics

    pub, model, algo = _publisher(tmp_path, mem_storage, n_gens=1)
    repl, sub = _start_pair(pub, tmp_path / "sub-plane")
    reg = obs_metrics.get_registry()
    resync = reg.counter("pio_plane_repl_resyncs_total", "x")
    try:
        assert sub.wait_generation(1, timeout=20)
        lag0 = resync.value(reason="lag")
        torn0 = resync.value(reason="torn")
        for _ in range(3):
            pub.publish([model], {"mode": "test"})
        assert sub.wait_generation(4, timeout=20)
        _assert_parity(tmp_path / "sub-plane", model, algo)
        # steady state is incremental: no lag/torn re-syncs
        assert resync.value(reason="lag") == lag0
        assert resync.value(reason="torn") == torn0
        bytes_total = reg.counter("pio_plane_repl_bytes_total", "x")
        assert bytes_total.value(dir="out", kind="delta") > 0
        assert bytes_total.value(dir="in", kind="delta") == \
            bytes_total.value(dir="out", kind="delta")
    finally:
        sub.stop()
        repl.stop()


# -- torn transfer -----------------------------------------------------------


def test_torn_transfer_quarantines_and_rerequests(
        mem_storage, host_serving, fast_repl, tmp_path, monkeypatch):
    """A mid-blob corruption (hash mismatch on arrival) quarantines the
    file on the subscriber, never flips over it, and re-requests the
    chain — converging bit-exact on the retry."""
    from predictionio_tpu.streaming import replicate

    pub, model, algo = _publisher(tmp_path, mem_storage, n_gens=2)
    # corrupt exactly one file frame's advertised hash: the payload
    # lands, fails verification, and the batch is re-requested
    real_send = replicate._send_frame
    tears = {"left": 1}

    def flaky_send(sock, header, payload_len=0):
        if header.get("type") == "file" and tears["left"]:
            tears["left"] -= 1
            header = dict(header, sha256="0" * 64)
        real_send(sock, header, payload_len)

    monkeypatch.setattr(replicate, "_send_frame", flaky_send)
    repl, sub = _start_pair(pub, tmp_path / "sub-plane")
    try:
        assert sub.wait_generation(2, timeout=30)
        assert tears["left"] == 0           # the fault actually fired
        assert sub.resyncs >= 1             # torn batch was re-requested
        quarantined = list(Path(tmp_path / "sub-plane")
                           .glob("*.quarantine"))
        assert quarantined                  # evidence kept out-of-band
        _assert_parity(tmp_path / "sub-plane", model, algo)
    finally:
        sub.stop()
        repl.stop()


# -- resume ------------------------------------------------------------------


def test_killed_subscriber_resumes_from_last_acked_generation(
        mem_storage, host_serving, fast_repl, tmp_path):
    """A subscriber that dies (stop == the daemon's crash point: the
    local manifest IS its resume state) reconnects with have=last
    flipped generation and receives only the missing generations —
    no keyframe re-sync, bit-exact convergence."""
    from predictionio_tpu.obs import metrics as obs_metrics
    from predictionio_tpu.streaming.replicate import PlaneSubscriber

    pub, model, algo = _publisher(tmp_path, mem_storage, n_gens=2)
    repl, sub = _start_pair(pub, tmp_path / "sub-plane")
    reg = obs_metrics.get_registry()
    resync = reg.counter("pio_plane_repl_resyncs_total", "x")
    try:
        assert sub.wait_generation(2, timeout=20)
        sub.stop()      # SIGKILL-equivalent for the daemon's state:
        # nothing is persisted beyond the plane dir itself
        for _ in range(2):
            pub.publish([model], {"mode": "test"})
        cold0 = resync.value(reason="cold")
        lag0 = resync.value(reason="lag")
        sub2 = PlaneSubscriber(str(tmp_path / "sub-plane"),
                               f"127.0.0.1:{repl.port}", node="t-sub-2")
        sub2.start()
        assert sub2.generation == 2         # resumed, not cold
        try:
            assert sub2.wait_generation(4, timeout=20)
            # incremental catch-up: no cold/lag re-sync fired
            assert resync.value(reason="cold") == cold0
            assert resync.value(reason="lag") == lag0
            _assert_parity(tmp_path / "sub-plane", model, algo)
        finally:
            sub2.stop()
    finally:
        sub.stop()
        repl.stop()


def test_lagged_past_gc_resyncs_from_keyframe(
        mem_storage, host_serving, fast_repl, tmp_path, monkeypatch):
    """A subscriber that fell behind the publisher's GC window cannot be
    served incrementally — the publisher re-plans from the keyframe
    chain (reason=lag) and still converges bit-exact."""
    from predictionio_tpu.obs import metrics as obs_metrics
    from predictionio_tpu.streaming.replicate import PlaneSubscriber

    monkeypatch.setenv("PIO_MODEL_PLANE_KEEP", "2")
    monkeypatch.setenv("PIO_MODEL_PLANE_FULL_EVERY", "2")
    pub, model, algo = _publisher(tmp_path, mem_storage, n_gens=2)
    repl, sub = _start_pair(pub, tmp_path / "sub-plane")
    reg = obs_metrics.get_registry()
    resync = reg.counter("pio_plane_repl_resyncs_total", "x")
    try:
        assert sub.wait_generation(2, timeout=20)
        sub.stop()
        lag0 = resync.value(reason="lag")
        for _ in range(6):                  # GC moves well past gen 2
            pub.publish([model], {"mode": "test"})
        sub2 = PlaneSubscriber(str(tmp_path / "sub-plane"),
                               f"127.0.0.1:{repl.port}", node="t-sub-2")
        sub2.start()
        try:
            assert sub2.wait_generation(8, timeout=20)
            assert resync.value(reason="lag") > lag0
            _assert_parity(tmp_path / "sub-plane", model, algo)
        finally:
            sub2.stop()
    finally:
        sub.stop()
        repl.stop()


# -- split-brain guards ------------------------------------------------------


def test_subscriber_refuses_locally_published_dir(
        mem_storage, host_serving, tmp_path):
    """A plane dir whose manifest lacks the replication marker belongs
    to a LOCAL publisher — subscribing to it must refuse, not fight."""
    from predictionio_tpu.streaming.replicate import PlaneSubscriber

    pub, _model, _algo = _publisher(tmp_path, mem_storage, n_gens=1)
    sub = PlaneSubscriber(str(pub.dir), "127.0.0.1:1")
    with pytest.raises(RuntimeError, match="locally-published"):
        sub.start()


def test_local_publisher_forces_keyframes_on_replica_dir(
        mem_storage, host_serving, tmp_path):
    """The dual guard: a local publisher finding the replication marker
    never publishes a delta against a chain another node wrote."""
    from predictionio_tpu.streaming.plane import REPLICA_KEY, ModelPlane

    pub, model, _algo = _publisher(tmp_path, mem_storage, n_gens=2)
    cur = pub.current()
    assert cur["kind"] == "delta"           # deltas flow normally
    pub._write_manifest({**cur, REPLICA_KEY: "other-node:9999"})
    pub.publish([model], {"mode": "test"})
    assert pub.current()["kind"] == "full"  # degraded to keyframe


def test_chain_files_walks_prev_links(mem_storage, host_serving,
                                      tmp_path, monkeypatch):
    """chain_files returns [keyframe .. file] in order, from headers
    alone; a broken link raises _PlaneCorrupt naming the culprit."""
    from predictionio_tpu.streaming.plane import _PlaneCorrupt

    monkeypatch.setenv("PIO_MODEL_PLANE_KEEP", "10")
    pub, _model, _algo = _publisher(tmp_path, mem_storage, n_gens=3)
    cur = pub.current()
    chain = pub.chain_files(cur["file"])
    assert chain[0].endswith(".arena")
    assert chain[-1] == cur["file"]
    assert chain == sorted(chain)
    os.unlink(os.path.join(pub.dir, chain[0]))
    with pytest.raises(_PlaneCorrupt):
        pub.chain_files(cur["file"])


# -- multi-process drill (tier-1 wrapper) ------------------------------------


def test_check_plane_replication_script():
    """Tier-1 wrapper for scripts/check_plane_replication.py: publisher
    deploy + 2 subscriber deploys, live folds, complete lineage on every
    node, one subscriber killed mid-stream re-syncs with zero
    staleness."""
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "check_plane_replication.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok:" in r.stdout
