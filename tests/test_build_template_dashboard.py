"""pio build / pio template / dashboard / engine manifests
(reference: Console.build + RegisterEngine → EngineManifests; template
gallery; dashboard module)."""

import json
import os
import urllib.request

import pytest

from predictionio_tpu.cli.main import main as pio_main
from predictionio_tpu.storage.base import EngineManifest


# ---------------------------------------------------------------------------
# EngineManifests repository across backends
# ---------------------------------------------------------------------------


def _manifest_roundtrip(store):
    m = EngineManifest(
        id="my-engine", version="1", name="My Engine",
        description="d", files=["/tmp/engine.json"],
        engine_factory="predictionio_tpu.models.recommendation.RecommendationEngine",
    )
    store.insert(m)
    got = store.get("my-engine", "1")
    assert got is not None
    assert got.engine_factory == m.engine_factory
    assert got.files == ["/tmp/engine.json"]
    # upsert replaces
    m2 = EngineManifest(id="my-engine", version="1", name="Renamed")
    store.insert(m2)
    assert store.get("my-engine", "1").name == "Renamed"
    assert len(store.get_all()) == 1
    assert store.get("my-engine", "2") is None
    assert store.delete("my-engine", "1")
    assert not store.delete("my-engine", "1")


def test_engine_manifests_memory():
    from predictionio_tpu.storage.memory import MemEngineManifests

    _manifest_roundtrip(MemEngineManifests())


def test_engine_manifests_localfs(tmp_path):
    from predictionio_tpu.storage.localfs import FSEngineManifests

    _manifest_roundtrip(FSEngineManifests(tmp_path))


def test_engine_manifests_sql():
    from predictionio_tpu.storage.sql import SQLClient, SQLEngineManifests

    _manifest_roundtrip(SQLEngineManifests(SQLClient(":memory:")))


# ---------------------------------------------------------------------------
# pio build
# ---------------------------------------------------------------------------


def test_pio_build_registers_manifest(mem_storage, tmp_path, capsys):
    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps({
        "id": "build-test",
        "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "App"}},
        "algorithms": [{"name": "als", "params": {"rank": 4}}],
    }))
    rc = pio_main(["build", "--engine-json", str(engine_json)])
    assert rc == 0
    assert "Build successful" in capsys.readouterr().out
    m = mem_storage.engine_manifests.get("build-test", "1")
    assert m is not None
    assert m.engine_factory.endswith("RecommendationEngine")
    assert str(engine_json) in m.files[0]


def test_pio_build_rejects_bad_factory(mem_storage, tmp_path):
    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps({"engineFactory": "no.such.module.Engine"}))
    assert pio_main(["build", "--engine-json", str(engine_json)]) == 1


# ---------------------------------------------------------------------------
# pio template
# ---------------------------------------------------------------------------


def test_template_list(capsys):
    assert pio_main(["template", "list"]) == 0
    out = capsys.readouterr().out
    for name in ("recommendation", "classification", "similar_product",
                 "universal_recommender", "text"):
        assert name in out


@pytest.mark.parametrize("template", [
    "recommendation", "classification", "similar_product",
    "universal_recommender", "text", "ecommerce", "complementary_purchase",
    "product_ranking", "lead_scoring",
])
def test_template_scaffold_builds(template, mem_storage, tmp_path):
    """Every scaffolded engine.json must pass `pio build` (params bind)."""
    dest = tmp_path / template
    assert pio_main(["template", "new", template, str(dest)]) == 0
    assert (dest / "engine.json").exists()
    assert (dest / "README.md").exists()
    assert pio_main(["build", "--engine-json", str(dest / "engine.json")]) == 0


def test_template_scaffold_refuses_overwrite(tmp_path):
    dest = tmp_path / "t"
    assert pio_main(["template", "new", "text", str(dest)]) == 0
    assert pio_main(["template", "new", "text", str(dest)]) == 1


def test_template_unknown(tmp_path):
    assert pio_main(["template", "new", "nope", str(tmp_path / "x")]) == 1


# ---------------------------------------------------------------------------
# dashboard
# ---------------------------------------------------------------------------


def test_dashboard_server(mem_storage):
    import datetime as dt

    from predictionio_tpu.api.dashboard import run_dashboard
    from predictionio_tpu.storage.base import EngineInstance, EvaluationInstance

    now = dt.datetime.now(dt.timezone.utc)
    mem_storage.engine_instances.insert(EngineInstance(
        id="ei1", status="COMPLETED", start_time=now, end_time=now,
        engine_id="reco", engine_version="1", engine_variant="default",
        engine_factory="f",
    ))
    mem_storage.evaluation_instances.insert(EvaluationInstance(
        id="ev1", status="EVALCOMPLETED", start_time=now, end_time=now,
        evaluation_class="my.Eval", evaluator_results="metric=0.9",
    ))
    httpd = run_dashboard(host="127.0.0.1", port=0, storage=mem_storage,
                          background=True)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        html = urllib.request.urlopen(base + "/").read().decode()
        assert "PredictionIO-TPU Dashboard" in html
        assert "my.Eval" in html and "reco" in html
        doc = json.loads(urllib.request.urlopen(base + "/dashboard.json").read())
        assert doc["evaluations"][0]["id"] == "ev1"
        assert doc["engineInstances"][0]["engineId"] == "reco"
        evs = json.loads(urllib.request.urlopen(base + "/evaluations.json").read())
        assert evs["evaluations"][0]["evaluatorResults"] == "metric=0.9"
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# manifest-based resolution + local engine modules
# ---------------------------------------------------------------------------


def test_train_resolves_engine_via_manifest(mem_storage, tmp_path, capsys):
    """After `pio build`, train finds the engine by --engine-id even when
    run from elsewhere (reference: RunWorkflow resolving via EngineManifest)."""
    import numpy as np

    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.storage.base import App

    app_id = mem_storage.apps.insert(App(0, "mfapp"))
    rng = np.random.default_rng(0)
    events = []
    for u in range(10):
        for i in range(6):
            if rng.random() < 0.9:
                liked = (u < 5) == (i < 3)
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": 5.0 if liked else 1.0})))
    mem_storage.l_events.insert_batch(events, app_id)

    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps({
        "id": "mf-engine",
        "engineFactory": "predictionio_tpu.models.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "mfapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 4, "lambda": 0.05,
                                   "meshDp": 1}}],
    }))
    assert pio_main(["build", "--engine-json", str(engine_json)]) == 0
    # engine.json path that does not exist + --engine-id -> manifest lookup
    rc = pio_main(["train", "--engine-json", str(tmp_path / "nope.json"),
                   "--engine-id", "mf-engine"])
    assert rc == 0
    assert "Training completed" in capsys.readouterr().out


def test_local_engine_module_importable(mem_storage, tmp_path):
    """engineFactory may name a module that lives next to engine.json
    (the scaffold README's customization path)."""
    (tmp_path / "my_local_engine.py").write_text(
        "from predictionio_tpu.models.recommendation import RecommendationEngine\n"
        "class LocalEngine(RecommendationEngine):\n"
        "    pass\n"
    )
    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps({
        "id": "local-engine",
        "engineFactory": "my_local_engine.LocalEngine",
        "datasource": {"params": {"appName": "App"}},
        "algorithms": [{"name": "als", "params": {"rank": 4}}],
    }))
    assert pio_main(["build", "--engine-json", str(engine_json)]) == 0
    m = mem_storage.engine_manifests.get("local-engine", "1")
    assert m is not None and m.engine_factory == "my_local_engine.LocalEngine"


def test_import_export_channel(mem_storage, tmp_path, capsys):
    """pio import/export --channel targets a named channel (reference:
    tools Import/Export channel support)."""
    assert pio_main(["app", "new", "ChApp"]) == 0
    assert pio_main(["channel", "new", "ChApp", "side"]) == 0
    capsys.readouterr()

    events = tmp_path / "ev.jsonl"
    events.write_text("\n".join(
        json.dumps({"event": "view", "entityType": "user", "entityId": f"u{k}",
                    "targetEntityType": "item", "targetEntityId": f"i{k}"})
        for k in range(5)) + "\n")
    assert pio_main(["import", "--app-name", "ChApp", "--channel", "side",
                     "--input", str(events)]) == 0
    assert "channel side" in capsys.readouterr().out

    # default channel is untouched; channel export returns the 5 events
    out_def = tmp_path / "default.jsonl"
    out_side = tmp_path / "side.jsonl"
    assert pio_main(["export", "--app-name", "ChApp", "--output", str(out_def)]) == 0
    assert pio_main(["export", "--app-name", "ChApp", "--channel", "side",
                     "--output", str(out_side)]) == 0
    assert out_def.read_text().strip() == ""
    assert len(out_side.read_text().strip().splitlines()) == 5

    # unknown channel rejected
    assert pio_main(["import", "--app-name", "ChApp", "--channel", "nope",
                     "--input", str(events)]) == 1


def test_example_engine_jsons_bind(mem_storage):
    """Every examples/*/engine.json must pass `pio build` (factory resolves,
    params bind against the dataclasses)."""
    import glob

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "examples", "*", "engine.json")))
    assert len(paths) >= 9
    for p in paths:
        assert pio_main(["build", "--engine-json", p]) == 0, p


def test_import_reports_bad_line_number(mem_storage, tmp_path):
    """A malformed line aborts `pio import` with its exact line number."""
    from predictionio_tpu.storage import App

    mem_storage.apps.insert(App(0, "ImpApp"))
    f = tmp_path / "events.jsonl"
    f.write_text(
        '{"event": "buy", "entityType": "u", "entityId": "a"}\n'
        "\n"   # blank lines are skipped and don't shift reported numbers
        '{"event": "buy", "entityType": "u"}\n'
        '{"event": "buy", "entityType": "u", "entityId": "c"}\n')
    import contextlib
    import io

    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = pio_main(["import", "--app-name", "ImpApp", "--input", str(f)])
    assert rc == 1
    assert "line 3" in err.getvalue(), err.getvalue()
    # syntactically invalid JSON also aborts with the line number, not a
    # traceback
    f.write_text('{"event": "buy", "entityType": "u", "entityId": "a"}\n'
                 '{"event": "buy",\n')
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = pio_main(["import", "--app-name", "ImpApp", "--input", str(f)])
    assert rc == 1 and "line 2" in err.getvalue(), err.getvalue()


def test_import_good_file_counts(mem_storage, tmp_path):
    from predictionio_tpu.storage import App

    app_id = mem_storage.apps.insert(App(0, "ImpApp2"))
    f = tmp_path / "events.jsonl"
    f.write_text("".join(
        json.dumps({"event": "buy", "entityType": "u", "entityId": f"u{k}"}) + "\n"
        for k in range(25)))
    assert pio_main(["import", "--app-name", "ImpApp2", "--input", str(f)]) == 0
    assert len(list(mem_storage.l_events.find(app_id))) == 25
