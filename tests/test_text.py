"""Text-classification template tests: spam-vs-ham over all three algorithms
(SMS-spam-shaped, BASELINE.md config #2/#5)."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.text import TextClassificationEngine, TextQuery
from predictionio_tpu.models.text.engine import (
    TextDSParams,
    TextLogRegParams,
    TextMLPParams,
    TextNBParams,
)
from predictionio_tpu.storage import App

SPAM = ["win free cash now", "free prize claim now", "win money fast free",
        "claim your free reward now", "cash prize winner claim today",
        "free free win big money"]
HAM = ["see you at dinner tonight", "meeting moved to tuesday",
       "can you pick up milk", "the report is due tomorrow",
       "happy birthday hope all is well", "lunch at noon works for me"]


@pytest.fixture()
def text_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "txtapp"))
    events = []
    for k, t in enumerate(SPAM):
        events.append(Event(event="train", entity_type="content", entity_id=f"s{k}",
                            properties=DataMap({"text": t, "label": "spam"})))
    for k, t in enumerate(HAM):
        events.append(Event(event="train", entity_type="content", entity_id=f"h{k}",
                            properties=DataMap({"text": t, "label": "ham"})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


@pytest.mark.parametrize("algo,params", [
    ("nb", TextNBParams(dim=512)),
    ("logreg", TextLogRegParams(dim=512, iterations=40)),
    ("mlp", TextMLPParams(vocab_size=512, max_len=16, iterations=120,
                          embed_dim=16, hidden_dim=32)),
])
def test_text_classification(text_app, algo, params):
    engine = TextClassificationEngine.apply()
    ep = EngineParams(
        data_source_params=TextDSParams(app_name="txtapp"),
        algorithm_params_list=[(algo, params)],
    )
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    spam_pred = predict(TextQuery("claim free cash prize now"))
    ham_pred = predict(TextQuery("are we still on for lunch tomorrow"))
    assert spam_pred.label == "spam", (algo, spam_pred)
    assert ham_pred.label == "ham", (algo, ham_pred)
    assert 0.0 <= spam_pred.confidence <= 1.0


def test_text_eval_folds(text_app):
    from predictionio_tpu.controller.evaluation import AverageMetric, MetricEvaluator

    class Accuracy(AverageMetric):
        def score_one(self, q, p, a):
            return 1.0 if p.label == a else 0.0

    engine = TextClassificationEngine.apply()
    ep = EngineParams(
        data_source_params=TextDSParams(app_name="txtapp", eval_k=3),
        algorithm_params_list=[("nb", TextNBParams(dim=512))],
    )
    result = MetricEvaluator(Accuracy()).evaluate(engine, [ep])
    assert result.best_score >= 0.5


def test_hashing_is_stable():
    from predictionio_tpu.ops.text import hash_token, hashing_vectorize

    assert hash_token("hello", 1024) == hash_token("hello", 1024)
    a = hashing_vectorize(["the cat sat"], 256)
    b = hashing_vectorize(["the cat sat"], 256)
    assert (a == b).all() and a.sum() == 3


def test_missing_text_events_raise(mem_storage):
    mem_storage.apps.insert(App(0, "emptytxt"))
    engine = TextClassificationEngine.apply()
    ep = EngineParams(
        data_source_params=TextDSParams(app_name="emptytxt"),
        algorithm_params_list=[("nb", TextNBParams())],
    )
    with pytest.raises(ValueError, match="no 'train' events"):
        engine.train(ep)


def test_text_trains_through_native_scan(tmp_path):
    """Text features ride the C++ property columns on segment backends."""
    from predictionio_tpu.native import native_available

    if not native_available():
        pytest.skip("native scanner unavailable")
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.events.event import DataMap, Event
    from predictionio_tpu.storage import App
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage

    storage = Storage(StorageConfig(
        sources={"S": {"type": "localfs", "path": str(tmp_path / "store")}},
        repositories={r: "S" for r in ("METADATA", "EVENTDATA", "MODELDATA")},
    ))
    app_id = storage.apps.insert(App(0, "textnat"))
    evs = []
    for k in range(40):
        spam = k % 2 == 0
        evs.append(Event(
            event="documents", entity_type="content", entity_id=f"d{k}",
            properties=DataMap({
                "text": ("win cash prize now" if spam else "see you at lunch")
                + f" {k}",
                "label": "spam" if spam else "ham"})))
    storage.l_events.insert_batch(evs, app_id)
    set_storage(storage)
    try:
        # the native columnar path must actually be available — otherwise
        # this test would silently cover only the row-object fallback
        from predictionio_tpu.store.event_store import PEventStore

        nb = PEventStore.native_batch("textnat", event_names=["documents"])
        assert nb is not None and nb.prop_columns is not None
        assert {"text", "label"} <= set(nb.prop_columns)
        from predictionio_tpu.models.text import TextClassificationEngine
        from predictionio_tpu.models.text.engine import TextDSParams, TextNBParams

        engine = TextClassificationEngine.apply()
        ep = EngineParams(
            data_source_params=TextDSParams(app_name="textnat",
                                            event_name="documents"),
            algorithm_params_list=[("nb", TextNBParams())],
        )
        models = engine.train(ep)
        predict = engine.predictor(ep, models)
        from predictionio_tpu.models.text.engine import TextQuery

        res = predict(TextQuery(text="free cash prize"))
        assert res.label == "spam"
    finally:
        set_storage(None)
