"""CCO op tests: cooccurrence counts, LLR correctness vs a naive reference,
tile streaming, and mesh parity."""

import numpy as np
import pytest

from predictionio_tpu.ops.cco import (
    block_interactions,
    cco_indicators,
    interaction_counts,
    llr_score,
)
from predictionio_tpu.parallel.mesh import MeshSpec, create_mesh


def naive_llr(k11, k12, k21, k22):
    def xlogx(x):
        return x * np.log(x) if x > 0 else 0.0

    def ent(*ks):
        return xlogx(sum(ks)) - sum(xlogx(k) for k in ks)

    return max(2.0 * (ent(k11 + k12, k21 + k22) + 0 - 0 + ent(k11 + k21, k12 + k22) - ent(k11, k12, k21, k22)), 0.0)


def naive_cco(pu, pi, ou, oi, n_users, n_ip, n_it):
    P = np.zeros((n_users, n_ip))
    A = np.zeros((n_users, n_it))
    P[pu, pi] = 1
    A[ou, oi] = 1
    C = P.T @ A
    row = P.sum(0)
    col = A.sum(0)
    llr = np.zeros_like(C)
    for i in range(n_ip):
        for j in range(n_it):
            k11 = C[i, j]
            k12 = row[i] - k11
            k21 = col[j] - k11
            k22 = n_users - k11 - k12 - k21
            llr[i, j] = naive_llr(k11, k12, k21, k22) if k11 > 0 else -np.inf
    return C, llr


def random_interactions(n_users, n_items, n_events, seed):
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, n_events).astype(np.int32)
    i = rng.integers(0, n_items, n_events).astype(np.int32)
    return u, i


def test_llr_matches_naive_formula():
    import jax.numpy as jnp

    cases = [(10, 5, 3, 100), (1, 0, 0, 50), (7, 7, 7, 7), (0, 3, 4, 10)]
    for k in cases:
        got = float(llr_score(*map(jnp.float32, k)))
        want = naive_llr(*k)
        assert abs(got - want) < 1e-3, (k, got, want)


@pytest.mark.parametrize("user_block,item_tile", [(64, 64), (16, 8), (1024, 4096)])
def test_cco_matches_naive(user_block, item_tile):
    n_users, n_ip, n_it = 50, 20, 15
    pu, pi = random_interactions(n_users, n_ip, 300, 1)
    ou, oi = random_interactions(n_users, n_it, 400, 2)
    # dedup for the naive side
    C, llr = naive_cco(pu, pi, ou, oi, n_users, n_ip, n_it)

    p = block_interactions(pu, pi, n_users, n_ip, user_block=user_block, dedup=True)
    o = block_interactions(ou, oi, n_users, n_it, user_block=user_block, dedup=True)
    # distinct-user counts from dedup'd blocked data
    rc = np.zeros(n_ip, np.float32)
    np.add.at(rc, p.item[p.mask > 0], 1)
    cc = np.zeros(n_it, np.float32)
    np.add.at(cc, o.item[o.mask > 0], 1)
    assert np.allclose(rc, C.sum(1) * 0 + (np.zeros((n_users, n_ip)) + _dense(pu, pi, n_users, n_ip)).sum(0))

    scores, idx = cco_indicators(p, o, rc, cc, n_users, top_k=n_it, item_tile=item_tile)
    for i in range(n_ip):
        got = {int(j): float(s) for s, j in zip(scores[i], idx[i]) if j >= 0}
        want = {j: llr[i, j] for j in range(n_it) if np.isfinite(llr[i, j]) and llr[i, j] >= 0}
        assert set(got) == set(want), (i, got, want)
        for j, s in got.items():
            assert abs(s - want[j]) < 1e-2, (i, j, s, want[j])


def _dense(u, i, n_users, n_items):
    M = np.zeros((n_users, n_items))
    M[u, i] = 1
    return M


def test_cco_top_k_and_threshold():
    n_users, n_ip, n_it = 40, 10, 12
    pu, pi = random_interactions(n_users, n_ip, 200, 3)
    ou, oi = random_interactions(n_users, n_it, 250, 4)
    p = block_interactions(pu, pi, n_users, n_ip)
    o = block_interactions(ou, oi, n_users, n_it)
    rc = _dense(pu, pi, n_users, n_ip).sum(0).astype(np.float32)
    cc = _dense(ou, oi, n_users, n_it).sum(0).astype(np.float32)
    scores, idx = cco_indicators(p, o, rc, cc, n_users, top_k=3)
    assert scores.shape == (n_ip, 3)
    # scores sorted descending per row
    finite = np.where(np.isfinite(scores), scores, -1e30)
    assert (np.diff(finite, axis=1) <= 1e-6).all()
    # high threshold kills everything
    s2, i2 = cco_indicators(p, o, rc, cc, n_users, top_k=3, llr_threshold=1e9)
    assert (i2 == -1).all()


def test_cco_exclude_self():
    n_users, n_items = 30, 8
    u, i = random_interactions(n_users, n_items, 150, 5)
    b = block_interactions(u, i, n_users, n_items, dedup=True)
    counts = _dense(u, i, n_users, n_items).sum(0).astype(np.float32)
    scores, idx = cco_indicators(b, b, counts, counts, n_users, top_k=4, exclude_self=True)
    for row in range(n_items):
        assert row not in idx[row][idx[row] >= 0]


def test_cco_mesh_matches_single():
    n_users, n_ip, n_it = 64, 12, 10
    pu, pi = random_interactions(n_users, n_ip, 300, 6)
    ou, oi = random_interactions(n_users, n_it, 300, 7)
    p = block_interactions(pu, pi, n_users, n_ip, user_block=8)
    o = block_interactions(ou, oi, n_users, n_it, user_block=8)
    rc = _dense(pu, pi, n_users, n_ip).sum(0).astype(np.float32)
    cc = _dense(ou, oi, n_users, n_it).sum(0).astype(np.float32)
    s1, i1 = cco_indicators(p, o, rc, cc, n_users, top_k=5)
    mesh = create_mesh(MeshSpec(dp=8, mp=1))
    s8, i8 = cco_indicators(p, o, rc, cc, n_users, top_k=5, mesh=mesh)
    assert np.allclose(np.where(np.isfinite(s1), s1, -1), np.where(np.isfinite(s8), s8, -1), atol=1e-3)
    assert (i1 == i8).all()


def test_dense_matches_tiled(monkeypatch):
    """The dense user-chunked path and the tiled fallback agree exactly."""
    n_users, n_ip, n_it = 60, 12, 17
    pu, pi = random_interactions(n_users, n_ip, 300, 11)
    ou, oi = random_interactions(n_users, n_it, 500, 12)
    p = block_interactions(pu, pi, n_users, n_ip, user_block=16, dedup=True)
    o = block_interactions(ou, oi, n_users, n_it, user_block=16, dedup=True)
    rc = interaction_counts(p.item[p.mask > 0], n_ip)
    cc = interaction_counts(o.item[o.mask > 0], n_it)

    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    sd, idd = cco_indicators(p, o, rc, cc, n_users, top_k=6, item_tile=8)
    monkeypatch.setenv("PIO_CCO_DENSE", "0")
    st, idt = cco_indicators(p, o, rc, cc, n_users, top_k=6, item_tile=8)
    np.testing.assert_allclose(sd, st, rtol=1e-5)
    # indices may tie-break differently only where scores tie; require
    # identical index sets per row for non-padding entries
    for r in range(n_ip):
        assert set(idd[r][sd[r] > -np.inf]) == set(idt[r][st[r] > -np.inf])


def test_dense_mesh_matches_single(monkeypatch):
    import jax

    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    n_users, n_ip, n_it = 64, 10, 10
    pu, pi = random_interactions(n_users, n_ip, 240, 21)
    ou, oi = random_interactions(n_users, n_it, 400, 22)
    p = block_interactions(pu, pi, n_users, n_ip, user_block=8)
    o = block_interactions(ou, oi, n_users, n_it, user_block=8)
    rc = interaction_counts(p.item[p.mask > 0], n_ip)
    cc = interaction_counts(o.item[o.mask > 0], n_it)
    s1, i1 = cco_indicators(p, o, rc, cc, n_users, top_k=5)
    mesh = create_mesh(MeshSpec(dp=8, mp=1))
    s8, i8 = cco_indicators(p, o, rc, cc, n_users, top_k=5, mesh=mesh)
    np.testing.assert_allclose(s1, s8, rtol=1e-5, atol=1e-5)


def test_dense_exclude_self_and_topk_overflow(monkeypatch):
    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    n_users, n_items = 40, 6
    u, i = random_interactions(n_users, n_items, 200, 31)
    b = block_interactions(u, i, n_users, n_items, dedup=True)
    counts = interaction_counts(b.item[b.mask > 0], n_items)
    # top_k wider than the (padded) item space still returns [I, top_k]
    scores, idx = cco_indicators(b, b, counts, counts, n_users,
                                 top_k=300, exclude_self=True)
    assert scores.shape == (n_items, 300) and idx.shape == (n_items, 300)
    for r in range(n_items):
        assert r not in set(idx[r][idx[r] >= 0])


def test_dense_matches_tiled_exclude_self(monkeypatch):
    """Both strategies mask self-pairs BEFORE top-k: full top_k correlators
    per row and identical scores either way."""
    n_users, n_items = 60, 14
    u, i = random_interactions(n_users, n_items, 400, 41)
    b = block_interactions(u, i, n_users, n_items, user_block=16, dedup=True)
    counts = interaction_counts(b.item[b.mask > 0], n_items)

    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    sd, idd = cco_indicators(b, b, counts, counts, n_users, top_k=5,
                             item_tile=8, exclude_self=True)
    monkeypatch.setenv("PIO_CCO_DENSE", "0")
    st, idt = cco_indicators(b, b, counts, counts, n_users, top_k=5,
                             item_tile=8, exclude_self=True)
    np.testing.assert_allclose(sd, st, rtol=1e-5)
    for r in range(n_items):
        assert r not in set(idd[r][idd[r] >= 0])
        assert r not in set(idt[r][idt[r] >= 0])
        assert set(idd[r][sd[r] > -np.inf]) == set(idt[r][st[r] > -np.inf])


def test_duplicates_collapse_without_host_dedup(monkeypatch):
    """Raw pairs with heavy duplication give the same indicators as
    pre-dedup'd pairs on BOTH device strategies — the scatter-max densify
    is the dedup, and marginals derive from it on device."""
    from predictionio_tpu.ops.cco import cco_indicators_coo, dedup_pairs

    n_users, n_ip, n_it = 40, 9, 11
    pu, pi = random_interactions(n_users, n_ip, 500, 51)  # ~500 raw, many dups
    ou, oi = random_interactions(n_users, n_it, 700, 52)
    pu_d, pi_d = dedup_pairs(pu, pi, n_ip)
    ou_d, oi_d = dedup_pairs(ou, oi, n_it)
    for dense in ("1", "0"):
        monkeypatch.setenv("PIO_CCO_DENSE", dense)
        s_raw, i_raw = cco_indicators_coo(
            pu, pi, ou, oi, n_users, n_ip, n_it, top_k=4, item_tile=8)
        s_ded, i_ded = cco_indicators_coo(
            pu_d, pi_d, ou_d, oi_d, n_users, n_ip, n_it, top_k=4, item_tile=8)
        np.testing.assert_allclose(s_raw, s_ded, rtol=1e-5)
        for r in range(n_ip):
            assert set(i_raw[r][s_raw[r] > -np.inf]) == set(i_ded[r][s_ded[r] > -np.inf])


def test_cco_train_indicators_matches_per_call(monkeypatch):
    """The staged multi-event-type entry returns exactly what independent
    cco_indicators_coo calls return (self + cross)."""
    from predictionio_tpu.ops.cco import cco_indicators_coo, cco_train_indicators

    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    n_users, n_ip, n_view = 50, 12, 18
    pu, pi = random_interactions(n_users, n_ip, 300, 61)
    vu, vi = random_interactions(n_users, n_view, 600, 62)
    out = cco_train_indicators(
        pu, pi,
        [("buy", pu, pi, n_ip), ("view", vu, vi, n_view)],
        n_users, n_ip, top_k=5, exclude_self_for="buy")
    s_self, i_self = cco_indicators_coo(
        pu, pi, pu, pi, n_users, n_ip, n_ip, top_k=5, exclude_self=True)
    s_cross, i_cross = cco_indicators_coo(
        pu, pi, vu, vi, n_users, n_ip, n_view, top_k=5)
    np.testing.assert_allclose(out["buy"][0], s_self, rtol=1e-5)
    np.testing.assert_allclose(out["view"][0], s_cross, rtol=1e-5)
    for r in range(n_ip):
        assert r not in set(out["buy"][1][r][out["buy"][1][r] >= 0])
        assert set(out["view"][1][r][out["view"][0][r] > -np.inf]) == set(
            i_cross[r][s_cross[r] > -np.inf])


def test_cco_train_indicators_tiled_fallback(monkeypatch):
    """Event types too big for the dense budget route through the tiled
    path inside the same call, with identical semantics."""
    from predictionio_tpu.ops.cco import cco_train_indicators

    n_users, n_ip, n_view = 30, 8, 10
    pu, pi = random_interactions(n_users, n_ip, 200, 71)
    vu, vi = random_interactions(n_users, n_view, 300, 72)
    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    dense = cco_train_indicators(
        pu, pi, [("buy", pu, pi, n_ip), ("view", vu, vi, n_view)],
        n_users, n_ip, top_k=4, exclude_self_for="buy")
    monkeypatch.setenv("PIO_CCO_DENSE", "0")
    tiled = cco_train_indicators(
        pu, pi, [("buy", pu, pi, n_ip), ("view", vu, vi, n_view)],
        n_users, n_ip, top_k=4, exclude_self_for="buy", item_tile=8, user_block=8)
    for name in ("buy", "view"):
        np.testing.assert_allclose(dense[name][0], tiled[name][0], rtol=1e-4)


def test_cco_train_indicators_mesh(monkeypatch):
    from predictionio_tpu.ops.cco import cco_train_indicators

    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    n_users, n_ip, n_view = 64, 10, 12
    pu, pi = random_interactions(n_users, n_ip, 250, 81)
    vu, vi = random_interactions(n_users, n_view, 400, 82)
    single = cco_train_indicators(
        pu, pi, [("buy", pu, pi, n_ip), ("view", vu, vi, n_view)],
        n_users, n_ip, top_k=5, exclude_self_for="buy")
    mesh = create_mesh(MeshSpec(dp=8, mp=1))
    sharded = cco_train_indicators(
        pu, pi, [("buy", pu, pi, n_ip), ("view", vu, vi, n_view)],
        n_users, n_ip, top_k=5, exclude_self_for="buy", mesh=mesh)
    for name in ("buy", "view"):
        np.testing.assert_allclose(single[name][0], sharded[name][0],
                                   rtol=1e-5, atol=1e-5)


def test_block_interactions_stream_matches_batch():
    """The streaming host-staging layout yields identical indicators to the
    one-shot layout (same data, batched arbitrarily)."""
    from predictionio_tpu.ops.cco import (
        block_interactions, block_interactions_stream, cco_indicators)

    n_users, n_items = 48, 12
    u, i = random_interactions(n_users, n_items, 400, 91)
    whole = block_interactions(u, i, n_users, n_items, user_block=16)
    streamed = block_interactions_stream(
        ((u[s:s + 37], i[s:s + 37]) for s in range(0, 400, 37)),
        n_users, n_items, user_block=16)
    s1, i1 = cco_indicators(whole, whole, None, None, n_users, top_k=5,
                            item_tile=8, exclude_self=True)
    s2, i2 = cco_indicators(streamed, streamed, None, None, n_users, top_k=5,
                            item_tile=8, exclude_self=True)
    np.testing.assert_allclose(s1, s2, rtol=1e-5)
    for r in range(n_items):
        assert set(i1[r][s1[r] > -np.inf]) == set(i2[r][s2[r] > -np.inf])


def test_resident_tiled_matches_chunked_tiled(monkeypatch):
    """The P-resident tiled strategy (primary densified once, reused per
    tile) returns the same scores as the chunked tiled path and the dense
    path."""
    from predictionio_tpu.ops import cco as cco_mod
    from predictionio_tpu.ops.cco import cco_indicators_coo

    n_users, n_ip, n_it = 70, 14, 19
    pu, pi = random_interactions(n_users, n_ip, 400, 101)
    ou, oi = random_interactions(n_users, n_it, 600, 102)

    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    sd, _ = cco_indicators_coo(pu, pi, ou, oi, n_users, n_ip, n_it,
                               top_k=5, item_tile=8)
    monkeypatch.setenv("PIO_CCO_DENSE", "0")
    # resident path active (P easily fits)
    assert cco_mod._resident_p_ok(n_users, n_ip)
    sr, _ = cco_indicators_coo(pu, pi, ou, oi, n_users, n_ip, n_it,
                               top_k=5, item_tile=8, user_block=16)
    # force the chunked tiled path by shrinking the resident budget
    monkeypatch.setattr(cco_mod, "_TILED_P_BYTES", 1)
    st, _ = cco_indicators_coo(pu, pi, ou, oi, n_users, n_ip, n_it,
                               top_k=5, item_tile=8, user_block=16)
    np.testing.assert_allclose(sd, sr, rtol=1e-4)
    np.testing.assert_allclose(sr, st, rtol=1e-4)


def test_resident_tiled_self_pair(monkeypatch):
    from predictionio_tpu.ops import cco as cco_mod
    from predictionio_tpu.ops.cco import cco_indicators_coo

    n_users, n_items = 50, 12
    u, i = random_interactions(n_users, n_items, 300, 111)
    monkeypatch.setenv("PIO_CCO_DENSE", "0")
    s1, i1 = cco_indicators_coo(u, i, u, i, n_users, n_items, n_items,
                                top_k=4, item_tile=8, exclude_self=True)
    monkeypatch.setattr(cco_mod, "_TILED_P_BYTES", 1)
    s2, i2 = cco_indicators_coo(u, i, u, i, n_users, n_items, n_items,
                                top_k=4, item_tile=8, exclude_self=True)
    np.testing.assert_allclose(s1, s2, rtol=1e-4)
    for r in range(n_items):
        assert r not in set(i1[r][i1[r] >= 0])

def test_sparse_host_matches_dense_and_tiled(monkeypatch):
    """The host sparse-count strategy (CPU-backend cross-join + bincount)
    is bit-identical to the device dense path — same integer counts, same
    device LLR/top-k tail — and set-identical to tiled under ties."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_ip, n_it = 70, 13, 19
    pu, pi = random_interactions(n_users, n_ip, 350, 51)
    ou, oi = random_interactions(n_users, n_it, 600, 52)

    def run():
        return cco_ops.cco_indicators_coo(
            pu, pi, ou, oi, n_users, n_ip, n_it,
            top_k=6, llr_threshold=0.3, item_tile=8)

    monkeypatch.setenv("PIO_CCO_SPARSE", "1")
    ss, si = run()
    monkeypatch.setenv("PIO_CCO_SPARSE", "0")
    monkeypatch.setenv("PIO_CCO_DENSE", "1")
    ds, di = run()
    monkeypatch.setenv("PIO_CCO_DENSE", "0")
    ts, ti = run()
    np.testing.assert_array_equal(ss, ds)      # same counts, same tail: exact
    np.testing.assert_array_equal(si, di)
    np.testing.assert_allclose(ss, ts, rtol=1e-5)
    for r in range(n_ip):
        assert set(si[r][ss[r] > -np.inf]) == set(ti[r][ts[r] > -np.inf])

    # over-budget expansion bails to the device path with identical output
    monkeypatch.setenv("PIO_CCO_SPARSE", "1")
    monkeypatch.delenv("PIO_CCO_DENSE", raising=False)
    monkeypatch.setattr(cco_ops, "_SPARSE_PAIR_BUDGET", 0)
    bs, bi_ = run()
    np.testing.assert_array_equal(bs, ds)
    np.testing.assert_array_equal(bi_, di)


def test_sparse_host_self_pair_and_train_indicators(monkeypatch):
    """cco_train_indicators on the sparse path: self-pair reuses the
    primary CSR, exclude_self masks the diagonal, multi-type results match
    the device dense runner exactly."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_items = 50, 11
    pu, pi = random_interactions(n_users, n_items, 260, 61)
    vu, vi = random_interactions(n_users, n_items, 500, 62)
    others = [("buy", pu, pi, n_items), ("view", vu, vi, n_items)]

    monkeypatch.setenv("PIO_CCO_SPARSE", "1")
    r_sparse = cco_ops.cco_train_indicators(
        pu, pi, others, n_users, n_items, top_k=4, exclude_self_for="buy")
    monkeypatch.setenv("PIO_CCO_SPARSE", "0")
    r_dense = cco_ops.cco_train_indicators(
        pu, pi, others, n_users, n_items, top_k=4, exclude_self_for="buy")
    for name in ("buy", "view"):
        np.testing.assert_array_equal(r_sparse[name][0], r_dense[name][0])
        np.testing.assert_array_equal(r_sparse[name][1], r_dense[name][1])
    for r in range(n_items):
        idx = r_sparse["buy"][1][r]
        assert r not in set(idx[idx >= 0])


def test_sparse_host_tail_matches_device_tail(monkeypatch):
    """The sparse host LLR/top-k tail (scores only nonzero cells, lexsort
    top-k) must be bit-identical to the dense device tail at both forced
    settings, including the COO fast path and exclude_self."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_items = 300, 64
    u, i = random_interactions(n_users, n_items, 900, 71)
    monkeypatch.setenv("PIO_CCO_SPARSE", "1")

    def run():
        r = cco_ops._SparseHostRunner(u, i, n_users, n_items)
        d = r.dispatch(u, i, n_items, 5, 1.0, True, self_pair=True)
        return r.collect(d)

    monkeypatch.setenv("PIO_CCO_SPARSE_TAIL", "device")
    ds, di = run()
    monkeypatch.setenv("PIO_CCO_SPARSE_TAIL", "host")
    hs, hi = run()
    np.testing.assert_array_equal(hs, ds)
    np.testing.assert_array_equal(hi, di)
    # auto at this tiny shape picks SOME tail; result must match either way
    monkeypatch.setenv("PIO_CCO_SPARSE_TAIL", "auto")
    as_, ai_ = run()
    np.testing.assert_array_equal(as_, ds)
    np.testing.assert_array_equal(ai_, di)
    # rows with fewer than top_k surviving cells pad with -inf / -1
    assert ((hi == -1) == (hs == -np.inf)).all()


def test_sparse_counts_coo_touched_path():
    """want_coo on a matrix ABOVE the bincount-branch gate must collect
    the touched cells from the unique-branch chunks — and they must equal
    a direct flatnonzero scan of the dense result."""
    from predictionio_tpu.ops import cco as cco_ops

    # 4200 x 4100 = 17.2M cells > _SPARSE_BINCOUNT_CELLS (16.8M)
    n_users, n_ip, n_it = 500, 4200, 4100
    assert n_ip * n_it > cco_ops._SPARSE_BINCOUNT_CELLS
    pu, pi = random_interactions(n_users, n_ip, 3000, 81)
    au, ai = random_interactions(n_users, n_it, 4000, 82)
    p = cco_ops._SparseHostCSR(pu, pi, n_ip, n_users)
    a = cco_ops._SparseHostCSR(au, ai, n_it, n_users)
    C, flat = cco_ops._sparse_counts(p, a, want_coo=True)
    np.testing.assert_array_equal(flat, np.flatnonzero(C))
    assert len(flat) > 0
    # and the host tail built from that COO matches the device tail
    s_host, i_host = cco_ops._llr_topk_sparse_host(
        C, p.col_counts, a.col_counts, float(n_users), 0.0, 6, False,
        flat=flat)
    import jax.numpy as jnp
    from predictionio_tpu.ops.pallas_kernels import pallas_mode
    s_dev, i_dev = cco_ops._llr_topk_dense(
        jnp.asarray(C), jnp.asarray(p.col_counts), jnp.asarray(a.col_counts),
        float(n_users), 0.0, top_k=6, exclude_self=False,
        pallas=pallas_mode(), topk="lax")
    s_dev, i_dev = cco_ops._finalize_topk(s_dev, i_dev, n_it)
    np.testing.assert_array_equal(s_host, s_dev)
    np.testing.assert_array_equal(i_host, i_dev)


def test_sparse_counts_coo_bincount_downgrade():
    """A bincount-branch chunk loses cell identities, so want_coo must
    fall back to the flatnonzero scan — exercised with a small matrix
    and a dense chunk (chunk * 8 >= cells), where the bincount branch
    actually fires."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_items = 40, 50         # 2500 cells << bincount gate
    pu, pi = random_interactions(n_users, n_items, 700, 91)
    p = cco_ops._SparseHostCSR(pu, pi, n_items, n_users)
    total = cco_ops._cross_join_pairs(p, p)
    assert total * 8 >= n_items * n_items, "need a dense chunk for the test"
    C, flat = cco_ops._sparse_counts(p, p, want_coo=True)
    np.testing.assert_array_equal(flat, np.flatnonzero(C))
    assert len(flat) > 0


def test_pure_coo_counts_match_dense():
    """_sparse_counts_coo (no dense matrix anywhere) must reproduce the
    dense host counts cell for cell, across the chunked merge."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_ip, n_it = 400, 300, 250
    pu, pi = random_interactions(n_users, n_ip, 5000, 101)
    au, ai = random_interactions(n_users, n_it, 6000, 102)
    p = cco_ops._SparseHostCSR(pu, pi, n_ip, n_users)
    a = cco_ops._SparseHostCSR(au, ai, n_it, n_users)
    cells, counts = cco_ops._sparse_counts_coo(p, a)
    C_ref = cco_ops._sparse_counts(p, a)
    C = np.zeros((n_ip, n_it), np.int32)
    C[cells // n_it, cells % n_it] = counts
    np.testing.assert_array_equal(C, C_ref)
    assert np.all(np.diff(cells) > 0)


def test_pure_coo_counts_chunked_merge():
    """The end-of-scan merge across expansion chunks (argsort +
    segment-sum) must aggregate duplicate cells exactly — forced by
    shrinking the chunk budget so every user lands in its own chunk."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_items = 200, 60
    pu, pi = random_interactions(n_users, n_items, 3000, 103)
    p = cco_ops._SparseHostCSR(pu, pi, n_items, n_users)
    saved = cco_ops._SPARSE_CHUNK_PAIRS
    try:
        cco_ops._SPARSE_CHUNK_PAIRS = 16   # many tiny chunks
        cells, counts = cco_ops._sparse_counts_coo(p, p)
    finally:
        cco_ops._SPARSE_CHUNK_PAIRS = saved
    C_ref = cco_ops._sparse_counts(p, p)
    C = np.zeros((n_items, n_items), np.int32)
    C[cells // n_items, cells % n_items] = counts
    np.testing.assert_array_equal(C, C_ref)


def test_huge_catalog_coo_dispatch_matches_dense(monkeypatch):
    """When the dense host count matrix is over budget the runner must
    take the pure-COO dispatch (counts + row-scoped sparse tail, no
    [I_p, I_t] array anywhere) and return bit-identical results —
    forced by shrinking _SPARSE_C_BYTES under the same shape."""
    from predictionio_tpu.ops import cco as cco_ops

    n_users, n_items = 300, 120
    u, i = random_interactions(n_users, n_items, 2500, 104)
    monkeypatch.setenv("PIO_CCO_SPARSE", "1")
    monkeypatch.setenv("PIO_CCO_SPARSE_TAIL", "host")

    def run():
        r = cco_ops._SparseHostRunner(u, i, n_users, n_items)
        d = r.dispatch(u, i, n_items, 6, 0.5, True, self_pair=True)
        assert d is not None
        return r.collect(d)

    s_ref, i_ref = run()
    saved = cco_ops._SPARSE_C_BYTES
    try:
        cco_ops._SPARSE_C_BYTES = 1024    # dense C "cannot exist"
        s_coo, i_coo = run()
    finally:
        cco_ops._SPARSE_C_BYTES = saved
    np.testing.assert_array_equal(s_ref, s_coo)
    np.testing.assert_array_equal(i_ref, i_coo)


def test_llr_topk_sparse_rows_matches_host_tail_slices():
    """The fold engine's row-scoped sparse tail must equal the TRAINING
    host tail's rows at an arbitrary row subset — same ``_llr_cells``
    compiled program, so bit-identity is structural — including
    self-pair masking at the subset's GLOBAL row ids.  (The host tail's
    own parity with the device tail is pinned separately on real count
    data; two DIFFERENT XLA compilations of the same elementwise chain
    can disagree by 1 ULP on adversarial inputs, so this test compares
    within the one program the fold actually shares with training.)"""
    from predictionio_tpu.ops import cco as cco_ops

    rng = np.random.default_rng(105)
    n_p, n_t, n_users = 90, 70, 500
    C = (rng.random((n_p, n_t)) < 0.1).astype(np.int32) * \
        rng.integers(1, 9, (n_p, n_t)).astype(np.int32)
    rc = C.sum(axis=1).astype(np.int64) + rng.integers(0, 5, n_p)
    cc = C.sum(axis=0).astype(np.int64) + rng.integers(0, 5, n_t)
    # full-matrix host tail with the diagonal masked, as training runs it
    s_host, i_host = cco_ops._llr_topk_sparse_host(
        C, rc, cc, float(n_users), 0.25, top_k=5, exclude_self=True)
    rows = np.asarray(sorted(rng.choice(n_p, 17, replace=False)), np.int64)
    sub = C[rows]
    lr, lc = np.nonzero(sub)
    s_sp, i_sp = cco_ops._llr_topk_sparse_rows(
        lr, lc, sub[lr, lc], rc[rows], cc, float(n_users), 0.25,
        top_k=5, n_rows=len(rows), n_cols=n_t, self_cols=rows)
    np.testing.assert_array_equal(s_sp, s_host[rows])
    np.testing.assert_array_equal(i_sp, i_host[rows])
