"""Complementary Purchase template tests: basket sessionization, rule
mining (support/confidence/lift), cart-aggregated serving."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import Event
from predictionio_tpu.models.complementary_purchase import (
    ComplementaryPurchaseEngine,
    CPQuery,
)
from predictionio_tpu.models.complementary_purchase.engine import (
    CPAlgorithmParams,
    CPDataSourceParams,
)
from predictionio_tpu.ops.cco import basket_rules
from predictionio_tpu.storage import App

APP = "cpapp"
T0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)


@pytest.fixture()
def cp_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, APP))
    rng = np.random.default_rng(6)
    events = []
    # coffee+filter bought together; tea+kettle together; bread alone.
    # One basket per (user, day): events inside a basket are seconds apart,
    # different days are far beyond the 1-hour window.
    for u in range(60):
        for day in range(3):
            base = T0 + dt.timedelta(days=day, hours=u % 12)
            basket = (["coffee", "filter"] if (u + day) % 2 == 0
                      else ["tea", "kettle"])
            if rng.random() < 0.3:
                basket = basket + ["bread"]
            for k, item in enumerate(basket):
                events.append(Event(
                    event="buy", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=item,
                    event_time=base + dt.timedelta(seconds=k)))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage, app_id


def make_ep(**algo):
    return EngineParams(
        data_source_params=CPDataSourceParams(app_name=APP),
        algorithm_params_list=[("rules", CPAlgorithmParams(**algo))],
    )


def test_basket_sessionization(cp_app):
    engine = ComplementaryPurchaseEngine.apply()
    ds = engine.make_components(make_ep())[0]
    td = ds.read_training()
    # 60 users x 3 days = 180 baskets
    assert td.n_baskets == 180
    # every basket holds 2 or 3 items
    sizes = np.bincount(td.basket_idx)
    assert set(sizes.tolist()) <= {2, 3}


def test_complements_found_and_ranked(cp_app):
    engine = ComplementaryPurchaseEngine.apply()
    ep = make_ep(min_support=0.01, min_confidence=0.2)
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(CPQuery(items=["coffee"], num=2))
    items = [s.item for s in res.item_scores]
    assert items and items[0] == "filter", items
    assert "coffee" not in items
    res = predict(CPQuery(items=["tea"], num=2))
    assert [s.item for s in res.item_scores][0] == "kettle"
    # cart aggregation: two antecedents still exclude the cart itself
    res = predict(CPQuery(items=["coffee", "tea"], num=4))
    items = [s.item for s in res.item_scores]
    assert not {"coffee", "tea"} & set(items)
    assert {"filter", "kettle"} <= set(items)


def test_min_confidence_prunes_weak_rules(cp_app):
    engine = ComplementaryPurchaseEngine.apply()
    # bread co-occurs randomly (30%) with everything: a high confidence
    # cut keeps the deterministic pairs and drops bread rules
    ep = make_ep(min_support=0.01, min_confidence=0.9)
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(CPQuery(items=["bread"], num=5))
    assert res.item_scores == []
    res = predict(CPQuery(items=["coffee"], num=5))
    assert [s.item for s in res.item_scores] == ["filter"]


def test_basket_rules_op_exact_metrics():
    # 5 baskets: {0,1} x4, {2} x1 -> conf(0->1)=1, lift=1/(4/5)=1.25
    b = np.array([0, 0, 1, 1, 2, 2, 3, 3, 4], np.int32)
    i = np.array([0, 1, 0, 1, 0, 1, 0, 1, 2], np.int32)
    lift, idx, conf = basket_rules(b, i, 5, 3, top_k=2)
    assert idx[0][0] == 1 and conf[0][0] == 1.0
    assert abs(lift[0][0] - 1.25) < 1e-6
    assert idx[2][0] == -1
    # duplicate items in one basket do not inflate counts (scatter-max)
    b2 = np.concatenate([b, [0, 0]]).astype(np.int32)
    i2 = np.concatenate([i, [0, 1]]).astype(np.int32)
    lift2, idx2, conf2 = basket_rules(b2, i2, 5, 3, top_k=2)
    assert np.allclose(lift[np.isfinite(lift)], lift2[np.isfinite(lift2)])


def test_model_roundtrip(cp_app):
    import pickle

    engine = ComplementaryPurchaseEngine.apply()
    ep = make_ep(min_support=0.01, min_confidence=0.2)
    models = engine.train(ep)
    restored = [pickle.loads(pickle.dumps(m)) for m in models]
    q = CPQuery(items=["coffee"], num=3)
    assert (engine.predictor(ep, models)(q).to_json()
            == engine.predictor(ep, restored)(q).to_json())


def test_basket_rules_chunked_exact(monkeypatch):
    """Counts stay exact when baskets span many scan chunks."""
    from predictionio_tpu.ops import cco

    monkeypatch.setattr(cco, "_BASKET_CHUNK", 4)
    rng = np.random.default_rng(1)
    n_baskets, n_items = 50, 8
    b = rng.integers(0, n_baskets, 400).astype(np.int32)
    i = rng.integers(0, n_items, 400).astype(np.int32)
    lift, idx, conf = basket_rules(b, i, n_baskets, n_items, top_k=n_items)
    # dense numpy reference
    B = np.zeros((n_baskets, n_items))
    B[b, i] = 1.0
    C = B.T @ B
    ci = np.diag(C)
    for row in range(n_items):
        for k_, j in enumerate(idx[row]):
            if j < 0:
                continue
            conf_ref = C[row, j] / max(ci[row], 1)
            lift_ref = conf_ref / (ci[j] / n_baskets)
            assert abs(conf[row, k_] - conf_ref) < 1e-5
            assert abs(lift[row, k_] - lift_ref) < 1e-4


def _host_reference_rules(gb, gi, n_baskets, n_items, top_k,
                          min_support=0.0, min_confidence=0.0):
    """Exact numpy reference from sparse pairs (no dense matrix)."""
    pairs = sorted(set(zip(gb.tolist(), gi.tolist())))
    by_basket = {}
    ci = np.zeros(n_items, np.int64)
    for b, i in pairs:
        by_basket.setdefault(b, []).append(i)
        ci[i] += 1
    counts = {}
    for items in by_basket.values():
        for i in items:
            for j in items:
                if i != j:
                    counts[(i, j)] = counts.get((i, j), 0) + 1
    n = max(float(n_baskets), 1.0)
    rules = {}
    for (i, j), c in counts.items():
        support, conf = c / n, c / ci[i]
        lift = conf / (ci[j] / n)
        if support >= min_support and conf >= min_confidence:
            rules.setdefault(i, []).append((lift, j, conf))
    out = {}
    for i, rs in rules.items():
        rs.sort(key=lambda t: (-t[0], t[1]))
        out[i] = rs[:top_k]
    return out


def test_basket_rules_tiled_matches_dense(monkeypatch):
    """Forcing the tiled strategy at a dense-feasible size: identical
    lift/ids/confidence (modulo tie order) to the dense path."""
    from predictionio_tpu.ops import cco as cco_ops

    rng = np.random.default_rng(8)
    n_baskets, n_items = 300, 90
    gb = rng.integers(0, n_baskets, 2_000).astype(np.int32)
    gi = rng.integers(0, n_items, 2_000).astype(np.int32)
    dense = basket_rules(gb, gi, n_baskets, n_items, top_k=6,
                         min_support=0.004, min_confidence=0.1)
    monkeypatch.setattr(cco_ops, "_BASKET_RULES_DENSE_MAX_ITEMS", 8)
    tiled = basket_rules(gb, gi, n_baskets, n_items, top_k=6,
                         min_support=0.004, min_confidence=0.1,
                         item_tile=32)
    np.testing.assert_allclose(dense[0], tiled[0], rtol=1e-5)
    for r in range(n_items):
        fin = np.isfinite(dense[0][r])
        assert set(dense[1][r][fin]) == set(tiled[1][r][fin])
    np.testing.assert_allclose(np.sort(dense[2], axis=1),
                               np.sort(tiled[2], axis=1), rtol=1e-5)


def test_basket_rules_past_old_cap():
    """The 40k-item cliff is gone: a 41k-item catalog trains on the tiled
    strategy and matches an exact sparse host reference row for row."""
    rng = np.random.default_rng(9)
    n_baskets, n_items = 200, 41_000
    # clustered baskets so real rules exist among high ids too
    gb = np.repeat(np.arange(n_baskets, dtype=np.int32), 6)
    base = rng.integers(0, n_items - 8, n_baskets)
    gi = (base[:, None] + rng.integers(0, 8, (n_baskets, 6))).astype(np.int32).ravel()
    st, si, conf = basket_rules(gb, gi, n_baskets, n_items, top_k=5,
                                item_tile=8192)
    assert st.shape == (n_items, 5)
    ref = _host_reference_rules(gb, gi, n_baskets, n_items, top_k=5)
    checked = 0
    for i, rs in list(ref.items())[:300]:
        got_lift = st[i][np.isfinite(st[i])]
        want_lift = np.array([t[0] for t in rs], np.float64)
        np.testing.assert_allclose(
            got_lift, want_lift[: len(got_lift)], rtol=1e-4)
        want_conf = {j: c for (_, j, c) in rs}
        for lift_v, j, cv in zip(st[i], si[i], conf[i]):
            if j >= 0 and j in want_conf:
                np.testing.assert_allclose(cv, want_conf[j], rtol=1e-4)
                checked += 1
    assert checked > 100


def test_cp_serve_batch_matches_serial(cp_app):
    """serve_batch_predict ≡ predict across carts, multi-item carts, and
    unresolvable carts in one batch."""
    engine = ComplementaryPurchaseEngine.apply()
    ep = make_ep()
    models = engine.train(ep)
    model = models[0]
    name, params = ep.algorithm_params_list[0]
    algo = engine.algorithm_classes[name](params)
    queries = [
        CPQuery(items=["coffee"], num=3),
        CPQuery(items=["tea"], num=2),
        CPQuery(items=["coffee", "tea"], num=4),
        CPQuery(items=["nothing-known"], num=3),
        CPQuery(items=[], num=3),
    ]
    serial = [algo.predict(model, q) for q in queries]
    batched = algo.serve_batch_predict(model, queries)
    for q, s, b in zip(queries, serial, batched):
        s_i = [(r.item, round(r.score, 4)) for r in s.item_scores]
        b_i = [(r.item, round(r.score, 4)) for r in b.item_scores]
        assert s_i == b_i, (q, s_i, b_i)
