"""Complementary Purchase template tests: basket sessionization, rule
mining (support/confidence/lift), cart-aggregated serving."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import Event
from predictionio_tpu.models.complementary_purchase import (
    ComplementaryPurchaseEngine,
    CPQuery,
)
from predictionio_tpu.models.complementary_purchase.engine import (
    CPAlgorithmParams,
    CPDataSourceParams,
)
from predictionio_tpu.ops.cco import basket_rules
from predictionio_tpu.storage import App

APP = "cpapp"
T0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)


@pytest.fixture()
def cp_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, APP))
    rng = np.random.default_rng(6)
    events = []
    # coffee+filter bought together; tea+kettle together; bread alone.
    # One basket per (user, day): events inside a basket are seconds apart,
    # different days are far beyond the 1-hour window.
    for u in range(60):
        for day in range(3):
            base = T0 + dt.timedelta(days=day, hours=u % 12)
            basket = (["coffee", "filter"] if (u + day) % 2 == 0
                      else ["tea", "kettle"])
            if rng.random() < 0.3:
                basket = basket + ["bread"]
            for k, item in enumerate(basket):
                events.append(Event(
                    event="buy", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=item,
                    event_time=base + dt.timedelta(seconds=k)))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage, app_id


def make_ep(**algo):
    return EngineParams(
        data_source_params=CPDataSourceParams(app_name=APP),
        algorithm_params_list=[("rules", CPAlgorithmParams(**algo))],
    )


def test_basket_sessionization(cp_app):
    engine = ComplementaryPurchaseEngine.apply()
    ds = engine.make_components(make_ep())[0]
    td = ds.read_training()
    # 60 users x 3 days = 180 baskets
    assert td.n_baskets == 180
    # every basket holds 2 or 3 items
    sizes = np.bincount(td.basket_idx)
    assert set(sizes.tolist()) <= {2, 3}


def test_complements_found_and_ranked(cp_app):
    engine = ComplementaryPurchaseEngine.apply()
    ep = make_ep(min_support=0.01, min_confidence=0.2)
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(CPQuery(items=["coffee"], num=2))
    items = [s.item for s in res.item_scores]
    assert items and items[0] == "filter", items
    assert "coffee" not in items
    res = predict(CPQuery(items=["tea"], num=2))
    assert [s.item for s in res.item_scores][0] == "kettle"
    # cart aggregation: two antecedents still exclude the cart itself
    res = predict(CPQuery(items=["coffee", "tea"], num=4))
    items = [s.item for s in res.item_scores]
    assert not {"coffee", "tea"} & set(items)
    assert {"filter", "kettle"} <= set(items)


def test_min_confidence_prunes_weak_rules(cp_app):
    engine = ComplementaryPurchaseEngine.apply()
    # bread co-occurs randomly (30%) with everything: a high confidence
    # cut keeps the deterministic pairs and drops bread rules
    ep = make_ep(min_support=0.01, min_confidence=0.9)
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(CPQuery(items=["bread"], num=5))
    assert res.item_scores == []
    res = predict(CPQuery(items=["coffee"], num=5))
    assert [s.item for s in res.item_scores] == ["filter"]


def test_basket_rules_op_exact_metrics():
    # 5 baskets: {0,1} x4, {2} x1 -> conf(0->1)=1, lift=1/(4/5)=1.25
    b = np.array([0, 0, 1, 1, 2, 2, 3, 3, 4], np.int32)
    i = np.array([0, 1, 0, 1, 0, 1, 0, 1, 2], np.int32)
    lift, idx, conf = basket_rules(b, i, 5, 3, top_k=2)
    assert idx[0][0] == 1 and conf[0][0] == 1.0
    assert abs(lift[0][0] - 1.25) < 1e-6
    assert idx[2][0] == -1
    # duplicate items in one basket do not inflate counts (scatter-max)
    b2 = np.concatenate([b, [0, 0]]).astype(np.int32)
    i2 = np.concatenate([i, [0, 1]]).astype(np.int32)
    lift2, idx2, conf2 = basket_rules(b2, i2, 5, 3, top_k=2)
    assert np.allclose(lift[np.isfinite(lift)], lift2[np.isfinite(lift2)])


def test_model_roundtrip(cp_app):
    import pickle

    engine = ComplementaryPurchaseEngine.apply()
    ep = make_ep(min_support=0.01, min_confidence=0.2)
    models = engine.train(ep)
    restored = [pickle.loads(pickle.dumps(m)) for m in models]
    q = CPQuery(items=["coffee"], num=3)
    assert (engine.predictor(ep, models)(q).to_json()
            == engine.predictor(ep, restored)(q).to_json())


def test_basket_rules_chunked_exact(monkeypatch):
    """Counts stay exact when baskets span many scan chunks."""
    from predictionio_tpu.ops import cco

    monkeypatch.setattr(cco, "_BASKET_CHUNK", 4)
    rng = np.random.default_rng(1)
    n_baskets, n_items = 50, 8
    b = rng.integers(0, n_baskets, 400).astype(np.int32)
    i = rng.integers(0, n_items, 400).astype(np.int32)
    lift, idx, conf = basket_rules(b, i, n_baskets, n_items, top_k=n_items)
    # dense numpy reference
    B = np.zeros((n_baskets, n_items))
    B[b, i] = 1.0
    C = B.T @ B
    ci = np.diag(C)
    for row in range(n_items):
        for k_, j in enumerate(idx[row]):
            if j < 0:
                continue
            conf_ref = C[row, j] / max(ci[row], 1)
            lift_ref = conf_ref / (ci[j] / n_baskets)
            assert abs(conf[row, k_] - conf_ref) < 1e-5
            assert abs(lift[row, k_] - lift_ref) < 1e-4


def test_basket_rules_item_cap():
    with pytest.raises(ValueError, match="tiled variant"):
        basket_rules(np.zeros(1, np.int32), np.zeros(1, np.int32),
                     1, 100_000, top_k=5)
