"""Test bootstrap: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's SharedSparkContext `local[*]` strategy (SURVEY.md §4)
— distributed semantics exercised without real hardware. Must set flags
before jax initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU regardless of the ambient platform config (the TPU VM's
# sitecustomize programmatically sets jax_platforms, so env vars alone are
# ignored). Set PIO_TEST_TPU=1 to run the suite against real hardware.
if not os.environ.get("PIO_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def mem_storage(monkeypatch):
    """Fresh in-memory Storage bound as the process default."""
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage

    cfg = StorageConfig(
        sources={"MEM": {"type": "memory"}},
        repositories={"METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM"},
    )
    storage = Storage(cfg)
    set_storage(storage)
    yield storage
    set_storage(None)


@pytest.fixture()
def fs_storage(tmp_path):
    from predictionio_tpu.storage.locator import Storage, StorageConfig, set_storage

    cfg = StorageConfig(
        sources={"FS": {"type": "localfs", "path": str(tmp_path / "store")}},
        repositories={"METADATA": "FS", "EVENTDATA": "FS", "MODELDATA": "FS"},
    )
    storage = Storage(cfg)
    set_storage(storage)
    yield storage
    set_storage(None)


@pytest.fixture()
def mesh8():
    from predictionio_tpu.parallel.mesh import MeshSpec, create_mesh

    return create_mesh(MeshSpec(dp=4, mp=2))
