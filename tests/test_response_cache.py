"""Provenance-invalidated response cache (serve.response_cache).

The cache's contract is ZERO staleness: a hit must be bit-identical to
the uncached tail, with ``PIO_SERVE_CACHE=off`` as the oracle.  These
tests drive real folds, real hot-swaps through
``QueryServerState.swap_models`` and the real model plane — the same
no-mocks rule as test_streaming_follow — plus direct unit coverage of
the key builder, the LRU bound, and 8-thread concurrency.
"""

import os
import threading
import types

import numpy as np
import pytest

from predictionio_tpu.serve import response_cache as rc


# -- helpers (test_streaming_follow idiom) -----------------------------------


def _buy(u, i, event="purchase"):
    from predictionio_tpu.events.event import Event

    return Event(event=event, entity_type="user", entity_id=u,
                 target_entity_type="item", target_entity_id=i)


def _set_item(i, props):
    from predictionio_tpu.events.event import DataMap, Event

    return Event(event="$set", entity_type="item", entity_id=i,
                 properties=DataMap(props))


def _cluster_events():
    """Two DISJOINT user/item clusters: a delta confined to cluster B
    provably cannot move any cluster-A answer (no shared users, items,
    or co-occurrence cells) — the shape selective invalidation needs."""
    evs = []
    for u in range(6):
        for it in range(4):
            if u == 1 and it >= 2:
                continue        # a1's own history stays short (iA0, iA1)
            evs.append(_buy(f"a{u}", f"iA{it}"))
            evs.append(_buy(f"b{u}", f"iB{it}"))
    return evs


def _ur_setup(fs_storage, app_name="rcapp", event_names=("purchase",),
              **algo_kw):
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithmParams, URDataSourceParams,
    )
    from predictionio_tpu.storage.base import App

    app_id = fs_storage.apps.insert(App(0, app_name))
    engine = UniversalRecommenderEngine.apply()
    ap = URAlgorithmParams(app_name=app_name, mesh_dp=1,
                           max_correlators_per_item=6, **algo_kw)
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name=app_name, event_names=list(event_names)),
        algorithm_params_list=[("ur", ap)])
    return app_id, engine, ap, ep


def _follow_pair(fs_storage, app_id, engine, ap, ep):
    from predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.create_server import QueryServerState

    core_workflow.run_train(engine, ep, engine_id="rc-eng",
                            storage=fs_storage)
    state = QueryServerState(
        engine, ep, UniversalRecommenderEngine.query_class, "rc-eng",
        "1", "default", storage=fs_storage)
    follower = state.follower = FollowTrainer(
        engine, ep, "rc-eng", storage=fs_storage, interval=3600,
        on_publish=state.swap_models, persist=False)
    assert follower.mode == "fold"
    assert follower.bootstrap()
    return state, follower


def _canon(res):
    return [(s.item, float(s.score)) for s in res.item_scores]


def _oracle(state, body):
    """The cold answer: same server, same generation, cache OFF."""
    os.environ["PIO_SERVE_CACHE"] = "off"
    try:
        return _canon(state.predict(body))
    finally:
        del os.environ["PIO_SERVE_CACHE"]


@pytest.fixture()
def host_serving(monkeypatch):
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")


@pytest.fixture()
def resp_cache(monkeypatch):
    """The process singleton, reset to defaults around each test."""
    for var in ("PIO_SERVE_CACHE", "PIO_SERVE_CACHE_MAX",
                "PIO_SERVE_CACHE_TTL_S", "PIO_SERVE_CACHE_AUDIT_N"):
        monkeypatch.delenv(var, raising=False)
    cache = rc.get_cache()
    cache.clear()
    cache.hit_count = cache.miss_count = 0
    cache.last_swap_invalidated = 0
    cache.last_swap_reason = ""
    yield cache
    cache.clear()


def _fake_model(n=0):
    return types.SimpleNamespace(indicator_idx={}, item_dict=None,
                                 popularity=None)


def _entry_args(seed):
    hist = {"purchase": np.array([seed, seed + 10], np.int64)}
    return (((f"it{seed}", 1.0),), hist, [seed], False, False, False)


# -- unit: key builder + intersection ----------------------------------------


def test_make_key_canonicalization():
    h = {"purchase": np.array([3, 7, 9], np.int64),
         "view": np.zeros(0, np.int64)}
    k1 = rc.make_key(5, None, h, [4, 2, 2])
    # blacklist canonicalizes to its sorted-unique id set
    assert k1 == rc.make_key(5, None, h, [2, 4])
    # empty per-type history arrays don't participate in the key
    assert k1 == rc.make_key(
        5, None, {"purchase": np.array([3, 7, 9], np.int64)}, [2, 4])
    # every other component is significant
    assert k1 != rc.make_key(6, None, h, [2, 4])
    assert k1 != rc.make_key(5, ("f",), h, [2, 4])
    assert k1 != rc.make_key(5, None, h, [2])
    assert k1 != rc.make_key(
        5, None, {"purchase": np.array([3, 7], np.int64)}, [2, 4])
    # no-history / no-blacklist shapes hash too
    assert rc.make_key(5, None, None, []) == rc.make_key(5, None, {}, [])


def test_intersects_sorted_arrays():
    a = np.array([1, 5, 9], np.int64)
    assert rc._intersects(a, np.array([5], np.int64))
    assert rc._intersects(np.array([9], np.int64), a)
    assert not rc._intersects(a, np.array([2, 4, 10], np.int64))
    assert not rc._intersects(a, np.zeros(0, np.int64))
    assert not rc._intersects(np.zeros(0, np.int64), a)


# -- unit: LRU bound, stale puts, kill switch --------------------------------


def test_lru_bound_eviction_and_stale_put(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_CACHE_MAX", "4")
    cache = rc.ResponseCache()
    model = _fake_model()
    cache.on_swap([model])
    for k in range(6):
        cache.put(model, ("k", k), *_entry_args(k))
    assert len(cache) == 4
    # LRU: the two oldest fell off, the newest four serve
    assert cache.lookup(model, ("k", 0))[0] is None
    assert cache.lookup(model, ("k", 1))[0] is None
    for k in range(2, 6):
        items, _ = cache.lookup(model, ("k", k))
        assert items == ((f"it{k}", 1.0),)
    # a put from a superseded generation is refused
    cache.put(_fake_model(), ("stale",), *_entry_args(99))
    assert cache.lookup(model, ("stale",))[0] is None
    # a lookup against a superseded generation bypasses (no hit, no fill)
    assert cache.lookup(_fake_model(), ("k", 5))[0] is None
    # kill switch: puts refused, armed_for goes dark
    monkeypatch.setenv("PIO_SERVE_CACHE", "off")
    assert not cache.armed_for(model)
    cache.put(model, ("dark",), *_entry_args(7))
    monkeypatch.delenv("PIO_SERVE_CACHE")
    assert cache.lookup(model, ("dark",))[0] is None


def test_swap_without_provenance_flushes_unit():
    cache = rc.ResponseCache()
    m1, m2 = _fake_model(), _fake_model()
    cache.on_swap([m1])
    cache.put(m1, ("k",), *_entry_args(1))
    assert len(cache) == 1
    # m2 carries no provenance relative to m1 → full flush
    cache.on_swap([m2])
    assert len(cache) == 0
    assert cache.last_swap_reason == "no_provenance"
    assert cache.last_swap_invalidated == 1
    # a non-single-model install disarms entirely
    cache.put(m2, ("k2",), *_entry_args(2))
    cache.on_swap([m2, m2])
    assert len(cache) == 0
    assert not cache.armed_for(m2)


def test_thread_safety_under_concurrent_swaps(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_CACHE_MAX", "64")
    cache = rc.ResponseCache()
    models = [_fake_model() for _ in range(3)]
    cache.on_swap([models[0]])
    errors = []
    barrier = threading.Barrier(8)

    def worker(tid):
        try:
            barrier.wait()
            for j in range(400):
                m = models[(tid + j) % 3]
                if j % 97 == 0:
                    cache.on_swap([m])
                elif j % 31 == 0:
                    cache.clear() if j % 62 else cache.on_swap([m])
                else:
                    key = ("t", tid, j % 40)
                    items, _ = cache.lookup(m, key)
                    if items is None:
                        cache.put(m, key, *_entry_args(j))
                len(cache)
        except Exception as e:   # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(cache) <= 64


# -- served responses: hits are bit-identical to the off oracle --------------


def test_cache_hit_bit_identical_to_oracle(fs_storage, host_serving,
                                           resp_cache):
    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item(f"iA{k}", {"category": "red" if k < 2 else "blue"})
         for k in range(4)], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    bodies = [
        {"user": "a1", "num": 3},
        {"user": "nobody", "num": 4},
        {"item": "iA1", "num": 4},
        {"user": "b2", "num": 4, "blacklistItems": ["iB1", "iB1"]},
        {"user": "a2", "num": 4,
         "fields": [{"name": "category", "values": ["red"], "bias": -1}]},
    ]
    first = [_canon(state.predict(b)) for b in bodies]       # miss + fill
    assert resp_cache.miss_count == len(bodies)
    assert len(resp_cache) == len(bodies)
    again = [_canon(state.predict(b)) for b in bodies]       # all hits
    assert resp_cache.hit_count == len(bodies)
    assert again == first
    for b, want in zip(bodies, first):
        assert _oracle(state, b) == want
    # blacklist canonicalization: dup/order variants share one entry
    hits0 = resp_cache.hit_count
    state.predict({"user": "b2", "num": 4, "blacklistItems": ["iB1"]})
    assert resp_cache.hit_count == hits0 + 1


def test_user_drift_reroutes_key_without_invalidation(fs_storage,
                                                      host_serving,
                                                      resp_cache):
    """An event append changes the user's history fingerprint — the next
    lookup MISSES under a new key even with no swap in between (the
    fold/swap only has to cover model drift, never user drift)."""
    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    body = {"user": "a1", "num": 3}
    state.predict(body)
    assert resp_cache.miss_count == 1
    # a1 buys something new: same query text, different history → miss
    fs_storage.l_events.insert_batch([_buy("a1", "iA3")], app_id)
    got = _canon(state.predict(body))
    assert resp_cache.miss_count == 2 and resp_cache.hit_count == 0
    assert _oracle(state, body) == got


# -- swap invalidation: selective survival, flush fallbacks ------------------


def test_fold_swap_selective_invalidation(fs_storage, host_serving,
                                          resp_cache):
    """A duplicate-only fold (pop moved on one B item, zero indicator
    rows) drops exactly the entries its changed sets reach: the cluster-B
    answer goes, the cluster-A answer survives the swap AS A HIT."""
    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    qa = {"user": "a1", "num": 2}       # 2 signal picks, no backfill
    qb = {"user": "b1", "num": 6}       # pads from popularity backfill
    want_a, want_b = _canon(state.predict(qa)), _canon(state.predict(qb))
    assert len(resp_cache) == 2
    # duplicate of an existing (b0, iB0) pair: no new co-occurrence
    # cells, but iB0's popularity count bumps
    fs_storage.l_events.insert_batch([_buy("b0", "iB0")], app_id)
    assert follower.tick() == "fold"
    assert resp_cache.last_swap_reason == "selective"
    assert resp_cache.last_swap_invalidated == 1
    # cluster A survived: served from cache, still oracle-identical
    hits0 = resp_cache.hit_count
    got_a = _canon(state.predict(qa))
    assert resp_cache.hit_count == hits0 + 1
    assert got_a == want_a == _oracle(state, qa)
    # cluster B was dropped: recomputed fresh against the new generation
    miss0 = resp_cache.miss_count
    got_b = _canon(state.predict(qb))
    assert resp_cache.miss_count == miss0 + 1
    assert got_b == _oracle(state, qb)


def test_props_change_drops_rule_entries_keeps_plain(fs_storage,
                                                     host_serving,
                                                     resp_cache):
    """A $set fold: entries that composed business rules drop (the mask
    depends on properties), plain history entries survive."""
    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item(f"iA{k}", {"category": "red"}) for k in range(4)], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    # a1's unseen candidates are iA2/iA3 — both red at fill time
    plain = {"user": "a1", "num": 2}
    ruled = {"user": "a1", "num": 4,
             "fields": [{"name": "category", "values": ["red"], "bias": -1}]}
    state.predict(plain)
    want_ruled = _canon(state.predict(ruled))
    assert want_ruled, "fixture: red filter should match items"
    # move iA3 to blue — a pure $set fold (no pair events)
    fs_storage.l_events.insert_batch(
        [_set_item("iA3", {"category": "blue"})], app_id)
    assert follower.tick() == "fold"
    assert resp_cache.last_swap_reason == "selective"
    hits0, miss0 = resp_cache.hit_count, resp_cache.miss_count
    got_plain = _canon(state.predict(plain))
    assert resp_cache.hit_count == hits0 + 1          # survived
    got_ruled = _canon(state.predict(ruled))
    assert resp_cache.miss_count == miss0 + 1         # dropped, refilled
    assert got_plain == _oracle(state, plain)
    assert got_ruled == _oracle(state, ruled)
    assert "iA3" not in [n for n, _ in got_ruled]


def test_retrain_and_restage_swaps_full_flush(fs_storage, host_serving,
                                              resp_cache):
    """Provenance-free generations (a from-scratch retrain swap, a
    max-lag restage) flush everything — and post-flush answers still
    match the oracle on the new generation."""
    from predictionio_tpu.store.event_store import invalidate_staging_cache

    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    body = {"user": "a1", "num": 3}
    state.predict(body)
    assert len(resp_cache) == 1
    # retrain swap: no _plane_prov linkage to the armed generation
    invalidate_staging_cache()
    state.swap_models(list(engine.train(ep)))
    assert len(resp_cache) == 0
    assert resp_cache.last_swap_reason == "no_provenance"
    got = _canon(state.predict(body))
    assert got == _oracle(state, body)
    assert len(resp_cache) == 1
    # restage: max-lag breach rebuilds the fold state from scratch
    follower.max_lag = 2
    fs_storage.l_events.insert_batch(
        [_buy(f"n{k}", "iA0") for k in range(6)], app_id)
    assert follower.tick() == "restage"
    assert len(resp_cache) == 0
    assert resp_cache.last_swap_reason == "no_provenance"
    got = _canon(state.predict(body))
    assert got == _oracle(state, body)


def test_rule_mask_cache_carries_when_props_untouched(fs_storage,
                                                      host_serving,
                                                      resp_cache,
                                                      monkeypatch):
    """Satellite: the rule-mask LRU survives swaps whose provenance
    proves properties untouched (carried BY OBJECT), and drops across a
    props-changing fold."""
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    app_id, engine, ap, ep = _ur_setup(
        fs_storage, available_date_name="", expire_date_name="")
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    fs_storage.l_events.insert_batch(
        [_set_item(f"iA{k}", {"category": "red"}) for k in range(4)], app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    ruled = {"user": "a1", "num": 4,
             "fields": [{"name": "category", "values": ["red"], "bias": -1}]}
    assert state.predict(ruled).item_scores
    m1 = follower._fold.model
    lru1 = m1.rule_mask_cache("host")
    assert len(lru1) > 0, "fixture: dense mask cache must populate"
    # props-untouched fold → the LRU object itself carries
    fs_storage.l_events.insert_batch([_buy("b0", "iB0")], app_id)
    assert follower.tick() == "fold"
    m2 = follower._fold.model
    assert m2 is not m1
    assert m2.rule_mask_cache("host") is lru1
    # props-changing fold → fresh (empty) cache on the new generation
    fs_storage.l_events.insert_batch(
        [_set_item("iA2", {"category": "blue"})], app_id)
    assert follower.tick() == "fold"
    m3 = follower._fold.model
    assert m3.rule_mask_cache("host") is not lru1
    assert len(m3.rule_mask_cache("host")) == 0
    after = {s.item for s in state.predict(ruled).item_scores}
    assert "iA2" not in after and after


# -- batch path --------------------------------------------------------------


def test_serve_batch_predict_shares_the_cache(fs_storage, host_serving,
                                              resp_cache):
    """serve_batch_predict consults and fills the SAME cache with
    per-row outcome counting — a single predict warms the batch path and
    vice versa, all bit-identical to the unbatched answers."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )

    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    model = follower._fold.model
    algo = URAlgorithm(ap)
    queries = [URQuery(user="a1", num=3), URQuery(user="b1", num=3),
               URQuery(user="nobody", num=2)]
    # warm one row through the single-query path
    single = _canon(state.predict({"user": "a1", "num": 3}))
    assert resp_cache.miss_count == 1
    batch = algo.serve_batch_predict(model, queries)
    assert resp_cache.hit_count == 1                  # a1 came from cache
    assert resp_cache.miss_count == 3                 # b1 + nobody filled
    assert _canon(batch[0]) == single
    for q, res in zip(queries, batch):
        assert _canon(algo.predict(model, q)) == _canon(res)
    # the whole batch now serves from cache
    again = algo.serve_batch_predict(model, queries)
    assert resp_cache.miss_count == 3
    assert [_canon(r) for r in again] == [_canon(r) for r in batch]


# -- plane workers: provenance rides the arena -------------------------------


def test_plane_worker_selective_invalidation(fs_storage, host_serving,
                                             resp_cache, tmp_path):
    """A prefork worker never sees the publisher's in-process weakref
    stash — the changed sets must ride the arena.  Load gen N and N+1
    through ModelPlane, swap the worker-side cache between them, and the
    cluster-A entry survives selectively off the plane-carried
    provenance."""
    from predictionio_tpu.models.universal_recommender import URQuery
    from predictionio_tpu.models.universal_recommender.engine import (
        URAlgorithm,
    )
    from predictionio_tpu.streaming.fold import URFoldState
    from predictionio_tpu.streaming.plane import ModelPlane

    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    tail = fs_storage.l_events.scan_tail_from(app_id, None, {}, base=None,
                                              heads=None)
    fold = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    wm, heads = tail["watermark"], tail["heads"]
    pub, sub = ModelPlane(str(tmp_path / "plane")), \
        ModelPlane(str(tmp_path / "plane"))
    pub.publish([fold.model])
    w1, _ = sub.load(sub.current())
    resp_cache.on_swap([w1])
    algo = URAlgorithm(ap)
    qa, qb = URQuery(user="a1", num=2), URQuery(user="b1", num=6)
    want_a = _canon(algo.predict(w1, qa))
    want_b = _canon(algo.predict(w1, qb))
    assert len(resp_cache) == 2
    # duplicate-only delta published as generation 2
    fs_storage.l_events.insert_batch([_buy("b0", "iB0")], app_id)
    tail = fs_storage.l_events.scan_tail_from(app_id, None, wm,
                                              base=fold.batch, heads=heads)
    m2 = fold.fold(tail["batch"])
    pub.publish([m2])
    w2, info = sub.load(sub.current())
    sp = w2.__dict__.get("_serve_prov")
    assert sp is not None, "serve provenance must ride the arena"
    assert sp["prev_gen"] == w1.__dict__["_plane_generation"]
    assert not sp["props_changed"]
    resp_cache.on_swap([w2])
    assert resp_cache.last_swap_reason == "selective"
    hits0, miss0 = resp_cache.hit_count, resp_cache.miss_count
    got_a = _canon(algo.predict(w2, qa))
    assert resp_cache.hit_count == hits0 + 1          # survived the swap
    assert got_a == want_a
    got_b = _canon(algo.predict(w2, qb))
    assert resp_cache.miss_count == miss0 + 1         # dropped, refilled
    os.environ["PIO_SERVE_CACHE"] = "off"
    try:
        assert got_a == _canon(algo.predict(w2, qa))
        assert got_b == _canon(algo.predict(w2, qb))
    finally:
        del os.environ["PIO_SERVE_CACHE"]


def test_plane_rebuild_without_provenance_flushes(fs_storage, host_serving,
                                                  resp_cache, tmp_path):
    """A rebuilt generation (restage/retrain — no fold linkage to the
    previous publish) carries no serveProv in the arena — the worker-
    side swap must full-flush."""
    from predictionio_tpu.store.event_store import invalidate_staging_cache
    from predictionio_tpu.streaming.fold import URFoldState
    from predictionio_tpu.streaming.plane import ModelPlane

    app_id, engine, ap, ep = _ur_setup(fs_storage)
    fs_storage.l_events.insert_batch(_cluster_events(), app_id)
    tail = fs_storage.l_events.scan_tail_from(app_id, None, {}, base=None,
                                              heads=None)
    fold = URFoldState.bootstrap(ap, ep.data_source_params, tail["batch"])
    pub, sub = ModelPlane(str(tmp_path / "plane")), \
        ModelPlane(str(tmp_path / "plane"))
    pub.publish([fold.model])
    w1, _ = sub.load(sub.current())
    resp_cache.on_swap([w1])
    resp_cache.put(w1, ("seed",), *_entry_args(1))
    # generation 2 is a from-scratch retrain: no _plane_prov linkage
    fs_storage.l_events.insert_batch([_buy("b0", "iB0")], app_id)
    invalidate_staging_cache()
    pub.publish(list(engine.train(ep)))
    w2, _ = sub.load(sub.current())
    assert "_serve_prov" not in w2.__dict__
    resp_cache.on_swap([w2])
    assert len(resp_cache) == 0
    assert resp_cache.last_swap_reason == "no_provenance"


# -- randomized property test: replay after every swap ------------------------


def test_randomized_folds_replay_bit_identical(fs_storage, host_serving,
                                               resp_cache, monkeypatch):
    """The acceptance property: across a randomized fold sequence (N
    bumps, new items, $set, duplicate-only, restage) every query replay
    after every swap is bit-identical to a cold PIO_SERVE_CACHE=off
    server on the SAME generation, with the online audit sampling every
    third hit and recording zero mismatches."""
    # force the pruned sparse re-LLR even at toy scale so folds carry
    # serve provenance exactly as the million-item regime does
    monkeypatch.setenv("PIO_FOLLOW_DENSE_RELLR_BYTES", "1")
    monkeypatch.setenv("PIO_SERVE_CACHE_AUDIT_N", "3")
    audit0 = rc._M_AUDIT.value()
    rng = np.random.default_rng(7)
    app_id, engine, ap, ep = _ur_setup(fs_storage)
    evs = [_buy(f"u{u}", f"i{it}")
           for u in range(10) for it in range(8) if rng.random() < 0.5]
    evs += [_set_item(f"i{it}", {"category": "red" if it < 4 else "blue"})
            for it in range(8)]
    fs_storage.l_events.insert_batch(evs, app_id)
    state, follower = _follow_pair(fs_storage, app_id, engine, ap, ep)
    bodies = ([{"user": f"u{u}", "num": 5} for u in range(0, 10, 2)]
              + [{"user": "nobody", "num": 3}, {"item": "i1", "num": 4},
                 {"user": "u1", "num": 5, "blacklistItems": ["i2"]},
                 {"user": "u3", "num": 6, "fields": [
                     {"name": "category", "values": ["red"], "bias": -1}]}])

    def replay(tag):
        for b in bodies:
            got = _canon(state.predict(b))
            assert got == _oracle(state, b), (tag, b)

    replay("bootstrap")
    replay("warm")         # second pass: mostly hits, audited every 3rd
    deltas = [
        # existing-user count bumps (new pairs, no new entities)
        [_buy(f"u{rng.integers(10)}", f"i{rng.integers(8)}")
         for _ in range(4)],
        # brand-new items + a new user (catalog growth)
        [_buy("u1", "fresh_x"), _buy("u2", "fresh_x"),
         _buy("newbie", "i0"), _buy("newbie", "fresh_y")],
        # property churn only
        [_set_item(f"i{k}", {"category": "gold"}) for k in (1, 5)],
        # duplicate-only (fold must skip every re-LLR)
        [e for e in evs if e.event == "purchase"][:10],
        # another bump round after growth
        [_buy(f"u{rng.integers(10)}", f"i{rng.integers(8)}")
         for _ in range(3)],
    ]
    selective_swaps = 0
    for k, delta in enumerate(deltas):
        fs_storage.l_events.insert_batch(delta, app_id)
        assert follower.tick() == "fold", k
        if resp_cache.last_swap_reason == "selective":
            selective_swaps += 1
        replay(f"fold{k}")
    # restage: provenance-free rebuild mid-sequence
    follower.max_lag = 2
    fs_storage.l_events.insert_batch(
        [_buy(f"z{k}", "i0") for k in range(6)], app_id)
    assert follower.tick() == "restage"
    follower.max_lag = None
    assert resp_cache.last_swap_reason == "no_provenance"
    replay("restage")
    # the sequence must have exercised BOTH regimes
    assert selective_swaps >= 1
    assert resp_cache.hit_count > 0
    # zero staleness, zero audit failures
    assert rc._M_AUDIT.value() == audit0
