"""Cluster observability (ISSUE 20): cross-node lineage stitching,
metrics federation, and cluster SLOs.

The unit of observation is the CLUSTER: one stitched lineage record per
generation must span the publisher's fold/publish stages AND every
subscriber node's repl.*/install/first_serve lanes, reaching
``cluster_complete`` only when all expected nodes installed and served.
Federation keeps a dead node visible (``up: false``) instead of
dropping it, and the cluster SLO rows ride the same burn-rate engine as
the local ones.
"""

import http.server
import json
import socket
import threading
import time

import pytest

from predictionio_tpu.obs import lineage as obs_lineage
from predictionio_tpu.obs.cluster import ClusterFederation, _divergence
from predictionio_tpu.obs.lineage import (
    LineageRecorder,
    apply_cluster_outcome,
    merge_records,
    render_lineage_cluster_text,
)
from predictionio_tpu.obs.slo import (
    CLUSTER_SLOS,
    SloEngine,
    arm_cluster_slos,
    get_engine,
    set_engine,
)

from test_plane_replication import (  # noqa: F401 - fixtures ride along
    _publisher,
    fast_repl,
    host_serving,
)


def _frag(lid, start, stages, outcome=None, generation=None):
    doc = {"lid": lid, "start": start, "stages": stages}
    if outcome:
        doc["outcome"] = outcome
    if generation is not None:
        doc["generation"] = generation
    return doc


def _stage(name, start, worker="w", node=None, duration_s=0.01):
    s = {"stage": name, "start": start, "duration_s": duration_s,
         "worker": worker}
    if node:
        s["node"] = node
    return s


def _node_lane(lid, start, node, worker=None):
    """A subscriber node's full lane: recv → verify → land → install →
    first_serve."""
    w = worker or node
    return [
        _stage("repl.recv", start, w, node),
        _stage("repl.verify", start + 0.05, w, node),
        _stage("repl.land", start + 0.1, w, node),
        _stage("install", start + 0.2, w, node),
        _stage("first_serve", start + 0.3, w, node),
    ]


def _origin_frag(lid, start=100.0):
    return _frag(lid, start,
                 [_stage("append_observed", start, "pub"),
                  _stage("publish", start + 0.5, "pub"),
                  _stage("install", start + 0.6, "pub-w"),
                  _stage("first_serve", start + 0.7, "pub-w")],
                 outcome="published", generation=7)


# -- stitched outcome semantics ----------------------------------------------


class TestClusterOutcome:
    def test_all_nodes_complete_is_cluster_complete(self):
        doc = merge_records([
            _origin_frag("ln-a"),
            _frag("ln-a", 100.0, _node_lane("ln-a", 101.0, "node-a")),
            _frag("ln-a", 100.0, _node_lane("ln-a", 102.0, "node-b")),
        ])[0]
        apply_cluster_outcome(doc, ["node-a", "node-b"],
                              live=["node-a", "node-b"])
        assert doc["outcome"] == "cluster_complete"
        cl = doc["cluster"]
        assert cl["done"] == ["node-a", "node-b"] and not cl["missing"]
        assert cl["nodes"]["node-a"]["status"] == "complete"
        # propagation = record start → LAST node's first_serve end
        assert cl["propagationMs"] == pytest.approx(
            (102.3 + 0.01 - 100.0) * 1e3, abs=1.0)

    def test_one_lagging_node_demotes_to_published(self):
        lane_b = _node_lane("ln-b", 102.0, "node-b")[:3]  # landed, no serve
        doc = merge_records([
            _origin_frag("ln-b"),
            _frag("ln-b", 100.0, _node_lane("ln-b", 101.0, "node-a")),
            _frag("ln-b", 100.0, lane_b),
        ])[0]
        apply_cluster_outcome(doc, ["node-a", "node-b"],
                              live=["node-a", "node-b"])
        assert doc["outcome"] == "published"      # cluster not done
        cl = doc["cluster"]
        assert cl["missing"] == ["node-b"]
        assert cl["nodes"]["node-b"]["status"] == "open"   # still live
        assert "propagationMs" not in cl

    def test_dead_node_lane_is_abandoned_never_seen_is_missing(self):
        doc = merge_records([
            _origin_frag("ln-c"),
            _frag("ln-c", 100.0,
                  _node_lane("ln-c", 101.0, "node-a")[:2]),
        ])[0]
        apply_cluster_outcome(doc, ["node-a", "node-b"], live=[])
        assert doc["cluster"]["nodes"]["node-a"]["status"] == "abandoned"
        assert doc["cluster"]["nodes"]["node-b"]["status"] == "abandoned"
        doc2 = merge_records([
            _origin_frag("ln-d"),
            _frag("ln-d", 100.0, _node_lane("ln-d", 101.0, "node-a")),
        ])[0]
        apply_cluster_outcome(doc2, ["node-a", "node-b"])  # no live view
        assert doc2["cluster"]["nodes"]["node-b"]["status"] == "missing"

    def test_no_expected_nodes_leaves_single_node_semantics(self):
        doc = merge_records([_origin_frag("ln-e")])[0]
        apply_cluster_outcome(doc, [])
        assert doc["outcome"] == "complete"       # unchanged

    def test_cluster_waterfall_renders_per_node_lanes(self):
        doc = merge_records([
            _origin_frag("ln-f"),
            _frag("ln-f", 100.0, _node_lane("ln-f", 101.0, "node-a")),
        ])[0]
        apply_cluster_outcome(doc, ["node-a", "node-b"],
                              live=["node-a"])
        text = render_lineage_cluster_text(doc)
        assert "node node-a" in text and "node node-b" in text
        assert "publisher" in text
        assert "repl.land" in text and "first_serve" in text


class TestOrphanSupersession:
    def test_repl_land_supersedes_cut_short_transfer(self):
        """Satellite bugfix: a subscriber record whose transfer was cut
        short (repl.recv, no land) goes ``abandoned`` as soon as a newer
        generation LANDS — repl.land is the subscriber's publish-
        equivalent marker, so post-resync orphans leak nothing."""
        recs = merge_records([
            _frag("ln-cut", 10.0,
                  [_stage("repl.recv", 10.0, "sub", "node-a")]),
            _frag("ln-next", 20.0,
                  [_stage("repl.recv", 20.0, "sub", "node-a"),
                   _stage("repl.land", 20.2, "sub", "node-a")]),
        ])
        by = {r["lid"]: r for r in recs}
        assert by["ln-cut"]["outcome"] == "abandoned"
        assert by["ln-next"]["outcome"] == "published"


# -- the real drill: wire-level stitching + a killed subscriber ---------------


class TestStitchedDrill:
    def _arm(self, tmp_path):
        rec = LineageRecorder(directory=tmp_path / "lineage",
                              tag="drill", enabled=True)
        obs_lineage.set_lineage(rec)
        return rec

    def _publish_gen(self, rec, pub, model, lid=None):
        lid = lid or rec.new_id()
        t0 = time.time()
        rec.begin(lid, start=t0)
        rec.stage(lid, "append_observed", start=t0, node="pub-node")
        pub.publish([model], {"mode": "test", "lineageId": lid})
        rec.stage(lid, "publish", start=time.time(), node="pub-node")
        rec.close(lid, "published")
        return lid

    def _serve_lane(self, rec, lid, node):
        """The serve half a deploy would stamp (install + first_serve
        carry the node from PIO_CLUSTER_NODE there; explicit here)."""
        rec.stage(lid, "install", node=node, flush=True)
        rec.stage(lid, "first_serve", node=node, flush=True)

    def test_killed_subscriber_lane_abandoned_record_survives(
            self, mem_storage, host_serving, fast_repl, tmp_path):
        from predictionio_tpu.streaming.replicate import (
            PlaneReplicator, PlaneSubscriber,
        )

        rec = self._arm(tmp_path)
        try:
            pub, model, _algo = _publisher(tmp_path, mem_storage,
                                           n_gens=0)
            lid1 = self._publish_gen(rec, pub, model)
            repl = PlaneReplicator(pub, bind="127.0.0.1:0")
            repl.start()
            sub_a = PlaneSubscriber(str(tmp_path / "sub-a"),
                                    f"127.0.0.1:{repl.port}",
                                    node="node-a")
            sub_b = PlaneSubscriber(str(tmp_path / "sub-b"),
                                    f"127.0.0.1:{repl.port}",
                                    node="node-b")
            sub_a.start()
            sub_b.start()
            try:
                assert sub_a.wait_generation(1, timeout=20)
                assert sub_b.wait_generation(1, timeout=20)
                view = repl.cluster_view()
                assert sorted(view["expected"]) == ["node-a", "node-b"]
                self._serve_lane(rec, lid1, "node-a")
                self._serve_lane(rec, lid1, "node-b")
                doc = rec.get(lid1)
                obs_lineage.apply_cluster_outcome(
                    doc, view["expected"], view["live"])
                assert doc["outcome"] == "cluster_complete"
                # the repl.* stages came over the REAL ack channel,
                # source-stamped by each subscriber
                for node in ("node-a", "node-b"):
                    names = {s["stage"] for s in doc["stages"]
                             if s.get("node") == node}
                    assert {"repl.recv", "repl.land", "install",
                            "first_serve"} <= names
                assert doc["cluster"]["propagationMs"] > 0

                # -- kill node-b, publish again: its lane must read
                #    abandoned while the cluster record survives
                sub_b.stop()
                deadline = time.time() + 10
                while time.time() < deadline:
                    if "node-b" not in repl.cluster_view()["live"]:
                        break
                    time.sleep(0.05)
                lid2 = self._publish_gen(rec, pub, model)
                assert sub_a.wait_generation(2, timeout=20)
                # let node-a's ack (carrying its repl.* stages) land
                deadline = time.time() + 10
                while time.time() < deadline:
                    d = rec.get(lid2)
                    names_a = {s["stage"] for s in d["stages"]
                               if s.get("node") == "node-a"}
                    if "repl.land" in names_a:
                        break
                    time.sleep(0.05)
                self._serve_lane(rec, lid2, "node-a")
                view = repl.cluster_view()
                assert sorted(view["expected"]) == ["node-a", "node-b"]
                assert view["live"] == ["node-a"]
                doc2 = rec.get(lid2)
                obs_lineage.apply_cluster_outcome(
                    doc2, view["expected"], view["live"])
                assert doc2["outcome"] == "published"   # not complete
                assert doc2["cluster"]["nodes"]["node-a"]["status"] == \
                    "complete"
                assert doc2["cluster"]["nodes"]["node-b"]["status"] == \
                    "abandoned"
            finally:
                sub_a.stop()
                sub_b.stop()
                repl.stop()
        finally:
            obs_lineage.set_lineage(None)


# -- metrics federation -------------------------------------------------------


def _history_body(generation=5, lag=0.0, reqs=(100.0, 200.0)):
    def sample(t, total):
        return {"t": t, "m": {
            "pio_model_plane_generation": {
                "type": "gauge",
                "series": {'worker="w"': float(generation)}},
            "pio_plane_repl_lag_generations": {
                "type": "gauge", "series": {'node="x"': float(lag)}},
            "pio_http_requests_total": {
                "type": "counter",
                "series": {'route="/queries.json",status="200"': total}},
        }}
    return {"worker": "w", "intervalSeconds": 5.0, "buckets": {},
            "samples": [sample(1000.0, reqs[0]), sample(1010.0, reqs[1])]}


class _NodeHandler(http.server.BaseHTTPRequestHandler):
    body = _history_body()

    def do_GET(self):
        if self.path.startswith("/metrics/history.json"):
            payload = json.dumps(type(self).body).encode()
        elif self.path == "/lineage.json":
            payload = json.dumps({"records": []}).encode()
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *a):
        pass


@pytest.fixture()
def node_server():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _NodeHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()
    srv.server_close()


class TestFederation:
    def test_down_node_stays_visible_as_stale(self, node_server):
        with socket.socket() as s:      # a port nothing listens on
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        peers = {
            "good": {"addr": "127.0.0.1", "httpPort": node_server,
                     "connected": True},
            "dead": {"addr": "127.0.0.1", "httpPort": dead_port,
                     "connected": False},
            "shy": {"addr": "127.0.0.1", "httpPort": 0,
                    "connected": True},
        }
        fed = ClusterFederation(lambda: peers, interval=60.0,
                                timeout=0.5)
        fed.scrape_once()
        fed.scrape_once()
        doc = fed.metrics_doc()
        nodes = doc["nodes"]
        # every peer reported — the down ones flagged, never dropped
        assert set(nodes) == {"good", "dead", "shy"}
        good = nodes["good"]
        assert good["up"] is True and good["error"] is None
        assert good["generation"] == 5
        assert good["qps"] == pytest.approx(10.0, abs=0.01)
        assert good["staleSeconds"] == pytest.approx(0.0, abs=5.0)
        dead = nodes["dead"]
        assert dead["up"] is False and dead["error"]
        shy = nodes["shy"]
        assert shy["up"] is False
        assert "no HTTP endpoint" in shy["error"]
        hist = fed.history_doc()
        assert len(hist["samples"]) == 2
        assert set(hist["samples"][-1]["nodes"]) == \
            {"good", "dead", "shy"}

    def test_divergence_math(self):
        assert _divergence([10.0, 10.0]) == 1.0
        assert _divergence([30.0, 10.0, 20.0]) == pytest.approx(1.5)
        assert _divergence([10.0]) == 1.0          # one node: no skew
        assert _divergence([None, 0.0]) == 1.0     # nothing flows


# -- cluster SLOs -------------------------------------------------------------


def _lag_sample(t, lag):
    return {"t": t, "m": {"pio_plane_repl_lag_generations": {
        "type": "gauge", "series": {'node="sub-1"': float(lag)}}}}


class TestClusterSlos:
    def test_repl_lag_burning_then_ok(self):
        eng = SloEngine(CLUSTER_SLOS)
        base = 1_000_000.0
        hot = [_lag_sample(base + i * 10, 20.0) for i in range(8)]
        doc = eng.evaluate(hot, {})
        v = doc["slos"]["cluster_repl_lag"]
        assert v["verdict"] == "burning"
        assert v["lastValue"] == 20.0
        cool = [_lag_sample(base + i * 10, 1.0) for i in range(8)]
        doc = eng.evaluate(cool, {})
        assert doc["slos"]["cluster_repl_lag"]["verdict"] == "ok"
        # divergence rows are quiet until the gauges exist
        assert doc["slos"]["cluster_qps_divergence"]["verdict"] == \
            "no_data"

    def test_arm_cluster_slos_is_idempotent(self):
        set_engine(None)
        try:
            n0 = len(get_engine().slos)
            eng = arm_cluster_slos()
            assert eng is get_engine()
            n1 = len(eng.slos)
            assert n1 == n0 + len(CLUSTER_SLOS)
            assert len(arm_cluster_slos().slos) == n1   # no dupes
            names = {s["name"] for s in eng.slos}
            assert {"cluster_propagation_p99", "cluster_repl_lag",
                    "cluster_qps_divergence",
                    "cluster_p95_divergence"} <= names
        finally:
            set_engine(None)
