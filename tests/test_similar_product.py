"""Similar-product template tests: ALS-cosine and cooccurrence similarity,
category/white/black-list filters."""

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.similar_product import (
    SimilarProductEngine,
    SimilarProductQuery,
)
from predictionio_tpu.models.similar_product.engine import (
    SPALSParams,
    SPCooccurrenceParams,
    SPDataSourceParams,
)
from predictionio_tpu.storage import App


@pytest.fixture()
def sp_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "spapp"))
    rng = np.random.default_rng(4)
    events = []
    # two co-view clusters: {a0..a4} and {z0..z4}
    for u in range(40):
        items = [f"a{i}" for i in range(5)] if u % 2 == 0 else [f"z{i}" for i in range(5)]
        for it in items:
            if rng.random() < 0.8:
                events.append(Event(event="view", entity_type="user",
                                    entity_id=f"u{u}", target_entity_type="item",
                                    target_entity_id=it))
    for i in range(5):
        events.append(Event(event="$set", entity_type="item", entity_id=f"a{i}",
                            properties=DataMap({"categories": ["alpha"]})))
        events.append(Event(event="$set", entity_type="item", entity_id=f"z{i}",
                            properties=DataMap({"categories": ["zeta"]})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


def make_ep(algo_name, params):
    return EngineParams(
        data_source_params=SPDataSourceParams(app_name="spapp"),
        algorithm_params_list=[(algo_name, params)],
    )


@pytest.mark.parametrize("algo,params", [
    # rank 2 = the data's true cluster count (implicit ALS at higher
    # rank overfits this tiny binary matrix and neighbors get noisy)
    ("als", SPALSParams(rank=2, num_iterations=20, mesh_dp=1)),
    ("cooccurrence", SPCooccurrenceParams(mesh_dp=1, min_llr=1.0)),
])
def test_similar_items_stay_in_cluster(sp_app, algo, params):
    engine = SimilarProductEngine.apply()
    ep = make_ep(algo, params)
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(SimilarProductQuery(items=["a1"], num=3))
    assert res.item_scores, f"{algo}: expected similar items"
    assert all(s.item.startswith("a") for s in res.item_scores), res.item_scores
    assert "a1" not in [s.item for s in res.item_scores]


def test_multi_item_query_and_blacklist(sp_app):
    engine = SimilarProductEngine.apply()
    ep = make_ep("cooccurrence", SPCooccurrenceParams(mesh_dp=1))
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(SimilarProductQuery(items=["a0", "a1"], num=4, black_list=["a2"]))
    items = [s.item for s in res.item_scores]
    assert "a2" not in items and "a0" not in items and "a1" not in items


def test_category_filter_and_whitelist(sp_app):
    engine = SimilarProductEngine.apply()
    ep = make_ep("cooccurrence", SPCooccurrenceParams(mesh_dp=1))
    models = engine.train(ep)
    predict = engine.predictor(ep, models)
    res = predict(SimilarProductQuery(items=["a0"], num=5, categories=["zeta"]))
    assert all(s.item.startswith("z") for s in res.item_scores)
    res2 = predict(SimilarProductQuery(items=["a0"], num=5, white_list=["a3"]))
    assert [s.item for s in res2.item_scores] in ([], ["a3"])


def test_query_json():
    q = SimilarProductQuery.from_json(
        {"items": ["i1"], "num": 2, "whiteList": ["i2"], "blackList": ["i3"],
         "categories": ["c"]})
    assert q.items == ["i1"] and q.white_list == ["i2"] and q.categories == ["c"]


@pytest.mark.parametrize("algo,params", [
    ("als", SPALSParams(rank=8, num_iterations=5, mesh_dp=1)),
    ("cooccurrence", SPCooccurrenceParams(mesh_dp=1)),
])
def test_sp_serve_batch_matches_serial(sp_app, algo, params):
    """serve_batch_predict ≡ predict on both algorithm kinds across
    plain / multi-item / rules / blacklist / unresolvable queries."""
    engine = SimilarProductEngine.apply()
    ep = make_ep(algo, params)
    models = engine.train(ep)
    model = models[0]
    a = engine.algorithm_classes[algo](params)
    queries = [
        SimilarProductQuery(items=["a1"], num=4),
        SimilarProductQuery(items=["a0", "a2"], num=3),
        SimilarProductQuery(items=["z1"], num=4, categories=["zeta"]),
        SimilarProductQuery(items=["a1"], num=4, white_list=["a2", "a3"]),
        SimilarProductQuery(items=["a1"], num=4, black_list=["a2"]),
        SimilarProductQuery(items=["nope"], num=4),          # unresolvable
        SimilarProductQuery(items=["a1"], num=4, categories=["ghost"]),
    ]
    serial = [a.predict(model, q) for q in queries]
    batched = a.serve_batch_predict(model, queries)
    assert len(batched) == len(queries)
    for q, s, b in zip(queries, serial, batched):
        s_i = [(r.item, round(r.score, 4)) for r in s.item_scores]
        b_i = [(r.item, round(r.score, 4)) for r in b.item_scores]
        assert s_i == b_i, (q, s_i, b_i)
