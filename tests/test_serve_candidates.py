"""Candidate-pruned serving tests (PR 7): the pruned host tail must be
an EXACT twin of the dense tail — same items, same float scores, same
tie order — across every scorer × tail × batching × candidates cell,
including the adversarial shapes that break naive pruning: duplicate
score vectors (tie order), rules selecting entirely outside the
candidate set, empty-postings event types, blacklists covering the
popularity head, all-masked queries, num=0, and cold users (where the
pruned path falls back to dense).  Plus the new observability surface:
pio_ur_serve_candidate_{total,frac}, pio_ur_host_inverted_bytes, the
per-name parallel inverted builds, and the env resolution rules."""

import threading

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.models.universal_recommender import (
    UniversalRecommenderEngine,
    URQuery,
)
from predictionio_tpu.models.universal_recommender.engine import (
    URAlgorithm,
    URAlgorithmParams,
    URDataSourceParams,
    URModel,
    _M_CAND,
    _M_CAND_FRAC,
    _M_INV_BYTES,
    _serve_candidates,
)
from predictionio_tpu.storage import App
from predictionio_tpu.store.columnar import CSRLookup, IdDict


# -- fabricated models: full control over score/popularity pathologies ----


def make_model(n_items=400, k=8, seed=0, popularity=None, const_llr=False,
               blank_type=None):
    """A URModel built directly (the bench's fabrication pattern):
    random indicator tables with -1 padding over two event types sharing
    the primary item space; ``const_llr`` makes every weight 1.0 so LLR
    scoring degenerates into duplicate-heavy counts; ``blank_type``
    forces one type's table to all -1 (empty postings)."""
    rng = np.random.default_rng(seed)
    item_dict = IdDict([f"i{j}" for j in range(n_items)])
    user_dict = IdDict([f"u{j}" for j in range(20)])
    idx, llr, dicts = {}, {}, {}
    for name in ("ev0", "ev1"):
        tbl = rng.integers(0, n_items, (n_items, k)).astype(np.int32)
        tbl[:, -1] = -1
        if name == blank_type:
            tbl = np.full((n_items, k), -1, np.int32)
        idx[name] = tbl
        llr[name] = (np.ones((n_items, k), np.float32) if const_llr
                     else np.sort(rng.random((n_items, k)).astype(
                         np.float32) * 4, axis=1)[:, ::-1].copy())
        dicts[name] = item_dict
    if popularity is None:
        # few distinct values: the backfill order is mostly ties
        popularity = (np.round(rng.random(n_items).astype(np.float32) * 4)
                      / 2).astype(np.float32)
    props = {f"i{j}": {"category": f"c{j % 5}"}
             for j in range(0, n_items, 3)}
    return URModel(
        primary_event="ev0", item_dict=item_dict, user_dict=user_dict,
        indicator_idx=idx, indicator_llr=llr, event_item_dicts=dicts,
        popularity=np.asarray(popularity, np.float32),
        item_properties=props,
        user_seen=CSRLookup.from_pairs(
            np.zeros(0, np.int32), np.zeros(0, np.int32), len(user_dict)),
    )


def make_algo(**over):
    params = dict(app_name="candapp", mesh_dp=1)
    params.update(over)
    return URAlgorithm(URAlgorithmParams(**params))


def canon(result):
    return [(s.item, float(s.score)) for s in result.item_scores]


def hist_for(model, ids, types=("ev0", "ev1")):
    return {t: np.asarray(sorted(set(ids)), np.int32) for t in types}


def run_both(algo, model, query, hist, monkeypatch):
    """(pruned, dense) canon results for one query under the host paths."""
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "on")
    pruned = canon(algo.predict(model, query, hist_override=hist))
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    dense = canon(algo.predict(model, query, hist_override=hist))
    return pruned, dense


# -- trained-model corpus parity across every cell ------------------------


@pytest.fixture()
def rules_app(mem_storage):
    app_id = mem_storage.apps.insert(App(0, "candapp"))
    rng = np.random.default_rng(11)
    events = []
    e_items = [f"e{i}" for i in range(6)]
    b_items = [f"b{i}" for i in range(6)]
    for u in range(30):
        mine = e_items if u < 15 else b_items
        for it in mine:
            if rng.random() < 0.7:
                events.append(Event(
                    event="purchase", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
            if rng.random() < 0.9:
                events.append(Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=it))
    for n, it in enumerate(e_items):
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({
                "category": "electronics",
                "availableDate": "2026-01-01T00:00:00",
                "expireDate": f"2026-0{(n % 6) + 1}-15T00:00:00"})))
    for it in b_items:
        events.append(Event(
            event="$set", entity_type="item", entity_id=it,
            properties=DataMap({"category": "books",
                                "availableDate": "2026-02-01T00:00:00"})))
    mem_storage.l_events.insert_batch(events, app_id)
    return mem_storage


@pytest.fixture()
def trained(rules_app):
    engine = UniversalRecommenderEngine.apply()
    ep = EngineParams(
        data_source_params=URDataSourceParams(
            app_name="candapp", event_names=["purchase", "view"]),
        algorithm_params_list=[("ur", URAlgorithmParams(
            app_name="candapp", mesh_dp=1, max_correlators_per_item=8,
            min_llr=0.0, available_date_name="availableDate",
            expire_date_name="expireDate"))],
    )
    models = engine.train(ep)
    return engine, ep, models


def corpus():
    q = URQuery.from_json
    return [
        q({"user": "u2", "num": 6}),
        q({"user": "stranger-cold", "num": 5}),          # dense fallback
        q({"item": "e1", "num": 4}),
        q({"itemSet": ["e0", "e2"], "num": 5}),
        q({"user": "u3", "num": 6,
           "fields": [{"name": "category", "values": ["books"],
                       "bias": -1}]}),
        # boost + likely backfill shortfall: the reorder fallback
        q({"user": "u3", "num": 12,
           "fields": [{"name": "category", "values": ["electronics"],
                       "bias": 3.0}]}),
        q({"user": "u4", "num": 6, "blacklistItems": ["e0", "b0"]}),
        q({"user": "u5", "num": 6,
           "dateRange": {"name": "expireDate",
                         "after": "2026-02-01T00:00:00"}}),
        q({"user": "u6", "num": 8, "currentDate": "2026-03-01T00:00:00"}),
        q({"user": "u7", "num": 6,
           "fields": [{"name": "category", "values": ["no-such"],
                       "bias": -1}]}),                   # all-masked
        q({"user": "u20", "num": 0}),                    # num=0
        q({"user": "ghost", "num": 4,
           "fields": [{"name": "category", "values": ["books"],
                       "bias": -1}]}),                   # backfill-only
    ]


@pytest.mark.parametrize("tail", ["host", "device"])
@pytest.mark.parametrize("scorer", ["host", "device"])
def test_corpus_parity_candidates_cells(trained, monkeypatch, scorer, tail):
    """Within each scorer × tail cell: candidates on/auto/off × serial/
    batch answer identically (exact floats, exact order).  On device
    cells the resolver forces candidates off, so the assert doubles as
    a guard that the knob cannot leak into device serving."""
    engine, ep, models = trained
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", scorer)
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", tail)
    queries = corpus()
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    reference = [canon(algo.predict(model, q)) for q in queries]
    assert any(reference), "corpus produced only empty results"
    for cand in ("on", "auto"):
        monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", cand)
        serial = [canon(algo.predict(model, q)) for q in queries]
        batched = [canon(r) for r in algo.serve_batch_predict(model, queries)]
        for qi, (s_got, b_got, want) in enumerate(
                zip(serial, batched, reference)):
            assert s_got == want, (scorer, tail, cand, "serial", qi)
            assert b_got == want, (scorer, tail, cand, "batch", qi)
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    batched_off = [canon(r) for r in algo.serve_batch_predict(model, queries)]
    assert batched_off == reference


# -- adversarial fabricated shapes ----------------------------------------


def test_duplicate_score_ties_exact_order(monkeypatch):
    """Counts-mode scoring (use_llr_weights=False) yields integer scores
    — duplicate-heavy vectors are argpartition's pathological case AND
    the tie-order trap.  Pruned must reproduce the dense boundary ties
    bit-for-bit, deep into the list (num ~ half the candidate set)."""
    model = make_model(const_llr=True)
    algo = make_algo()
    hist = hist_for(model, range(0, 60))
    for num in (5, 40, 120):
        q = URQuery(user="u1", num=num)
        pruned, dense = run_both(algo, model, q, hist, monkeypatch)
        assert pruned == dense and len(pruned) == num


def test_duplicate_llr_weights_exact(monkeypatch):
    """use_llr_weights with constant weights: every posting contributes
    1.0 — weighted-bincount float sums must match the dense scatter."""
    model = make_model(const_llr=True)
    algo = make_algo(use_llr_weights=True)
    hist = hist_for(model, range(10, 50))
    q = URQuery(user="u1", num=30)
    pruned, dense = run_both(algo, model, q, hist, monkeypatch)
    assert pruned == dense


def test_constant_popularity_backfill_tie_order(monkeypatch):
    """All-equal popularity: the backfill merge's walk order is PURE tie
    order (id ascending) — any ordering bug shows immediately.  The
    tiny history forces a deep backfill pad."""
    model = make_model(popularity=np.full(400, 0.5, np.float32))
    algo = make_algo()
    hist = hist_for(model, [3], types=("ev0",))
    q = URQuery(user="u1", num=50)
    pruned, dense = run_both(algo, model, q, hist, monkeypatch)
    assert pruned == dense and len(pruned) == 50


def test_rules_selecting_outside_candidate_set(monkeypatch):
    """A hard filter whose items are DISJOINT from the candidate set:
    the signal masks to nothing and every result comes from backfill
    restricted to the rule's items — the pruned tail must find them via
    the popularity walk, never by inventing candidates."""
    model = make_model(n_items=300)
    algo = make_algo()
    # candidates drawn from postings of items 0..20; category c4 items
    # (j % 5 == 4 over the sampled j % 3 == 0 grid) are scattered wide
    hist = hist_for(model, range(0, 20))
    q = URQuery.from_json({
        "user": "u1", "num": 8,
        "fields": [{"name": "category", "values": ["c4"], "bias": -1}]})
    pruned, dense = run_both(algo, model, q, hist, monkeypatch)
    assert pruned == dense
    assert pruned, "filter should still backfill from matching items"


def test_boost_with_backfill_shortfall_falls_back(monkeypatch):
    """A value boost (non-binary mask) plus a backfill shortfall cannot
    merge from the popularity order — the pruned tail must fall back to
    dense (counted) and stay exact."""
    model = make_model(n_items=300)
    algo = make_algo()
    hist = hist_for(model, [1], types=("ev0",))
    q = URQuery.from_json({
        "user": "u1", "num": 40,
        "fields": [{"name": "category", "values": ["c1"], "bias": 2.5}]})
    before = _M_CAND.value(outcome="fallback_backfill_reorder")
    pruned, dense = run_both(algo, model, q, hist, monkeypatch)
    assert pruned == dense
    assert _M_CAND.value(outcome="fallback_backfill_reorder") > before


def test_rare_match_backfill_scan_budget_falls_back(monkeypatch):
    """A rule matching a thin slice of a big catalog would make the
    pruned backfill walk re-evaluate the sliced predicate over most of
    the popularity order on EVERY query (the pruned path never populates
    the mask cache) — past _BACKFILL_SCAN_BUDGET scanned ids it must
    fall back to dense (counted, and the dense pass caches the full
    mask) while staying exact."""
    model = make_model(n_items=3000)
    algo = make_algo()
    hist = hist_for(model, [1], types=("ev0",))
    monkeypatch.setattr(URAlgorithm, "_BACKFILL_SCAN_BUDGET", 8)
    q = URQuery.from_json({
        "user": "u1", "num": 40,
        "fields": [{"name": "category", "values": ["c1"], "bias": -1}]})
    before = _M_CAND.value(outcome="fallback_backfill_scan")
    pruned, dense = run_both(algo, model, q, hist, monkeypatch)
    assert pruned == dense and pruned
    assert _M_CAND.value(outcome="fallback_backfill_scan") > before


def test_empty_postings_event_type(monkeypatch):
    """An event type whose table is all -1 contributes no candidates but
    must not break the union; with EVERY type blank there are no
    candidates at all and the query falls back to dense (counted)."""
    one_blank = make_model(blank_type="ev1")
    algo = make_algo()
    hist = hist_for(one_blank, range(0, 30))
    q = URQuery(user="u1", num=10)
    pruned, dense = run_both(algo, one_blank, q, hist, monkeypatch)
    assert pruned == dense and pruned

    all_blank = make_model(blank_type="ev1")
    all_blank.indicator_idx["ev0"] = np.full_like(
        all_blank.indicator_idx["ev0"], -1)
    before = _M_CAND.value(outcome="fallback_no_candidates")
    pruned, dense = run_both(algo, all_blank, q, hist, monkeypatch)
    assert pruned == dense
    assert _M_CAND.value(outcome="fallback_no_candidates") > before


def test_blacklist_covering_popularity_head_and_candidates(monkeypatch):
    """Blacklist the whole popularity head (forces the merge to walk
    deep) AND every candidate (forces backfill-only assembly)."""
    model = make_model(n_items=300)
    algo = make_algo()
    hist = hist_for(model, [5], types=("ev0",))
    sparse = algo._score_history_host(model, hist)
    cand_items = [f"i{int(j)}" for j in sparse[0]]
    head = [f"i{int(j)}" for j in model.host_pop_order()[:80]]
    q = URQuery.from_json({"user": "u1", "num": 10,
                           "blacklistItems": sorted(set(cand_items + head))})
    pruned, dense = run_both(algo, model, q, hist, monkeypatch)
    assert pruned == dense and pruned


def test_all_masked_and_num0(monkeypatch):
    model = make_model()
    algo = make_algo()
    hist = hist_for(model, range(0, 10))
    q_masked = URQuery.from_json({
        "user": "u1", "num": 6,
        "fields": [{"name": "category", "values": ["nope"], "bias": -1}]})
    q_zero = URQuery(user="u1", num=0)
    for q in (q_masked, q_zero):
        pruned, dense = run_both(algo, model, q, hist, monkeypatch)
        assert pruned == dense == []


def test_candidate_metrics_observed(monkeypatch):
    """A pruned serve increments outcome=pruned and lands a candidate
    fraction observation bounded by the true candidate count."""
    model = make_model()
    algo = make_algo()
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "on")
    hist = hist_for(model, range(0, 8))
    sparse = algo._score_history_host(model, hist)
    frac = len(sparse[0]) / len(model.item_dict)
    _M_CAND_FRAC.clear_series()
    before = _M_CAND.value(outcome="pruned")
    algo.predict(model, URQuery(user="u1", num=5), hist_override=hist)
    assert _M_CAND.value(outcome="pruned") == before + 1
    snap = _M_CAND_FRAC._snapshot_series()
    assert snap and abs(next(iter(snap.values()))["sum"] - frac) < 1e-9


def test_sliced_mask_equals_full_mask_gather(trained, monkeypatch):
    """_mask_from_key_host_sliced(ids) ≡ _mask_from_key_host()[ids] for
    every rule shape in the corpus — the factor-by-factor exactness the
    pruned tail's parity rests on."""
    engine, ep, models = trained
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    rng = np.random.default_rng(5)
    ids = np.unique(rng.integers(0, len(model.item_dict), 30)).astype(
        np.int32)
    for q in corpus():
        key = algo._mask_rule_key(q)
        if key is None:
            continue
        full = algo._mask_from_key_host(model, *key)
        sliced = algo._mask_from_key_host_sliced(model, key, ids)
        np.testing.assert_array_equal(full[ids], sliced, err_msg=str(key))


def test_cached_full_mask_is_gathered(trained, monkeypatch):
    """When a dense query already composed and cached the full mask, the
    pruned path gathers from it instead of re-evaluating predicates."""
    engine, ep, models = trained
    algo = URAlgorithm(ep.algorithm_params_list[0][1])
    model = models[0]
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    q = URQuery.from_json({
        "user": "u2", "num": 5,
        "fields": [{"name": "category", "values": ["books"], "bias": -1}]})
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    dense = canon(algo.predict(model, q))       # populates the cache
    assert len(model.rule_mask_cache("host")) == 1
    calls = []
    orig = algo._mask_from_key_host_sliced
    monkeypatch.setattr(
        algo, "_mask_from_key_host_sliced",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw))
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "on")
    pruned = canon(algo.predict(model, q))
    assert pruned == dense
    assert calls == [], "cached full mask was not gathered"


# -- env resolution, gauges, parallel warm --------------------------------


def test_env_resolution(monkeypatch):
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.delenv("PIO_UR_SERVE_CANDIDATES", raising=False)
    assert _serve_candidates() == "on"          # auto on host/host
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "off")
    assert _serve_candidates() == "off"
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "on")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "device")
    assert _serve_candidates() == "off"         # no sparse set device-side
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "device")
    assert _serve_candidates() == "off"


def test_host_inverted_bytes_gauge(monkeypatch):
    model = make_model()
    model.host_inverted("ev0")
    indptr, rows, w = model.host_inverted("ev0")
    want = indptr.nbytes + rows.nbytes + w.nbytes
    assert _M_INV_BYTES.value(event="ev0") == want


def test_warm_propagates_parallel_build_failure(monkeypatch):
    """A builder thread's failure must fail warm() itself (deploy-time),
    not surface as a 500 on the first serving query for that type."""
    model = make_model()
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    model.indicator_idx["ev1"] = None   # unbuildable second type
    with pytest.raises(AttributeError):
        model.warm()


def test_warm_builds_all_types_in_parallel(monkeypatch):
    """warm() under the host scorer builds EVERY event type's inversion
    (concurrently — per-name locks) and, with candidates on, the
    popularity order; concurrent warms stay exactly-once per type."""
    model = make_model()
    monkeypatch.setenv("PIO_UR_SERVE_SCORER", "host")
    monkeypatch.setenv("PIO_UR_SERVE_TAIL", "host")
    monkeypatch.setenv("PIO_UR_SERVE_CANDIDATES", "on")
    results = []
    barrier = threading.Barrier(4)

    def warm():
        barrier.wait()
        model.warm()
        results.append({n: model.host_inverted(n)[0]
                        for n in model.indicator_idx})

    threads = [threading.Thread(target=warm) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 4
    for name in model.indicator_idx:
        assert all(r[name] is results[0][name] for r in results), \
            f"{name} built more than once"
    assert "_host_pop_order" in model.__dict__
    order = model.host_pop_order()
    assert sorted(order.tolist()) == list(range(len(model.item_dict)))
