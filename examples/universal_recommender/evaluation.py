"""Example `pio eval` setup for the Universal Recommender: leave-one-out
hit@10 with a minLLR grid supplied by an EngineParamsGenerator.

    pio eval examples.universal_recommender.evaluation.UREvaluation \
             examples.universal_recommender.evaluation.MinLlrGrid
"""

from predictionio_tpu.controller import EngineParams, Evaluation
from predictionio_tpu.controller.evaluation import EngineParamsGenerator, params_grid
from predictionio_tpu.models.universal_recommender import UniversalRecommenderEngine
from predictionio_tpu.models.universal_recommender.engine import (
    HitRateMetric,
    MRRMetric,
    NDCGMetric,
    PrecisionAtKMetric,
    URAlgorithmParams,
    URDataSourceParams,
)

_BASE = EngineParams(
    data_source_params=URDataSourceParams(
        app_name="MyShop", event_names=["purchase", "view"],
        eval_users=500, eval_num=10),
    algorithm_params_list=[("ur", URAlgorithmParams(app_name="MyShop"))],
)


class UREvaluation(Evaluation):
    engine = UniversalRecommenderEngine.apply()
    metric = HitRateMetric()
    # side metrics reported per candidate alongside the selection metric
    other_metrics = (NDCGMetric(), PrecisionAtKMetric(10), MRRMetric())


class MinLlrGrid(EngineParamsGenerator):
    engine_params_list = params_grid(_BASE, "ur", {"min_llr": [0.0, 2.0, 5.0]})
