"""Example evaluation for `pio eval` (reference analogue: a template's
Evaluation.scala): precision@10 over a 3-fold split, tuning ALS rank."""

from predictionio_tpu.controller import EngineParams, Evaluation, OptionAverageMetric
from predictionio_tpu.models.recommendation import RecommendationEngine
from predictionio_tpu.models.recommendation.engine import (
    ALSAlgorithmParams,
    DataSourceParams,
)


class PrecisionAt10(OptionAverageMetric):
    def score_one(self, q, p, a):
        actual_item, rating = a
        if rating < 4.0:
            return None
        return 1.0 if actual_item in [s.item for s in p.item_scores] else 0.0


class RecommendationEvaluation(Evaluation):
    engine = RecommendationEngine.apply()
    metric = PrecisionAt10()
    engine_params_list = [
        EngineParams(
            data_source_params=DataSourceParams(app_name="MyApp", eval_k=3),
            algorithm_params_list=[("als", ALSAlgorithmParams(rank=r, num_iterations=6, mesh_dp=1))],
        )
        for r in (4, 8)
    ]
